"""Single-token decode attention over a resident KV cache — Pallas TPU
kernel plus a pure-JAX fallback with identical math.

The autoregressive hot path: one new query per sequence attends over that
sequence's cached keys/values. There is no O(T^2) score matrix here — per
(batch, head) the work is a [1, D] x [D, S] matvec — so the op is purely
HBM-bandwidth-bound (arithmetic intensity ~1 flop/byte). What the kernel
buys over the XLA fallback is the same thing flash_attention buys the
training path: the masked scores, softmax statistics and weighted sum all
live in VMEM while K/V blocks stream through, so the [B, H, S] score
tensor is never written to HBM and the per-position mask costs no extra
pass.

Structure mirrors `ops/flash_attention.py`: grid (B*H, S/block_kv) with
the kv dimension innermost/sequential, per-row running (m, l, acc)
softmax statistics in VMEM scratch, finalize on the last kv block. Two
decode-specific twists:

- **position masking**: each sequence attends to cache positions
  ``<= pos[b]`` (its current token's position — the caller writes the new
  K/V at ``pos`` *before* attending). ``pos`` rides in as a per-row
  [BH, 128] i32 tile (the fused_xent `_rows128` idiom).
- **data-dependent block skip**: kv blocks strictly past ``pos`` are
  predicated away with ``pl.when(k_start <= pos)`` — a *runtime* branch,
  unlike flash's static causal predicate — so short sequences in a long
  preallocated cache don't pay for the empty tail.

Layout: the public cache layout is ``[B, S, H, D]`` (matching
`models.gpt.init_kv_cache`'s ``[L, B, S, H, D]``); the kernel wants
(S, D) as the trailing tile per (b, h), so the wrapper transposes K/V to
``[B*H, S, D]`` on entry. The fallback consumes ``[B, S, H, D]``
directly.

**Paged variant** (`paged_decode_attention`): K/V live in a shared block
pool ``[n_blocks, block_size, H, D]`` and each sequence names its blocks
through an int32 block table ``[B, max_blocks]`` (logical block j of
sequence b is physical block ``tables[b, j]``). The Pallas kernel rides
the same online-softmax structure with the kv grid dimension walking
*logical* blocks; the block table and positions arrive as scalar
prefetch (`pltpu.PrefetchScalarGridSpec`), so the K/V BlockSpec index
maps dereference the table and the DMA engine fetches exactly the
blocks the sequence owns — the pool is never materialized per sequence.
The JAX fallback gathers ``pool[tables]`` and reuses
`reference_decode_attention`; both paths mask logical positions
``> pos[b]``, so stale data in partially-filled tail blocks never
contributes.

**Int8 pools** (`ops/quant.py`): every paged op takes optional
``k_scale`` / ``v_scale`` arrays ``[n_blocks, bs, H]`` f32 — one scale
per (position, head) row of an int8 pool. Dequantization happens
*inside* the kernels (the scale tile rides the same table-dereferenced
DMA schedule as its payload block) and inside the fallbacks (gathered
through the same `gather_kv_pages`), so HBM reads stay int8 and the
block-table machinery above never sees the dtype. Scales absent ==
full-precision pool, bit-for-bit the pre-quantization math.

**Fused paged prefill** (`paged_prefill_attention`): chunked-prefill
attention for one sequence over the same paged pool — the dense-math
JAX path is exactly the gather+einsum that used to live inline in
`models.gpt.prefill_paged`, and the Pallas path reuses the multi-query
verify kernel (the prefill staircase ``col <= start + row`` IS the
verify mask with ``pos = start``), so the [C, S] score matrix stays in
VMEM instead of round-tripping through HBM.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_tpu.ops.flash_attention import (
    _CompilerParams,
    _head_pad_target,
    _pad_heads,
    _pick_block,
)

NEG_INF = -1e30


# ---------------------------------------------------------------------------
# pure-JAX fallback (the everywhere-correct path; CPU/CI default)
# ---------------------------------------------------------------------------

def reference_decode_attention(q, k, v, pos):
    """q [B, H, D]; k, v [B, S, H, D]; pos [B] i32. Attends to cache
    positions <= pos[b] and returns [B, H, D] in q.dtype. Accumulation is
    f32 regardless of input dtype (same contract as the kernel)."""
    b, s, h, d = k.shape
    scores = jnp.einsum("bhd,bshd->bhs", q.astype(jnp.float32),
                        k.astype(jnp.float32),
                        preferred_element_type=jnp.float32) * (d ** -0.5)
    live = jnp.arange(s, dtype=jnp.int32)[None, None, :] <= \
        pos.astype(jnp.int32)[:, None, None]
    scores = jnp.where(live, scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhs,bshd->bhd", p, v.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# pallas kernel
# ---------------------------------------------------------------------------

def _decode_kernel(q_ref, k_ref, v_ref, pos_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, sm_scale: float,
                   block_kv: int):
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    pos = pos_ref[0, 0]
    k_start = ki * block_kv

    # Runtime predicate: blocks wholly past this row's position contribute
    # nothing — skip them (pos is data, so this is a dynamic branch, not
    # flash's static causal one).
    @pl.when(k_start <= pos)
    def _body():
        q = q_ref[0].astype(jnp.float32)            # [1, D]
        k = k_ref[0].astype(jnp.float32)            # [bkv, D]
        s = jax.lax.dot_general(
            q * sm_scale, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)     # [1, bkv]
        col = k_start + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(col <= pos, s, NEG_INF)
        m_prev = m_scr[:1, :1]                      # [1, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                      # [1, bkv]
        l_scr[:1, :1] = l_scr[:1, :1] * corr + jnp.sum(
            p, axis=1, keepdims=True)
        m_scr[:1, :1] = m_new
        v = v_ref[0]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # [1, D]
        acc_scr[:1] = acc_scr[:1] * corr + pv

    # Finalize unconditionally at the last block: the last kv block may
    # itself be dead (pos early in the cache), but the output write must
    # still happen (flash's _finalize structure).
    @pl.when(ki == pl.num_programs(1) - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:1] / l_scr[:1, :1]).astype(o_ref.dtype)


def _decode_bhsd(q, k, v, pos, *, sm_scale: float, block_kv: int,
                 interpret: bool):
    """q [BH, 1, D]; k, v [BH, S, D]; pos [BH, 128] i32 -> [BH, 1, D]."""
    bh, s, d = k.shape
    grid = (bh, s // block_kv)
    return pl.pallas_call(
        functools.partial(_decode_kernel, sm_scale=sm_scale,
                          block_kv=block_kv),
        out_shape=jax.ShapeDtypeStruct((bh, 1, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_kv, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_kv, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, 128), lambda b, j: (b, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, d), lambda b, j: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((8, 128), jnp.float32),    # m (cell [0, 0] used)
            pltpu.VMEM((8, 128), jnp.float32),    # l
            pltpu.VMEM((8, d), jnp.float32),      # acc (row 0 used)
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, pos)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def decode_attention(q, k, v, pos, *, impl: str = "auto",
                     block_kv: int = 512):
    """Decode-step attention: ``q [B, H, D]`` against a KV cache
    ``k, v [B, S, H, D]``, attending to positions ``<= pos[b]``
    (``pos [B]`` i32, the position of the token q was computed from).
    Returns ``[B, H, D]`` in q.dtype.

    impl: "auto" (pallas on TPU-friendly shapes, else jax) | "pallas" |
    "jax". The two paths share the same masking/accumulation math and
    agree to f32 tolerance."""
    if q.ndim != 3 or k.ndim != 4:
        raise ValueError(
            f"decode_attention wants q [B, H, D] and k/v [B, S, H, D]; "
            f"got {q.shape} and {k.shape}")
    b, s, h, d = k.shape
    bkv = _pick_block(s, block_kv)
    if impl == "auto":
        impl = "pallas" if (jax.default_backend() == "tpu"
                            and bkv is not None) else "jax"
    if impl == "jax":
        return reference_decode_attention(q, k, v, pos)
    if impl != "pallas":
        raise ValueError(
            f"unknown decode_attention impl {impl!r} "
            "(expected 'auto' | 'pallas' | 'jax')")
    if bkv is None:
        raise ValueError(
            f"cache length {s} has no pallas block plan; use impl='jax'")
    interpret = jax.default_backend() != "tpu"
    d_pad = _head_pad_target(d)
    # [B, S, H, D] -> [B*H, S, D]: (S, D) become the trailing tile per
    # row. On TPU this is one cache-sized transpose per call — the price
    # of keeping the public cache layout sequence-major; a head-major
    # resident cache is the follow-up that removes it.
    kt = _pad_heads(k, d_pad).transpose(0, 2, 1, 3).reshape(b * h, s, d_pad)
    vt = _pad_heads(v, d_pad).transpose(0, 2, 1, 3).reshape(b * h, s, d_pad)
    qt = _pad_heads(q, d_pad).reshape(b * h, 1, d_pad)
    pos_rows = jnp.broadcast_to(
        pos.astype(jnp.int32).reshape(b, 1, 1), (b, h, 128)
    ).reshape(b * h, 128)
    out = _decode_bhsd(qt, kt, vt, pos_rows, sm_scale=d ** -0.5,
                       block_kv=bkv, interpret=interpret)
    return out.reshape(b, h, d_pad)[..., :d]


# ---------------------------------------------------------------------------
# paged variant: K/V behind a block table
# ---------------------------------------------------------------------------

def gather_kv_pages(pool, tables):
    """Materialize per-sequence K or V from a block pool:
    ``pool [n_blocks, bs, H, D]`` gathered through ``tables
    [B, max_blocks]`` -> ``[B, max_blocks * bs, H, D]`` where row b's
    logical position ``p`` lives at ``(tables[b, p // bs], p % bs)``.
    The JAX fallback path and the chunked-prefill context read share
    this one gather."""
    b, mb = tables.shape
    nb, bs = pool.shape[0], pool.shape[1]
    flat = pool.reshape((nb * bs,) + pool.shape[2:])
    idx = (tables.astype(jnp.int32)[:, :, None] * bs
           + jnp.arange(bs, dtype=jnp.int32)[None, None, :]).reshape(
        b, mb * bs)
    return flat[idx]


def _gather_dequant(pool, scale, tables):
    """Gather a (possibly int8) pool through block tables; with a
    per-row ``scale [n_blocks, bs, H]`` the gathered sequence is
    dequantized to f32 (`ops.quant` row convention), otherwise it is
    returned untouched — the full-precision path stays bit-identical."""
    seq = gather_kv_pages(pool, tables)
    if scale is None:
        return seq
    return seq.astype(jnp.float32) * \
        gather_kv_pages(scale, tables).astype(jnp.float32)[..., None]


def reference_paged_decode_attention(q, k_pool, v_pool, tables, pos, *,
                                     k_scale=None, v_scale=None):
    """q [B, H, D]; k_pool, v_pool [n_blocks, bs, H, D]; tables
    [B, max_blocks] i32; pos [B] i32. Gather-then-attend fallback with
    the exact masking/accumulation math of the paged kernel. With
    ``k_scale`` / ``v_scale`` [n_blocks, bs, H] f32 the pools are int8
    and dequantized after the gather (same math the kernel applies
    in VMEM)."""
    k_seq = _gather_dequant(k_pool, k_scale, tables)
    v_seq = _gather_dequant(v_pool, v_scale, tables)
    return reference_decode_attention(q, k_seq, v_seq, pos)


def _paged_kernel(tbl_ref, pos_ref, q_ref, k_ref, v_ref, *rest,
                  sm_scale: float, block_size: int, n_heads: int,
                  quantized: bool):
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        ks_ref = vs_ref = None
        o_ref, m_scr, l_scr, acc_scr = rest
    ji = pl.program_id(1)

    @pl.when(ji == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    pos = pos_ref[pl.program_id(0) // n_heads]
    k_start = ji * block_size     # LOGICAL position of this kv block --
    # the BlockSpec index maps already dereferenced tbl_ref, so k_ref
    # holds the right physical block; masking stays in logical space.

    @pl.when(k_start <= pos)
    def _body():
        q = q_ref[0].astype(jnp.float32)            # [1, D]
        k = k_ref[0, 0].astype(jnp.float32)         # [bs, D]
        if quantized:
            # Per-row dequant in VMEM: the int8 payload and its f32
            # scale column rode the same table-dereferenced DMA.
            k = k * ks_ref[0, 0].astype(jnp.float32)[:, None]
        s = jax.lax.dot_general(
            q * sm_scale, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)     # [1, bs]
        col = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(col <= pos, s, NEG_INF)
        m_prev = m_scr[:1, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_scr[:1, :1] = l_scr[:1, :1] * corr + jnp.sum(
            p, axis=1, keepdims=True)
        m_scr[:1, :1] = m_new
        v = v_ref[0, 0]
        if quantized:
            v = v.astype(jnp.float32) * \
                vs_ref[0, 0].astype(jnp.float32)[:, None]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # [1, D]
        acc_scr[:1] = acc_scr[:1] * corr + pv

    @pl.when(ji == pl.num_programs(1) - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:1] / l_scr[:1, :1]).astype(o_ref.dtype)


def _paged_bhsd(q, k, v, tables, pos, *, sm_scale: float, n_heads: int,
                interpret: bool, ks=None, vs=None):
    """q [BH, 1, D]; k, v [n_blocks, H, bs, D] head-major pool; tables
    [B, max_blocks]; pos [B] i32 -> [BH, 1, D]. Grid walks (row, logical
    block); the physical block index comes out of the scalar-prefetched
    table inside the BlockSpec index maps — paging lives entirely in the
    DMA schedule, the kernel body is the stock online softmax. With
    ``ks``/``vs`` [n_blocks, H, bs] (head-major per-row scales) the
    pools are int8 and dequantized in VMEM."""
    bh, _, d = q.shape
    mb = tables.shape[1]
    bs = k.shape[2]
    grid = (bh, mb)
    h = n_heads
    quantized = ks is not None

    pool_spec = pl.BlockSpec((1, 1, bs, d),
                             lambda i, j, tbl, ps: (tbl[i // h, j],
                                                    i % h, 0, 0))
    in_specs = [
        pl.BlockSpec((1, 1, d), lambda i, j, tbl, ps: (i, 0, 0)),
        pool_spec,
        pool_spec,
    ]
    operands = [tables, pos, q, k, v]
    if quantized:
        # The scale column rides the same table-dereferenced schedule as
        # its payload block, one [bs] row per (block, head).
        scale_spec = pl.BlockSpec((1, 1, bs),
                                  lambda i, j, tbl, ps: (tbl[i // h, j],
                                                         i % h, 0))
        in_specs += [scale_spec, scale_spec]
        operands += [ks, vs]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, d), lambda i, j, tbl, ps: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((8, 128), jnp.float32),    # m (cell [0, 0] used)
            pltpu.VMEM((8, 128), jnp.float32),    # l
            pltpu.VMEM((8, d), jnp.float32),      # acc (row 0 used)
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_kernel, sm_scale=sm_scale,
                          block_size=bs, n_heads=n_heads,
                          quantized=quantized),
        out_shape=jax.ShapeDtypeStruct((bh, 1, d), q.dtype),
        grid_spec=grid_spec,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(*operands)


def reference_paged_verify_attention(q, k_pool, v_pool, tables, pos, *,
                                     k_scale=None, v_scale=None):
    """Multi-query verify attention, gather-then-attend fallback.

    q [B, W, H, D]: W query tokens per sequence, token i of row b sits at
    logical position ``pos[b] + i`` and attends to cache positions
    ``<= pos[b] + i`` (the caller writes all W tokens' K/V *before*
    attending, so draft token i sees drafts 0..i-1 — in-cache causal).
    k_pool, v_pool [n_blocks, bs, H, D]; tables [B, max_blocks] i32;
    pos [B] i32. Returns [B, W, H, D] in q.dtype. ``k_scale``/``v_scale``
    [n_blocks, bs, H] f32 mark int8 pools (dequantized after the
    gather)."""
    k_seq = _gather_dequant(k_pool, k_scale, tables)
    v_seq = _gather_dequant(v_pool, v_scale, tables)
    b, s, h, d = k_seq.shape
    w = q.shape[1]
    scores = jnp.einsum("bwhd,bshd->bhws", q.astype(jnp.float32),
                        k_seq.astype(jnp.float32),
                        preferred_element_type=jnp.float32) * (d ** -0.5)
    limit = pos.astype(jnp.int32)[:, None] + jnp.arange(w, dtype=jnp.int32)
    live = jnp.arange(s, dtype=jnp.int32)[None, None, :] <= \
        limit[:, :, None]                                # [B, W, S]
    scores = jnp.where(live[:, None], scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhws,bshd->bwhd", p, v_seq.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def _paged_mq_kernel(tbl_ref, pos_ref, q_ref, k_ref, v_ref, *rest,
                     sm_scale: float, block_size: int, n_heads: int,
                     w_real: int, quantized: bool):
    """`_paged_kernel` generalized to W query rows per (b, h): the online
    softmax statistics become per-row vectors, the mask becomes the
    staircase ``col <= pos + row``, and the runtime block skip widens to
    the LAST query row's horizon (``pos + w_real - 1``)."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        ks_ref = vs_ref = None
        o_ref, m_scr, l_scr, acc_scr = rest
    ji = pl.program_id(1)

    @pl.when(ji == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    pos = pos_ref[pl.program_id(0) // n_heads]
    k_start = ji * block_size

    @pl.when(k_start <= pos + w_real - 1)
    def _body():
        q = q_ref[0].astype(jnp.float32)            # [Wp, D]
        k = k_ref[0, 0].astype(jnp.float32)         # [bs, D]
        if quantized:
            k = k * ks_ref[0, 0].astype(jnp.float32)[:, None]
        s = jax.lax.dot_general(
            q * sm_scale, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)     # [Wp, bs]
        col = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        # Padded q rows (>= w_real) reuse the last real row's mask so
        # they keep >= 1 live column (l stays nonzero); their output is
        # sliced away by the wrapper.
        row = jnp.minimum(
            jax.lax.broadcasted_iota(jnp.int32, s.shape, 0), w_real - 1)
        s = jnp.where(col <= pos + row, s, NEG_INF)
        m_prev = m_scr[:, :1]                       # [Wp, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                      # [Wp, bs]
        l_scr[:, :1] = l_scr[:, :1] * corr + jnp.sum(
            p, axis=1, keepdims=True)
        m_scr[:, :1] = m_new
        v = v_ref[0, 0]
        if quantized:
            v = v.astype(jnp.float32) * \
                vs_ref[0, 0].astype(jnp.float32)[:, None]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # [Wp, D]
        acc_scr[:] = acc_scr[:] * corr + pv

    @pl.when(ji == pl.num_programs(1) - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] / l_scr[:, :1]).astype(o_ref.dtype)


def _paged_mq_bhsd(q, k, v, tables, pos, *, sm_scale: float,
                   n_heads: int, w_real: int, interpret: bool,
                   ks=None, vs=None):
    """q [BH, Wp, D] (Wp = W padded to a sublane multiple); k, v
    [n_blocks, H, bs, D] head-major pool; tables [B, max_blocks]; pos
    [B] i32 -> [BH, Wp, D]. Same DMA schedule as `_paged_bhsd` — only
    the q/o tile grows from one row to Wp. ``ks``/``vs``
    [n_blocks, H, bs] mark int8 pools (dequantized in VMEM)."""
    bh, wp, d = q.shape
    mb = tables.shape[1]
    bs = k.shape[2]
    h = n_heads
    quantized = ks is not None

    pool_spec = pl.BlockSpec((1, 1, bs, d),
                             lambda i, j, tbl, ps: (tbl[i // h, j],
                                                    i % h, 0, 0))
    in_specs = [
        pl.BlockSpec((1, wp, d), lambda i, j, tbl, ps: (i, 0, 0)),
        pool_spec,
        pool_spec,
    ]
    operands = [tables, pos, q, k, v]
    if quantized:
        scale_spec = pl.BlockSpec((1, 1, bs),
                                  lambda i, j, tbl, ps: (tbl[i // h, j],
                                                         i % h, 0))
        in_specs += [scale_spec, scale_spec]
        operands += [ks, vs]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, mb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, wp, d),
                               lambda i, j, tbl, ps: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((wp, 128), jnp.float32),   # m (col 0 used)
            pltpu.VMEM((wp, 128), jnp.float32),   # l
            pltpu.VMEM((wp, d), jnp.float32),     # acc
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_mq_kernel, sm_scale=sm_scale,
                          block_size=bs, n_heads=n_heads, w_real=w_real,
                          quantized=quantized),
        out_shape=jax.ShapeDtypeStruct((bh, wp, d), q.dtype),
        grid_spec=grid_spec,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(*operands)


def _check_scales(k_scale, v_scale, k_pool, op: str):
    """Both-or-neither scale validation shared by the paged wrappers;
    returns True when the pool is quantized."""
    if (k_scale is None) != (v_scale is None):
        raise ValueError(
            f"{op} wants both k_scale and v_scale or neither; got "
            f"k_scale={'set' if k_scale is not None else None}, "
            f"v_scale={'set' if v_scale is not None else None}")
    if k_scale is None:
        return False
    if k_scale.shape != k_pool.shape[:3]:
        raise ValueError(
            f"{op} scale shape {k_scale.shape} != pool row shape "
            f"{k_pool.shape[:3]} ([n_blocks, bs, H])")
    return True


def paged_verify_attention(q, k_pool, v_pool, tables, pos, *,
                           k_scale=None, v_scale=None,
                           impl: str = "auto"):
    """Masked multi-query attention through the paged cache — the verify
    half of speculative decoding. ``q [B, W, H, D]`` holds W query tokens
    per sequence (current token + W-1 speculated continuations); token i
    of row b sits at logical position ``pos[b] + i`` and attends to cache
    positions ``<= pos[b] + i``. Pools/tables as in
    `paged_decode_attention`, including the int8 ``k_scale``/``v_scale``
    contract. Returns ``[B, W, H, D]`` in q.dtype.

    impl: "auto" (pallas on TPU-friendly shapes, else jax) | "pallas" |
    "jax"; the paths share masking/accumulation math."""
    if q.ndim != 4 or k_pool.ndim != 4 or tables.ndim != 2:
        raise ValueError(
            "paged_verify_attention wants q [B, W, H, D], pools "
            f"[n_blocks, bs, H, D] and tables [B, max_blocks]; got "
            f"{q.shape}, {k_pool.shape}, {tables.shape}")
    quantized = _check_scales(k_scale, v_scale, k_pool,
                              "paged_verify_attention")
    b, w, h, d = q.shape
    bs = k_pool.shape[1]
    if impl == "auto":
        impl = "pallas" if (jax.default_backend() == "tpu"
                            and bs % 8 == 0) else "jax"
    if impl == "jax":
        return reference_paged_verify_attention(
            q, k_pool, v_pool, tables, pos,
            k_scale=k_scale, v_scale=v_scale)
    if impl != "pallas":
        raise ValueError(
            f"unknown paged_verify_attention impl {impl!r} "
            "(expected 'auto' | 'pallas' | 'jax')")
    if bs % 8 != 0:
        raise ValueError(
            f"block_size {bs} is not a multiple of 8; use impl='jax'")
    interpret = jax.default_backend() != "tpu"
    d_pad = _head_pad_target(d)
    wp = max(8, ((w + 7) // 8) * 8)
    kt = _pad_heads(k_pool, d_pad).transpose(0, 2, 1, 3)
    vt = _pad_heads(v_pool, d_pad).transpose(0, 2, 1, 3)
    qt = _pad_heads(q, d_pad).transpose(0, 2, 1, 3).reshape(
        b * h, w, d_pad)
    qt = jnp.pad(qt, ((0, 0), (0, wp - w), (0, 0)))
    ks = vs = None
    if quantized:
        ks = k_scale.transpose(0, 2, 1)      # head-major [nb, H, bs]
        vs = v_scale.transpose(0, 2, 1)
    out = _paged_mq_bhsd(qt, kt, vt, tables.astype(jnp.int32),
                         pos.astype(jnp.int32), sm_scale=d ** -0.5,
                         n_heads=h, w_real=w, interpret=interpret,
                         ks=ks, vs=vs)
    return out.reshape(b, h, wp, d_pad)[:, :, :w, :d].transpose(
        0, 2, 1, 3)


def paged_decode_attention(q, k_pool, v_pool, tables, pos, *,
                           k_scale=None, v_scale=None,
                           impl: str = "auto"):
    """Decode-step attention through a paged KV cache: ``q [B, H, D]``
    against a block pool ``k_pool, v_pool [n_blocks, block_size, H, D]``
    indexed by ``tables [B, max_blocks]`` i32 (logical block j of row b
    is physical block ``tables[b, j]``; entries past the allocated
    length may be any valid block — they are masked). Attends to logical
    positions ``<= pos[b]`` and returns ``[B, H, D]`` in q.dtype.

    With ``k_scale``/``v_scale`` ``[n_blocks, bs, H]`` f32 the pools
    hold int8 payloads (`ops.quant.quantize_rows` convention, one scale
    per position-head row); both impls dequantize at read — in VMEM for
    pallas, post-gather for jax — so HBM traffic stays int8.

    impl: "auto" (pallas on TPU-friendly shapes, else jax) | "pallas" |
    "jax". Paths share masking/accumulation math exactly like
    `decode_attention`."""
    if q.ndim != 3 or k_pool.ndim != 4 or tables.ndim != 2:
        raise ValueError(
            "paged_decode_attention wants q [B, H, D], pools "
            f"[n_blocks, bs, H, D] and tables [B, max_blocks]; got "
            f"{q.shape}, {k_pool.shape}, {tables.shape}")
    quantized = _check_scales(k_scale, v_scale, k_pool,
                              "paged_decode_attention")
    b, h, d = q.shape
    bs = k_pool.shape[1]
    if impl == "auto":
        impl = "pallas" if (jax.default_backend() == "tpu"
                            and bs % 8 == 0) else "jax"
    if impl == "jax":
        return reference_paged_decode_attention(
            q, k_pool, v_pool, tables, pos,
            k_scale=k_scale, v_scale=v_scale)
    if impl != "pallas":
        raise ValueError(
            f"unknown paged_decode_attention impl {impl!r} "
            "(expected 'auto' | 'pallas' | 'jax')")
    if bs % 8 != 0:
        raise ValueError(
            f"block_size {bs} is not a multiple of 8; use impl='jax'")
    interpret = jax.default_backend() != "tpu"
    d_pad = _head_pad_target(d)
    # [n_blocks, bs, H, D] -> head-major [n_blocks, H, bs, D]: the
    # kernel's per-(row, block) tile is (bs, D) for one head.
    kt = _pad_heads(k_pool, d_pad).transpose(0, 2, 1, 3)
    vt = _pad_heads(v_pool, d_pad).transpose(0, 2, 1, 3)
    qt = _pad_heads(q, d_pad).reshape(b * h, 1, d_pad)
    ks = vs = None
    if quantized:
        ks = k_scale.transpose(0, 2, 1)
        vs = v_scale.transpose(0, 2, 1)
    out = _paged_bhsd(qt, kt, vt, tables.astype(jnp.int32),
                      pos.astype(jnp.int32), sm_scale=d ** -0.5,
                      n_heads=h, interpret=interpret, ks=ks, vs=vs)
    return out.reshape(b, h, d_pad)[..., :d]


# ---------------------------------------------------------------------------
# fused paged prefill: chunked-prefill attention over the pool
# ---------------------------------------------------------------------------

def reference_paged_prefill_attention(q, k_pool, v_pool, table, start, *,
                                      k_scale=None, v_scale=None):
    """Dense-math chunked-prefill attention for ONE sequence — exactly
    the gather+einsum that lived inline in `models.gpt.prefill_paged`
    (bit-for-bit on full-precision pools), factored out so the fused
    kernel has a reference to agree with.

    q [C, H, D]: the chunk's queries, token t at absolute position
    ``start + t``; the caller has already scattered the chunk's K/V into
    the pool, so token t attends to gathered positions ``<= start + t``
    (whole-prefix causal). k_pool, v_pool [n_blocks, bs, H, D]; table
    [max_blocks] i32; start scalar i32. Returns [C, H, D] in q.dtype.
    ``k_scale``/``v_scale`` [n_blocks, bs, H] mark int8 pools."""
    c, h, d = q.shape
    nb, bs = k_pool.shape[0], k_pool.shape[1]
    table = table.astype(jnp.int32)
    kctx = _gather_dequant(k_pool, k_scale, table[None])[0]
    vctx = _gather_dequant(v_pool, v_scale, table[None])[0]
    positions = jnp.asarray(start, jnp.int32) + \
        jnp.arange(c, dtype=jnp.int32)
    scores = jnp.einsum(
        "thd,shd->hts", q.astype(jnp.float32), kctx.astype(jnp.float32),
        preferred_element_type=jnp.float32) * (d ** -0.5)
    cols = jnp.arange(kctx.shape[0], dtype=jnp.int32)
    live = cols[None, None, :] <= positions[None, :, None]
    scores = jnp.where(live, scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    att = jnp.einsum("hts,shd->thd", p, vctx.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return att.astype(q.dtype)


def paged_prefill_attention(q, k_pool, v_pool, table, start, *,
                            k_scale=None, v_scale=None,
                            impl: str = "auto"):
    """Chunked-prefill attention for one sequence through the paged
    pool: ``q [C, H, D]`` (chunk token t at absolute position
    ``start + t``) attends over the sequence's whole gathered prefix —
    the caller scatters the chunk's K/V into the pool FIRST, exactly as
    `models.gpt.prefill_paged` always has.

    The pallas path reuses the multi-query verify kernel: the prefill
    staircase (token t sees positions ``<= start + t``) is the verify
    mask with ``pos = start`` and ``W = C``, so the [C, S] score matrix
    lives blockwise in VMEM instead of round-tripping through HBM, and
    the runtime block skip prunes pool blocks past ``start + C - 1``.
    The jax path is the legacy dense gather+einsum
    (`reference_paged_prefill_attention`) — bit-identical to the
    pre-fused inline math, which keeps ``impl="jax"`` the bitwise
    default on CPU. ``k_scale``/``v_scale`` [n_blocks, bs, H] mark int8
    pools, dequantized at read on both paths.

    impl: "auto" (pallas on TPU-friendly shapes, else jax) | "pallas" |
    "jax". Returns ``[C, H, D]`` in q.dtype."""
    if q.ndim != 3 or k_pool.ndim != 4 or table.ndim != 1:
        raise ValueError(
            "paged_prefill_attention wants q [C, H, D], pools "
            f"[n_blocks, bs, H, D] and table [max_blocks]; got "
            f"{q.shape}, {k_pool.shape}, {table.shape}")
    quantized = _check_scales(k_scale, v_scale, k_pool,
                              "paged_prefill_attention")
    c, h, d = q.shape
    bs = k_pool.shape[1]
    if impl == "auto":
        impl = "pallas" if (jax.default_backend() == "tpu"
                            and bs % 8 == 0) else "jax"
    if impl == "jax":
        return reference_paged_prefill_attention(
            q, k_pool, v_pool, table, start,
            k_scale=k_scale, v_scale=v_scale)
    if impl != "pallas":
        raise ValueError(
            f"unknown paged_prefill_attention impl {impl!r} "
            "(expected 'auto' | 'pallas' | 'jax')")
    if bs % 8 != 0:
        raise ValueError(
            f"block_size {bs} is not a multiple of 8; use impl='jax'")
    interpret = jax.default_backend() != "tpu"
    d_pad = _head_pad_target(d)
    wp = max(8, ((c + 7) // 8) * 8)
    kt = _pad_heads(k_pool, d_pad).transpose(0, 2, 1, 3)
    vt = _pad_heads(v_pool, d_pad).transpose(0, 2, 1, 3)
    # One sequence == one batch row of the mq kernel: B=1, W=C,
    # pos=start. Padded q rows (>= C) compute a discarded garbage row —
    # the same thing the dense path's padded chunk tail does.
    qt = q.transpose(1, 0, 2)                      # [H, C, D]
    qt = _pad_heads(qt, d_pad)
    qt = jnp.pad(qt, ((0, 0), (0, wp - c), (0, 0)))
    ks = vs = None
    if quantized:
        ks = k_scale.transpose(0, 2, 1)
        vs = v_scale.transpose(0, 2, 1)
    tables = table.astype(jnp.int32)[None]
    pos = jnp.asarray(start, jnp.int32).reshape(1)
    out = _paged_mq_bhsd(qt, kt, vt, tables, pos, sm_scale=d ** -0.5,
                         n_heads=h, w_real=c, interpret=interpret,
                         ks=ks, vs=vs)
    return out[:, :c, :d].transpose(1, 0, 2)
