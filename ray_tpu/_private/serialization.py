"""Zero-copy-friendly serialization.

Counterpart of the reference's `_private/serialization.py` (pickle5 +
out-of-band buffers into plasma, :395 `_serialize_to_pickle5`). Envelope
layout (all little-endian):

    [u32 magic][u32 nbuf][u64 meta_len][u64 buf_len * nbuf]
    [meta(pickle bytes)][pad to 64][buf0][pad to 64][buf1]...

Large contiguous buffers (numpy arrays, bytes) are carried out-of-band so a
reader backed by an mmap can expose them zero-copy; pickle5's buffer protocol
does the heavy lifting, cloudpickle handles closures/lambdas/classes.
"""

import pickle
import struct
from typing import Callable

import cloudpickle

from ray_tpu._private.constants import BUFFER_ALIGNMENT

_MAGIC = 0x52545055  # "RTPU"
_HEADER = struct.Struct("<II Q")


def _align(n: int) -> int:
    return (n + BUFFER_ALIGNMENT - 1) // BUFFER_ALIGNMENT * BUFFER_ALIGNMENT


def _dumps_with_buffers(value) -> tuple[bytes, list[pickle.PickleBuffer]]:
    buffers: list[pickle.PickleBuffer] = []
    # Fast path: plain pickle (C pickler, no reducer_override dispatch) —
    # this is most of the put() cost for small data values. Two escapes
    # to cloudpickle: anything plain pickle can't handle (lambdas,
    # closures, locally-defined classes) raises, and anything that
    # pickled BY REFERENCE into __main__ would unpickle against the
    # wrong __main__ in another process — cloudpickle serializes those
    # by value. The b"__main__" scan is conservative: a false hit only
    # costs the fallback.
    try:
        meta = pickle.dumps(value, protocol=5,
                            buffer_callback=buffers.append)
        if b"__main__" not in meta:
            return meta, buffers
    except Exception:
        pass
    buffers.clear()
    # cloudpickle.dumps supports protocol 5 + buffer_callback and falls back to
    # pickling by value for interactively-defined functions/classes.
    meta = cloudpickle.dumps(value, protocol=5, buffer_callback=buffers.append)
    return meta, buffers


def serialized_size(value) -> tuple[int, bytes, list[pickle.PickleBuffer]]:
    """Compute the envelope size without materializing it (so the object
    store can allocate the mmap first and write in place)."""
    meta, buffers = _dumps_with_buffers(value)
    raws = [b.raw() for b in buffers]
    size = _HEADER.size + 8 * len(raws)
    size += len(meta)
    for r in raws:
        size = _align(size) + r.nbytes
    return size, meta, buffers


def write_envelope(dest: memoryview, meta: bytes,
                   buffers: list[pickle.PickleBuffer]) -> int:
    """Write the envelope into `dest`; returns bytes written."""
    raws = [b.raw() for b in buffers]
    off = 0
    _HEADER.pack_into(dest, off, _MAGIC, len(raws), len(meta))
    off += _HEADER.size
    for r in raws:
        struct.pack_into("<Q", dest, off, r.nbytes)
        off += 8
    dest[off:off + len(meta)] = meta
    off += len(meta)
    for r in raws:
        aligned = _align(off)
        off = aligned
        dest[off:off + r.nbytes] = r  # raw() is always 1-D contiguous "B"
        off += r.nbytes
    for b in buffers:
        b.release()
    return off


def dumps(value) -> bytes:
    """One-shot serialize to a standalone bytes envelope (inline objects)."""
    size, meta, buffers = serialized_size(value)
    out = bytearray(size)
    n = write_envelope(memoryview(out), meta, buffers)
    return bytes(out[:n])


def loads(view) -> object:
    """Deserialize from a bytes-like/memoryview envelope.

    Buffers are passed as sub-views of `view`: zero-copy when `view` is an
    mmap over the store file (arrays come out read-only, matching the
    reference's immutable plasma-backed numpy views, serialization.py:373).
    """
    view = memoryview(view)
    magic, nbuf, meta_len = _HEADER.unpack_from(view, 0)
    if magic != _MAGIC:
        raise ValueError("corrupt object envelope (bad magic)")
    off = _HEADER.size
    buf_lens = []
    for _ in range(nbuf):
        (n,) = struct.unpack_from("<Q", view, off)
        buf_lens.append(n)
        off += 8
    meta = view[off:off + meta_len]
    off += meta_len
    buffers = []
    for n in buf_lens:
        off = _align(off)
        buffers.append(pickle.PickleBuffer(view[off:off + n]))
        off += n
    return pickle.loads(meta, buffers=buffers)


def dumps_message(msg) -> bytes:
    """Serialize a control-plane message (no out-of-band buffers)."""
    return cloudpickle.dumps(msg, protocol=5)


loads_message: Callable[[bytes], object] = pickle.loads
