"""Per-process log capture + tail-to-head streaming.

Counterpart of the reference's log pipeline: every worker/daemon process
writes stdout+stderr to its own file under the session (node) dir, a
LogMonitor tails those files (`python/ray/_private/log_monitor.py:102`)
and publishes new lines so the driver can print them
(`worker.py` log_to_driver) and the dashboard can serve them
(`dashboard/modules/log/`). Here the head and every HostDaemon run one
`LogTailer` each over their local ``logs/`` dir; daemons ship batches to
the head over the node channel, and the head fans batches out to
subscribed drivers + keeps a bounded ring per source for `/api/logs`.
"""

from __future__ import annotations

import os
import threading
import time

from ray_tpu._private import config


class LogTailer:
    """Tails every ``*.log`` file under `log_dir`, invoking
    ``emit(source, lines)`` with decoded new lines. `source` is the file
    name minus extension (e.g. ``worker-abc123``)."""

    def __init__(self, log_dir: str, emit, interval: float | None = None):
        self.log_dir = log_dir
        self.emit = emit
        self.interval = (config.get("LOG_TAIL_INTERVAL_S")
                         if interval is None else interval)
        self._offsets: dict[str, int] = {}     # path -> bytes consumed
        self._partial: dict[str, bytes] = {}   # path -> trailing part-line
        self._stop = threading.Event()

    def start(self) -> "LogTailer":
        threading.Thread(target=self._loop, name="log-tailer",
                         daemon=True).start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll()
            except Exception:
                pass
            self._stop.wait(self.interval)

    def poll(self) -> None:
        """One tail pass (public so tests can drive it deterministically)."""
        if not os.path.isdir(self.log_dir):
            return
        for name in sorted(os.listdir(self.log_dir)):
            if not name.endswith(".log"):
                continue
            path = os.path.join(self.log_dir, name)
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            off = self._offsets.get(path, 0)
            if size < off:          # truncated/rotated: start over
                off = 0
                self._partial.pop(path, None)
            if size == off:
                continue
            try:
                with open(path, "rb") as f:
                    f.seek(off)
                    chunk = f.read(size - off)
            except OSError:
                continue
            self._offsets[path] = off + len(chunk)
            data = self._partial.pop(path, b"") + chunk
            *lines, tail = data.split(b"\n")
            if tail:
                self._partial[path] = tail
            if lines:
                self.emit(name[:-4],
                          [ln.decode(errors="replace") for ln in lines])


class LogRing:
    """Bounded per-source line ring the head serves `/api/logs` from
    (daemon files aren't reachable across machines, their lines are)."""

    def __init__(self, max_lines: int | None = None):
        self.max_lines = (config.get("LOG_RING_LINES")
                          if max_lines is None else max_lines)
        self._lock = threading.Lock()
        self._rings: dict[str, list[str]] = {}
        self._stamps: dict[str, float] = {}

    def append(self, source: str, lines: list[str]) -> None:
        with self._lock:
            ring = self._rings.setdefault(source, [])
            ring.extend(lines)
            if len(ring) > self.max_lines:
                del ring[:len(ring) - self.max_lines]
            self._stamps[source] = time.time()

    def sources(self) -> list[dict]:
        with self._lock:
            return [{"source": s, "lines": len(r),
                     "last_ts": self._stamps.get(s)}
                    for s, r in sorted(self._rings.items())]

    def tail(self, source: str, n: int = 200) -> list[str]:
        if n <= 0:
            return []
        with self._lock:
            return list(self._rings.get(source, [])[-n:])
