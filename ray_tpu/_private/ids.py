"""Unique identifiers for objects/tasks/actors/jobs.

Counterpart of the reference's `src/ray/common/id.h` (JobID/TaskID/ActorID/
ObjectID). We use 16 random bytes rendered as hex; IDs are plain strings so
they pickle cheaply and hash fast in Python dicts.
"""

import os
import binascii


def _rand_hex(nbytes: int = 16) -> str:
    return binascii.hexlify(os.urandom(nbytes)).decode()


def new_object_id() -> str:
    return "obj_" + _rand_hex()


def new_task_id() -> str:
    return "task_" + _rand_hex(8)


def new_actor_id() -> str:
    return "actor_" + _rand_hex(8)


def new_worker_id() -> str:
    return "worker_" + _rand_hex(6)


def new_placement_group_id() -> str:
    return "pg_" + _rand_hex(6)


def new_job_id() -> str:
    return "job_" + _rand_hex(4)


def new_node_id() -> str:
    return "node_" + _rand_hex(6)
