"""Unique identifiers for objects/tasks/actors/jobs.

Counterpart of the reference's `src/ray/common/id.h` (JobID/TaskID/ActorID/
ObjectID). We use 16 random bytes rendered as hex; IDs are plain strings so
they pickle cheaply and hash fast in Python dicts.
"""

import os
import random

# Uniqueness, not secrecy: a per-process PRNG seeded from the OS avoids
# one urandom syscall per id on the task-submission hot path (~1M ids
# per large driver run). getrandbits is a single C call under the GIL,
# so concurrent submitters can share it safely.
_rng = random.Random(os.urandom(16))


def _rand_hex(nbytes: int = 16) -> str:
    return f"{_rng.getrandbits(nbytes * 8):0{nbytes * 2}x}"


def reseed() -> None:
    """Re-seed after fork (child processes must not replay the parent's
    id stream)."""
    global _rng
    _rng = random.Random(os.urandom(16))


# Any fork site (user-level multiprocessing included, not just our
# forkserver) gets a fresh stream — id collisions between forked
# children would silently alias distinct objects in the store.
os.register_at_fork(after_in_child=reseed)


def new_object_id() -> str:
    return "obj_" + _rand_hex()


def new_task_id() -> str:
    return "task_" + _rand_hex(8)


def new_actor_id() -> str:
    return "actor_" + _rand_hex(8)


def new_worker_id() -> str:
    return "worker_" + _rand_hex(6)


def new_placement_group_id() -> str:
    return "pg_" + _rand_hex(6)


def new_job_id() -> str:
    return "job_" + _rand_hex(4)


def new_node_id() -> str:
    return "node_" + _rand_hex(6)
