"""Head node server: cluster store, cluster scheduler, object directory,
and the head host's own worker pool.

The head process plays the reference's GCS (gcs_server.h:78: named actors,
KV, job table, node membership, placement groups) plus the head host's
raylet (node_manager.h:117: worker leasing, dependency management, local
dispatch) plus the ownership-based object directory
(reference_count.h:61 + ownership_based_object_directory.h).

Additional hosts run a `HostDaemon` each (`daemon.py` — the raylet
equivalent owning that host's object store and worker pool). The head's
cluster scheduler (`_pick_node`: affinity → SPREAD → locality → pack, the
hybrid_scheduling_policy.h:50 counterpart) assigns tasks to nodes and
leases them over the node channel; object bytes move node-to-node through
chunked pulls (object_manager.h:130,139). `cluster_utils.Cluster` spins up
N daemons on one machine with fake resources — the reference's
one-host multi-raylet test fixture (python/ray/cluster_utils.py:99).

Worker processes connect over a UNIX socket; the message set is
`protocol.py`.
"""

from __future__ import annotations

import atexit
import itertools
import logging
import os
import shutil
import subprocess
import sys
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field, replace
from multiprocessing import connection

from ray_tpu._private import config, constants, ids, netaddr, protocol
from ray_tpu._private.object_store import Descriptor, ObjectStore
from ray_tpu._private.serialization import dumps
from ray_tpu.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    ObjectFreedError,
    ObjectLostError,
    PlacementGroupError,
    SchedulingError,
    RayTpuError,
    TaskCancelledError,
    WorkerCrashedError,
)

logger = logging.getLogger("ray_tpu")

_EPS = 1e-9


def _lineage_size(spec) -> int:
    """Approximate retained bytes of one lineage entry (blob + inline
    args + fixed overhead)."""
    n = len(spec.function_blob or b"") + 256
    for kind, v in list(spec.args) + list(spec.kwargs.values()):
        if kind == "v":
            n += len(v)
    return n


def _fits(avail: dict, req: dict) -> bool:
    return all(avail.get(k, 0.0) + _EPS >= v for k, v in req.items())


def _sub(avail: dict, req: dict) -> None:
    for k, v in req.items():
        avail[k] = avail.get(k, 0.0) - v


def _add(avail: dict, req: dict) -> None:
    for k, v in req.items():
        avail[k] = avail.get(k, 0.0) + v


def plan_gang_placement(pools, bundles, strategy, *, links=None,
                        link_load=None, bandwidth=0.0):
    """Pure bundle-placement planner (no NodeServer state): pick a pool
    for every bundle under `strategy`, contention-aware for bandwidth-
    tagged gangs.

    pools      ordered [(pool_id, available_resources)] — first entry is
               the preferred pool (the head's own ledger).
    links      pool_id -> iterable of interconnect link-group ids the
               pool hangs off (ICI ring / DCN pod, RAY_TPU_LINK_GROUPS).
    link_load  link id -> number of bandwidth-tagged gangs already
               placed on that link.
    bandwidth  this gang's declared appetite (GB/s); 0 keeps the legacy
               ordering exactly (contention never enters the sort key).

    Scoring follows the contention model of 2207.07817: a pool's cost is
    the number of bandwidth-hungry gangs sharing any of its links, so a
    tagged gang gets anti-affinity from links other tagged gangs load.
    SPREAD ranks fitting pools by (bundle count so far, contention,
    arrival order); PACK/STRICT_PACK rank by (contention, arrival
    order). All keys are integers and the sort is stable, so placement
    is deterministic for a given pool order and load map.

    Returns a list of pool ids aligned with `bundles`, or None if the
    gang is infeasible on the current free pools.
    """
    links = links or {}
    link_load = link_load or {}
    sim = {pid: dict(av) for pid, av in pools}
    order = [pid for pid, _ in pools]
    idx = {pid: i for i, pid in enumerate(order)}

    if bandwidth:
        cost = {pid: sum(link_load.get(l, 0) for l in links.get(pid, ()))
                for pid in order}
    else:
        cost = dict.fromkeys(order, 0)

    if strategy == "STRICT_PACK":
        # every bundle on ONE pool; tagged gangs try quiet pools first
        for pid in sorted(order, key=lambda p: (cost[p], idx[p])):
            s = dict(sim[pid])
            if all(_fits(s, b) and (_sub(s, b) or True) for b in bundles):
                return [pid] * len(bundles)
        return None
    assignment = []
    if strategy == "STRICT_SPREAD":
        used = set()
        for b in bundles:
            ranked = sorted(order, key=lambda p: (cost[p], idx[p]))
            pid = next((p for p in ranked
                        if p not in used and _fits(sim[p], b)), None)
            if pid is None:
                return None
            _sub(sim[pid], b)
            used.add(pid)
            assignment.append(pid)
        return assignment
    if strategy == "SPREAD":
        # best-effort distinct: prefer the fitting pool with the fewest
        # bundles so far, quietest links breaking the tie
        counts = dict.fromkeys(order, 0)
        for b in bundles:
            ranked = sorted(order,
                            key=lambda p: (counts[p], cost[p], idx[p]))
            pid = next((p for p in ranked if _fits(sim[p], b)), None)
            if pid is None:
                return None
            _sub(sim[pid], b)
            counts[pid] += 1
            assignment.append(pid)
        return assignment
    # PACK (default): first-fit in (contention, arrival) order — with no
    # bandwidth tag that is exactly the legacy head-first scan
    ranked = sorted(order, key=lambda p: (cost[p], idx[p]))
    for b in bundles:
        pid = next((p for p in ranked if _fits(sim[p], b)), None)
        if pid is None:
            return None
        _sub(sim[pid], b)
        assignment.append(pid)
    return assignment


@dataclass
class _TaskState:
    spec: protocol.TaskSpec
    deps: set = field(default_factory=set)   # unresolved object ids
    submitter: object = None                 # _WorkerConn for nested submits
    retries_left: int = 0
    retry_exceptions: bool = False
    cancelled: bool = False
    node: str | None = None                  # node leased to (None = head)
    node_released: bool = False              # resources released (blocked)
    tpu_chips: list = field(default_factory=list)
    localizing: bool = False                 # remote-arg pull in flight
    dep_failures: int = 0                    # free requeues on dep pulls


@dataclass
class _WorkerConn:
    worker_id: str
    conn: connection.Connection
    proc: object = None                      # mp.Process | subprocess.Popen
    # "generic" (pool) | "actor" | "dedicated" (TPU / runtime-env tasks,
    # retire after one task) | "attach" (external CLI/job connections)
    kind: str = "generic"
    idle: bool = True
    current: _TaskState | None = None
    known_functions: set = field(default_factory=set)
    send_lock: threading.Lock = field(default_factory=threading.Lock)
    # resources temporarily released while the worker blocks in get()
    released: dict = field(default_factory=dict)
    alive: bool = True
    # True for conns accepted on the TCP listener from another machine:
    # they can't mmap this host's store, so get/put payloads ride inline
    remote: bool = False
    # set exactly once when RegisterWorker lands: spawn waiters block on
    # THIS, not the global cv (a notify_all herd under creation bursts)
    reg_event: threading.Event = field(default_factory=threading.Event)
    # True while a pool worker is converted into an actor host; lets a
    # failed constructor hand the (still healthy) worker back to the pool
    pooled_actor: bool = False
    # Pipelined-submission receive state (only the per-worker reader
    # thread touches these): next expected SubmitRequest.seq, and
    # whether a nack for the current gap is already outstanding.
    sub_next: int = 0
    sub_nacked: bool = False

    def send(self, msg) -> bool:
        # conn is None between spawn and registration
        return protocol.safe_send(self.conn, self.send_lock, msg)


@dataclass
class _ActorState:
    actor_id: str
    creation_spec: protocol.TaskSpec
    worker: _WorkerConn | None = None
    ready: bool = False
    dead: bool = False
    death_cause: str = ""
    queue: list = field(default_factory=list)    # pending _TaskState, FIFO
    inflight: list = field(default_factory=list)
    max_concurrency: int = 1
    max_restarts: int = 0
    restarts_used: int = 0
    max_task_retries: int = 0
    name: str | None = None
    resources: dict = field(default_factory=dict)
    tpu_chips: list = field(default_factory=list)
    method_meta: dict = field(default_factory=dict)  # for get_actor handles
    pending_restart: bool = False
    node: str | None = None      # node hosting the actor (None = head)


@dataclass
class _PlacementGroup:
    pg_id: str
    bundles: list            # list[dict]
    strategy: str
    available: list = None   # per-bundle remaining resources
    bundle_nodes: list = None  # per-bundle node id (None = head)
    # Declared interconnect appetite (GB/s, 0 = indifferent). Bandwidth-
    # tagged gangs count toward per-link contention in the placement
    # model (2207.07817): later tagged gangs steer away from links these
    # bundles already load.
    bandwidth: float = 0.0

    def __post_init__(self):
        if self.available is None:
            self.available = [dict(b) for b in self.bundles]
        if self.bundle_nodes is None:
            self.bundle_nodes = [None] * len(self.bundles)


@dataclass
class _RemoteNode:
    """Head-side record of a registered HostDaemon (the GCS's view of one
    raylet: gcs_node_manager + per-node resource bookkeeping)."""
    node_id: str
    conn: connection.Connection
    address: str                              # daemon listener (peer pulls)
    pid: int = 0
    proc: object = None                       # Popen if the head spawned it
    total: dict = field(default_factory=dict)
    available: dict = field(default_factory=dict)
    free_tpu_chips: list = field(default_factory=list)
    # interconnect link-group ids this host hangs off (RegisterNode
    # .link_groups, from RAY_TPU_LINK_GROUPS on the daemon's machine)
    links: list = field(default_factory=list)
    alive: bool = True
    inflight: dict = field(default_factory=dict)  # task_id -> _TaskState
    last_seq: int = 0   # highest NodeSeq seen (dedupe for blip replays)
    send_lock: threading.Lock = field(default_factory=threading.Lock)
    # duck-typing so the shared get/wait request handlers accept a node
    # channel in place of a _WorkerConn
    kind: str = "node"
    worker_id: str = ""
    current: object = None
    released: dict = field(default_factory=dict)
    # daemons localize via the pull plane, never inline (see _WorkerConn)
    remote: bool = False

    def send(self, msg) -> bool:
        return protocol.safe_send(self.conn, self.send_lock, msg)


class NodeServer:
    """One per session; lives in the driver process."""

    def __init__(self, resources: dict, session_dir: str, num_tpu_chips: int,
                 standalone: bool = False):
        self.session_dir = session_dir
        self.standalone = standalone
        self.node_id = ids.new_node_id()
        self.store = ObjectStore(session_dir)
        self.total_resources = dict(resources)
        self.available = dict(resources)
        self.free_tpu_chips = list(range(num_tpu_chips))

        self.lock = threading.RLock()
        self.cv = threading.Condition(self.lock)   # object-ready notification

        self.directory: dict[str, Descriptor] = {}
        self.obj_waiting_tasks: dict[str, list[_TaskState]] = {}
        # counter-based get() waiters: oid -> [waiter dicts]; each
        # registration decrements instead of every blocked get()
        # rescanning its whole id list per wakeup (O(ids^2) for a
        # 100k-ref ray.get otherwise)
        self._get_waiters: dict[str, list] = {}

        # Distributed refcount state (reference: ReferenceCounter,
        # reference_count.h:61). An object is freed when: no process holds
        # a live ObjectRef (ref_holders empty), no queued/running task will
        # consume it (task_arg_refs 0), and it never escaped via pickle.
        self.ref_holders: dict[str, set] = {}     # oid -> holder ids
        self.escaped_refs: set = set()
        self.task_arg_refs: dict[str, int] = {}   # oid -> pending consumers
        self.obj_origin: dict[str, str] = {}      # oid -> worker_id|driver
        self.dead_pending: set = set()            # released pre-registration
        # ids freed by refcounting: tombstones so a racing get/wait/submit
        # fails fast instead of waiting forever for a re-registration that
        # can never come (bounded FIFO)
        self.freed_refs: "OrderedDict[str, bool]" = OrderedDict()
        # task_ids whose args were already released (exactly-once guard);
        # bounded FIFO so a long session doesn't grow it forever
        self._args_released: "OrderedDict[str, bool]" = OrderedDict()

        self.pending: "deque[_TaskState]" = deque()
        self.workers: dict[str, _WorkerConn] = {}
        self.actors: dict[str, _ActorState] = {}
        self.named_actors: dict[str, str] = {}
        self.placement_groups: dict[str, _PlacementGroup] = {}
        self.kv: dict[tuple, bytes] = {}

        # Multi-node state (the GCS side of the split, gcs_server.h:78):
        # registered HostDaemons, head-local cached copies of remote
        # objects, which nodes cached copies of what (for promotion on
        # owner-node death, object_recovery_manager.h:41), and objects
        # whose every copy died with a node.
        self.nodes: dict[str, _RemoteNode] = {}
        self.local_copies: dict[str, Descriptor] = {}
        # oid -> {node_id: that node's OWN copy descriptor} (backing can
        # differ from the primary's, so promotion must use it verbatim)
        self.copy_nodes: dict[str, dict] = {}
        self.lost_objects: dict[str, str] = {}    # oid -> cause
        # Lineage: producing TaskSpec per live task-returned object, so a
        # copy lost with its node can be rebuilt by re-executing the task
        # (reference: lineage pinning in ReferenceCounter + resubmission,
        # task_manager.h:173, object_recovery_manager.h:41). Entries drop
        # when the object is freed or the FIFO cap evicts them.
        self.lineage: "OrderedDict[str, protocol.TaskSpec]" = OrderedDict()
        self._lineage_bytes = 0                    # accumulated spec bytes
        self.reconstructions: dict[str, int] = {}  # oid -> rebuild count
        self.reconstructing: set = set()           # oids being rebuilt
        self._spread_rr = 0
        from ray_tpu._private.pull_plane import PullClient
        self._pull_client = PullClient()
        self._head_pulling: set = set()       # oids being pulled to head

        self._task_errors: dict[str, str] = {}
        # Observability: task lifecycle records (reference: TaskEventBuffer →
        # GcsTaskManager) + per-process metrics snapshots pushed by workers.
        from ray_tpu._private.events import TaskEventRecorder
        self.task_events = TaskEventRecorder()
        self.metrics_by_proc: dict[str, list] = {}
        # the head's lane in merged chrome-trace exports
        from ray_tpu.util import tracing as _tracing
        _tracing.set_process_label("driver")
        # recorder occupancy counters on /metrics (events_tasks_tracked,
        # events_stage_samples, events_got_pending)
        from ray_tpu.util import telemetry as _telemetry
        _telemetry.register_stats_source("task_events", self.task_events,
                                         kind="events")
        self._shutdown = False
        self._spawning = 0      # generic workers currently starting up
        self._spawn_failures = 0  # consecutive startup failures

        # Pidfile lets a later init() garbage-collect sessions whose driver
        # crashed without shutdown (the reference GCs stale session dirs in
        # _private/node.py similarly).
        with open(os.path.join(session_dir, "driver.pid"), "w") as f:
            f.write(str(os.getpid()))

        # Session authkey, in precedence order: operator-pinned env (a
        # k8s Secret — head pod restarts keep the credential), an
        # existing session file (standalone restart into the same dir),
        # else freshly minted. Persisted (0600) so external processes —
        # the CLI, job drivers — can attach to this session (reference:
        # Redis password / GCS address in the session dir).
        keypath = os.path.join(session_dir, "authkey")
        env_key = os.environ.get("RAY_TPU_AUTHKEY") if standalone else None
        on_disk = None
        if os.path.exists(keypath):
            with open(keypath, "rb") as f:
                on_disk = f.read()
        if env_key:
            self._authkey = bytes.fromhex(env_key)
        elif standalone and on_disk:
            self._authkey = on_disk
        else:
            self._authkey = os.urandom(16)
        if on_disk != self._authkey:
            # write only on change: restarting heads must not truncate
            # the file under clients that are mid-read retrying attach
            fd = os.open(keypath,
                         os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
            with os.fdopen(fd, "wb") as f:
                f.write(self._authkey)
        self._address = os.path.join(session_dir, "node.sock")
        if standalone and os.path.exists(self._address):
            # leftover socket from the previous head incarnation
            os.unlink(self._address)
        if standalone:
            self._restore_state()
        self._sched_event = threading.Event()
        threading.Thread(target=self._scheduler_loop,
                         name="ray_tpu-scheduler", daemon=True).start()
        # free-fanout outbox: _maybe_free_locked runs under self.lock,
        # and O(workers) blocking sends in there would let one full pipe
        # stall the whole head during a release storm — a dedicated
        # thread drains the sends outside the lock
        import collections as _collections
        self._free_outbox: _collections.deque = _collections.deque()
        self._free_event = threading.Event()
        threading.Thread(target=self._free_fanout_loop,
                         name="ray_tpu-free-fanout", daemon=True).start()
        self._listener = netaddr.listener(self._address, self._authkey)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="ray_tpu-accept", daemon=True)
        self._accept_thread.start()
        # TCP tier: daemons and client drivers on OTHER machines dial this
        # listener (reference: gRPC-over-TCP everywhere cross-host,
        # src/ray/rpc/grpc_server.h; UDS stays for same-host workers).
        self.tcp_address = None
        self._tcp_listener = None
        if config.get("TRANSPORT") == "tcp" or config.get("HEAD_PORT"):
            bind = (config.get("HEAD_BIND_HOST"), config.get("HEAD_PORT"))
            self._tcp_listener = netaddr.listener(bind, self._authkey)
            self.tcp_address = netaddr.bound_address(self._tcp_listener)
            # published for operators/other machines (reference: GCS
            # address in the session files, services.py:1353)
            with open(os.path.join(session_dir, "head_address"), "w") as f:
                f.write(self.tcp_address)
            threading.Thread(
                target=self._accept_loop, args=(self._tcp_listener, True),
                name="ray_tpu-tcp-accept", daemon=True).start()
        if self.store.arena_stats() is not None:
            threading.Thread(target=self._spill_loop,
                             name="ray_tpu-spill", daemon=True).start()
        from ray_tpu._private.memory_monitor import MemoryMonitor
        self._memory_monitor = MemoryMonitor(self)
        self._memory_monitor.start()
        # Log pipeline (reference: log_monitor.py:102 + dashboard log
        # module): tail this host's per-process log files; daemons ship
        # theirs over the node channel; ring + subscribers fan out.
        from ray_tpu._private.log_monitor import LogRing, LogTailer
        self._log_ring = LogRing()
        self._log_subs: list = []     # conns (have .send) or callables
        # stack-dump collection + pubsub channels
        self._stack_req = itertools.count(1)
        self._stack_waits: dict = {}
        self._stack_cv = threading.Condition()
        self._pubsub: dict = {}       # channel -> [last_seq, ring]
        self._pubsub_cv = threading.Condition()
        self._log_tailer = LogTailer(
            os.path.join(session_dir, "logs"),
            lambda src, lines: self._publish_logs(
                protocol.LogBatch(src, None, lines))).start()
        if standalone:
            threading.Thread(target=self._snapshot_loop,
                             name="ray_tpu-gcs-snapshot",
                             daemon=True).start()
        # usage stats: local session snapshot always; network report only
        # when explicitly opted in (usage_lib.py:92 analog, inverted)
        from ray_tpu._private.usage_stats import UsageReporter
        self._usage_reporter = UsageReporter(self).start()
        atexit.register(self.shutdown)

    # ------------------------------------------------------------------
    # on-demand stack dumps (reference: `ray stack` CLI scripts.py:1786 +
    # py-spy profile_manager.py — workers self-sample, no ptrace)
    # ------------------------------------------------------------------

    def collect_stacks(self, worker_id: str | None = None,
                       timeout: float = 5.0) -> dict:
        """Fan DumpStack to head-local workers and every node; gather
        replies for up to `timeout`s. -> {worker_id: {pid, stacks}}."""
        req = next(self._stack_req)
        box: dict = {}
        with self._stack_cv:
            self._stack_waits[req] = box
        expect = 0
        with self.lock:
            for w in self.workers.values():
                if w.alive and w.kind != "attach" and (
                        worker_id is None or w.worker_id == worker_id):
                    if w.send(protocol.DumpStack(req, worker_id)):
                        expect += 1
            nodes = [n for n in self.nodes.values() if n.alive]
        for n in nodes:
            n.send(protocol.DumpStack(req, worker_id))
        deadline = time.monotonic() + timeout
        grace = 0.5    # node worker counts are unknown up front: stop
        #                once replies go quiet for this long
        last_size, quiet_since = 0, time.monotonic()
        with self._stack_cv:
            while True:
                rem = deadline - time.monotonic()
                if rem <= 0 or (worker_id is not None and box):
                    break
                if not nodes and expect and len(box) >= expect:
                    break
                if len(box) != last_size:
                    last_size, quiet_since = len(box), time.monotonic()
                elif box and time.monotonic() - quiet_since >= grace:
                    break
                self._stack_cv.wait(min(rem, 0.25))
            self._stack_waits.pop(req, None)
        return dict(box)

    def _on_stack_reply(self, msg: protocol.StackDumpReply) -> None:
        with self._stack_cv:
            box = self._stack_waits.get(msg.req_id)
            if box is not None:
                box[msg.worker_id] = {"pid": msg.pid, "stacks": msg.text}
                self._stack_cv.notify_all()

    # ------------------------------------------------------------------
    # pubsub channels (reference: src/ray/pubsub/publisher.h:307 long-
    # poll publisher/subscriber framework; here a head-held ring per
    # channel + long-poll control verbs)
    # ------------------------------------------------------------------

    def pubsub_publish(self, channel: str, message) -> int:
        with self._pubsub_cv:
            seq, ring = self._pubsub.setdefault(channel, [0, []])
            seq += 1
            ring.append((seq, message))
            cap = config.get("PUBSUB_RING_MESSAGES")
            if len(ring) > cap:
                del ring[:len(ring) - cap]
            self._pubsub[channel] = [seq, ring]
            self._pubsub_cv.notify_all()
        return seq

    def pubsub_poll(self, channel: str, after: int,
                    timeout: float = 30.0):
        """Long-poll: block until the channel holds messages with seq >
        after (or timeout) -> (last_seq, [messages]). Runs on a
        _BLOCKING_CONTROL thread, never a reader loop."""
        deadline = time.monotonic() + max(0.0, timeout)
        with self._pubsub_cv:
            while True:
                seq, ring = self._pubsub.get(channel, (0, []))
                fresh = [m for s, m in ring if s > after]
                if fresh:
                    return seq, fresh
                rem = deadline - time.monotonic()
                if rem <= 0 or self._shutdown:
                    return seq, []
                self._pubsub_cv.wait(min(rem, 0.5))

    # ------------------------------------------------------------------
    # log pipeline fanout
    # ------------------------------------------------------------------

    def _publish_logs(self, batch: protocol.LogBatch) -> None:
        key = batch.source if batch.node_id is None \
            else f"{batch.node_id}/{batch.source}"
        self._log_ring.append(key, batch.lines)
        with self.lock:
            subs = list(self._log_subs)
        dead = []
        for s in subs:
            if callable(s):
                try:
                    s(batch)
                except Exception:
                    dead.append(s)
            elif not s.send(batch) or not s.alive:
                dead.append(s)
        if dead:
            with self.lock:
                self._log_subs = [s for s in self._log_subs
                                  if s not in dead]

    def _log_subscribe(self, w) -> bool:
        if w is None:
            # driver-mode client: print straight to this process's stderr
            # (reference: worker.py log_to_driver printing with a
            # (pid=..., ip=...) prefix)
            def _print(batch: protocol.LogBatch):
                nid = batch.node_id or "head"
                for ln in batch.lines:
                    print(f"({batch.source}, node={nid}) {ln}",
                          file=sys.stderr)
            sub = _print
        else:
            sub = w
        with self.lock:
            self._log_subs.append(sub)
        return True

    # ------------------------------------------------------------------
    # autoscaler monitor (reference: autoscaler/_private/monitor.py:126 —
    # the head-side Monitor reads cluster load every tick,
    # update_load_metrics :249, and drives StandardAutoscaler.update)
    # ------------------------------------------------------------------

    def attach_autoscaler(self, config: dict, provider=None) -> dict:
        """Close the loop: demand flows head -> LoadMetrics ->
        StandardAutoscaler -> NodeProvider -> real HostDaemons."""
        from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
        from ray_tpu.autoscaler.load_metrics import LoadMetrics
        from ray_tpu.autoscaler.node_provider import make_node_provider
        prov_spec = config.pop("provider", None) \
            if isinstance(config, dict) else None
        if prov_spec and prov_spec.get("type") == "gcp-tpu":
            # booted slices need somewhere to register; the head is the
            # only party that knows its own dialable address + authkey.
            # A UNIX-socket-only head would bake an unjoinable path into
            # every slice's startup script — refuse before billing starts.
            if not prov_spec.get("head_address"):
                if self.tcp_address is None:
                    raise RuntimeError(
                        "gcp-tpu provider requires the head to listen on "
                        "TCP so slices can join; start it with --port "
                        "(or RAY_TPU_TRANSPORT=tcp)")
                prov_spec["head_address"] = self.tcp_address
            prov_spec.setdefault("authkey_hex", self._authkey.hex())
        with self.lock:
            if getattr(self, "_autoscaler", None) is not None:
                raise RuntimeError("autoscaler already attached")
            self._load_metrics = LoadMetrics()
            self._pending_gangs: list = []
            self._autoscaler = StandardAutoscaler(
                provider or make_node_provider(prov_spec, self), config,
                self._load_metrics)
            self._autoscaler_err: str | None = None
            self._autoscaler_ts: float = 0.0
        threading.Thread(target=self._monitor_loop,
                         name="ray_tpu-autoscaler", daemon=True).start()
        return {"ok": True}

    def _monitor_loop(self):
        period = config.get("AUTOSCALER_UPDATE_INTERVAL_S")
        while not self._shutdown:
            time.sleep(period)
            if self._autoscaler is None:     # torn down
                return
            try:
                self._update_load_metrics()
                self._autoscaler.update()
                self._autoscaler_err = None
            except Exception as e:
                logger.exception("autoscaler update failed")
                self._autoscaler_err = repr(e)
            self._autoscaler_ts = time.time()
            # capacity may have arrived for a waiting placement group
            with self.cv:
                self.cv.notify_all()

    def _update_load_metrics(self):
        lm = self._load_metrics
        with self.lock:
            actor_nodes = {a.node for a in self.actors.values()
                           if not a.dead and a.ready}
            head_busy = any(w.current is not None
                            for w in self.workers.values())
            lm.update_node("head", self.total_resources, self.available,
                           busy=head_busy or None in actor_nodes)
            for nid, n in list(self.nodes.items()):
                if not n.alive:
                    lm.remove_node(nid)
                    continue
                pg_here = any(
                    nid in pg.bundle_nodes
                    for pg in self.placement_groups.values())
                lm.update_node(nid, n.total, n.available,
                               busy=bool(n.inflight)
                               or nid in actor_nodes or pg_here)
            # unplaced actor creations sit in self.pending too, so one
            # pass covers both task and actor demand
            demands = [dict(t.spec.resources) for t in self.pending
                       if not t.deps and not t.cancelled]
            gangs = [[dict(b) for b in g] for g in self._pending_gangs]
            lm.set_demands(demands, gangs)

    def dashboard_snapshot(self) -> dict:
        """One cheap gauge sample for the dashboard's timeseries charts
        (reference: dashboard/modules/metrics/ feeds grafana; here the
        UI buffers these client-side and draws its own sparklines)."""
        with self.lock:
            snap = {
                "ts": time.time(),
                "nodes_alive": 1 + sum(
                    1 for n in self.nodes.values() if n.alive),
                "workers_alive": sum(
                    1 for w in self.workers.values()
                    if w.alive and w.kind != "attach"),
                "actors_alive": sum(
                    1 for a in self.actors.values() if not a.dead),
                "tasks_pending": len(self.pending),
                "objects_tracked": len(self.directory),
            }
        st = self.store.arena_stats() or {}
        snap["store_used_bytes"] = int(st.get("used", 0))
        snap["store_num_objects"] = int(st.get("num_objects", 0))
        return snap

    def autoscaler_teardown(self) -> dict:
        """Terminate every provider node (cloud slices!) before the head
        dies — `ray-tpu down` must never leak billed TPU capacity. The
        head process is the only place the provider instance lives, so
        teardown is a control verb, not a CLI-side loop."""
        a = getattr(self, "_autoscaler", None)
        if a is None:
            return {"terminated": 0}
        # stop the monitor loop first or min_workers would relaunch what
        # we are about to terminate
        with self.lock:
            self._autoscaler = None
        errs = []
        nids = a.provider.non_terminated_nodes({})
        for nid in nids:
            try:
                a.provider.terminate_node(nid)
            except Exception as e:
                errs.append(f"{nid}: {e!r}")
        return {"terminated": len(nids) - len(errs), "errors": errs}

    def autoscaler_status(self) -> dict:
        a = getattr(self, "_autoscaler", None)
        if a is None:
            return {"enabled": False}
        with self.lock:
            pending = len([t for t in self.pending if not t.deps])
            gangs = len(self._pending_gangs)
        return {
            "enabled": True,
            "workers_by_type": a._workers_by_type(),
            "max_workers": a.config["max_workers"],
            "pending_demands": pending,
            "pending_gangs": gangs,
            "infeasible_gangs": len(a.infeasible_gangs),
            "last_update_ts": self._autoscaler_ts,
            "last_error": self._autoscaler_err,
        }

    # ------------------------------------------------------------------
    # metadata persistence (standalone head only; reference: Redis-backed
    # GCS store, store_client/redis_store_client.h:33 — daemons and
    # detached actors survive a head restart, test_gcs_fault_tolerance.py)
    # ------------------------------------------------------------------

    def _snapshot_path(self) -> str:
        return os.path.join(self.session_dir, "head_state.pkl")

    def _snapshot_loop(self):
        import pickle
        period = config.get("HEAD_SNAPSHOT_INTERVAL_S")
        uri = config.get("HEAD_SNAPSHOT_URI")
        last_digest = None
        while not self._shutdown:
            time.sleep(period)
            try:
                state = self._snapshot_state()
                blob = pickle.dumps(state)
                import hashlib
                digest = hashlib.sha1(blob).digest()
                if digest == last_digest:
                    continue      # unchanged: skip disk AND remote writes
                tmp = self._snapshot_path() + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(blob)
                os.replace(tmp, self._snapshot_path())
                if uri:
                    # remote mirror -> a replacement head on another
                    # machine can take over (Redis-GCS analog)
                    from ray_tpu.util import storage
                    storage.write_bytes(
                        storage.uri_join(uri, "head_state.pkl"), blob)
                last_digest = digest
            except Exception:
                logger.exception("head snapshot failed")

    def _snapshot_state(self) -> dict:
        """Cluster METADATA only (no object payloads): what a restarted
        head needs to re-attach daemons and detached actors."""
        with self.lock:
            actors = {}
            for aid, a in self.actors.items():
                if a.dead:
                    continue
                actors[aid] = {
                    "creation_spec": a.creation_spec,
                    "max_concurrency": a.max_concurrency,
                    "max_restarts": a.max_restarts,
                    "restarts_used": a.restarts_used,
                    "max_task_retries": a.max_task_retries,
                    "name": a.name,
                    "resources": dict(a.resources),
                    "tpu_chips": list(a.tpu_chips),
                    "method_meta": a.method_meta,
                    "node": a.node,
                }
            pgs = {pid: {"bundles": pg.bundles, "strategy": pg.strategy,
                         "available": pg.available,
                         "bundle_nodes": pg.bundle_nodes,
                         "bandwidth": pg.bandwidth}
                   for pid, pg in self.placement_groups.items()}
            return {
                "named_actors": dict(self.named_actors),
                "actors": actors,
                "kv": dict(self.kv),
                "placement_groups": pgs,
            }

    def _restore_state(self):
        import pickle
        path = self._snapshot_path()
        blob = None
        if os.path.exists(path):
            try:
                with open(path, "rb") as f:
                    blob = f.read()
            except OSError:
                logger.exception("local head snapshot unreadable")
        if blob is None:
            uri = config.get("HEAD_SNAPSHOT_URI")
            if uri:
                # failover: a fresh machine with no session dir restores
                # the cluster metadata from the remote mirror
                try:
                    from ray_tpu.util import storage
                    blob = storage.read_bytes(
                        storage.uri_join(uri, "head_state.pkl"))
                    logger.warning("restoring head state from %s", uri)
                except FileNotFoundError:
                    pass
                except Exception:
                    logger.exception("remote head snapshot unreadable")
        if blob is None:
            return
        try:
            state = pickle.loads(blob)
        except Exception:
            logger.exception("head snapshot unreadable; starting fresh")
            return
        for aid, d in state.get("actors", {}).items():
            a = _ActorState(
                actor_id=aid, creation_spec=d["creation_spec"],
                max_concurrency=d["max_concurrency"],
                max_restarts=d["max_restarts"],
                restarts_used=d["restarts_used"],
                max_task_retries=d["max_task_retries"],
                name=d["name"], resources=d["resources"],
                tpu_chips=d["tpu_chips"], method_meta=d["method_meta"],
                node=d["node"])
            if d["node"] is None:
                # head-local actor processes died with the head
                a.dead = True
                a.death_cause = "head restarted (actor lived on the head)"
            else:
                # awaiting its daemon's re-registration
                a.ready = False
            self.actors[aid] = a
        for a in self.actors.values():
            if not a.dead:
                continue
            # the normal death path credits a PG actor's resources back to
            # its bundle (_release_actor_resources); the snapshot carries
            # the debit, so mirror that credit here or the slot leaks
            pg_state = state.get("placement_groups", {}).get(
                a.creation_spec.placement_group_id or "")
            if pg_state is not None and pg_state["available"]:
                _add(pg_state["available"][0], a.resources)
        self.named_actors.update(state.get("named_actors", {}))
        self.kv.update(state.get("kv", {}))
        for pid, d in state.get("placement_groups", {}).items():
            self.placement_groups[pid] = _PlacementGroup(
                pg_id=pid, bundles=d["bundles"], strategy=d["strategy"],
                available=d["available"], bundle_nodes=d["bundle_nodes"],
                bandwidth=d.get("bandwidth", 0.0))
            # bundles reserved on the head itself are re-held now;
            # daemon-side bundles are re-held at re-registration
            for b, nid in zip(d["bundles"], d["bundle_nodes"]):
                if nid is None:
                    _sub(self.available, b)
        logger.warning(
            "restored head state: %d actors (%d named), %d kv keys, "
            "%d placement groups",
            len(self.actors), len(self.named_actors), len(self.kv),
            len(self.placement_groups))

    # ------------------------------------------------------------------
    # connection plumbing
    # ------------------------------------------------------------------

    def _accept_loop(self, listener=None, remote=False):
        listener = listener or self._listener
        while not self._shutdown:
            try:
                conn = listener.accept()
            except Exception:
                # One bad handshake (EOF mid-connect, wrong authkey ->
                # AuthenticationError) must not kill the accept loop; only
                # shutdown ends it.
                if self._shutdown:
                    return
                time.sleep(0.05)
                continue
            threading.Thread(target=self._serve_conn, args=(conn, remote),
                             daemon=True).start()

    def _serve_conn(self, conn, remote=False):
        try:
            reg = conn.recv()
        except (EOFError, OSError, TypeError):
            return
        if isinstance(reg, protocol.RegisterNode):
            self._serve_node_conn(conn, reg)
            return
        if not isinstance(reg, protocol.RegisterWorker):
            conn.close()
            return
        with self.lock:
            w = self.workers.get(reg.worker_id)
            if w is None:
                # Late registration of a worker we spawned, or an external
                # attach client (CLI / job driver): never dispatch to those.
                w = _WorkerConn(reg.worker_id, conn)
                if reg.worker_id.startswith("attach_"):
                    w.kind = "attach"
                    w.idle = False
                self.workers[reg.worker_id] = w
            else:
                w.conn = conn
            w.remote = remote
            w.alive = True
            w.reg_event.set()
            self.cv.notify_all()
        self._reader_loop(w)

    def _reader_loop(self, w: _WorkerConn):
        while True:
            try:
                msg = w.conn.recv()
            except (EOFError, OSError, TypeError):
                self._on_worker_death(w)
                return
            try:
                self._handle(w, msg)
            except Exception:
                logger.exception("error handling %r from %s", type(msg),
                                 w.worker_id)

    def _handle(self, w: _WorkerConn, msg):
        if isinstance(msg, protocol.TaskDone):
            self._on_task_done(w, msg)
        elif isinstance(msg, protocol.StackDumpReply):
            self._on_stack_reply(msg)
        elif isinstance(msg, protocol.PutRequest):
            # the putting worker certainly holds its new ObjectRef right
            # now, but its batched "hold" report may lag by up to the
            # flush period: record an implicit hold so a fast consumer
            # can't free the object in that window (idempotent with the
            # explicit hold; cleared by the worker's eventual release)
            self.ref_hold(msg.object_id, w.worker_id)
            desc = msg.desc
            if (desc.inline is not None
                    and len(desc.inline) > constants.INLINE_OBJECT_MAX_BYTES):
                # oversized inline put from a cross-machine client: land the
                # bytes in the head's store so they don't ride every
                # subsequent control message
                desc = self.store.put_serialized(msg.object_id, desc.inline)
                # the head's store owns the bytes now, so the free path
                # must delete them here, not at the putting client
                self.register_object(msg.object_id, desc, origin="driver")
                return
            self.register_object(msg.object_id, desc,
                                 origin=w.worker_id)
        elif isinstance(msg, protocol.GetRequest):
            threading.Thread(
                target=self._serve_get, args=(w, msg), daemon=True).start()
        elif isinstance(msg, protocol.WaitRequest):
            threading.Thread(
                target=self._serve_wait, args=(w, msg), daemon=True).start()
        elif isinstance(msg, protocol.SubmitRequest):
            if msg.seq is not None:
                self._on_pipelined_submit(w, msg)
            else:
                try:
                    self.submit(msg.spec, submitter=w)
                    w.send(protocol.SubmitReply(msg.req_id, ok=True))
                except Exception as e:
                    w.send(protocol.SubmitReply(msg.req_id, ok=False,
                                                error=repr(e)))
        elif isinstance(msg, protocol.ActorCallRequest):
            self._dispatch_control(w, msg)
        else:
            logger.warning("unknown message %r", type(msg))

    # Credit cadence for pipelined submissions: ack every quarter window
    # so the sender's ring stays shallow without an ack per task.
    _SUBMIT_CREDIT_EVERY = max(1, constants.SUBMIT_WINDOW // 4)

    def _on_pipelined_submit(self, w: _WorkerConn, msg) -> None:
        """Seq state machine for one worker's pipelined submit stream
        (runs on that worker's reader thread, the only writer of
        `sub_next`/`sub_nacked`). In-order: apply + periodic credit.
        Duplicate (replay overlap): drop and re-credit, so the sender
        prunes its ring and learns the watermark even when the original
        credit was lost. Gap: nack once with the expected seq; the
        sender replays from there in order."""
        seq = msg.seq
        if seq == w.sub_next:
            w.sub_next = seq + 1
            w.sub_nacked = False
            try:
                self.submit(msg.spec, submitter=w)
            except Exception as e:
                if not isinstance(e, RayTpuError):
                    e = RayTpuError(f"submit failed: {e!r}")
                self._store_error(msg.spec.return_ids, e, spec=msg.spec)
            if w.sub_next % self._SUBMIT_CREDIT_EVERY == 0:
                w.send(protocol.SubmitCredit(w.sub_next - 1))
        elif seq < w.sub_next:
            w.send(protocol.SubmitCredit(w.sub_next - 1))
        elif not w.sub_nacked:
            w.sub_nacked = True
            w.send(protocol.SubmitNack(w.sub_next))

    # Control verbs that may block for a long time (autoscaler-waiting
    # placement groups) must not run inline on a connection's reader
    # thread: that would stall every other message on the channel —
    # including, on a node channel, the TaskDone that frees the very
    # capacity being waited for.
    _BLOCKING_CONTROL = frozenset({"create_pg", "pubsub_poll", "stack"})

    def _dispatch_control(self, w, msg: protocol.ActorCallRequest):
        def run():
            try:
                result = self._control(msg.method, msg.payload, w)
                w.send(protocol.ActorCallReply(msg.req_id, result=result))
            except Exception as e:
                w.send(protocol.ActorCallReply(msg.req_id, error=repr(e)))
        if msg.method in self._BLOCKING_CONTROL:
            threading.Thread(target=run, daemon=True,
                             name=f"ctl-{msg.method}").start()
        else:
            run()

    # ------------------------------------------------------------------
    # node channels (head <-> HostDaemon; the GCS side of the split)
    # ------------------------------------------------------------------

    def _serve_node_conn(self, conn, reg: protocol.RegisterNode):
        node = _RemoteNode(
            node_id=reg.node_id, conn=conn, address=reg.address,
            pid=reg.pid, total=dict(reg.resources),
            available=dict(reg.resources),
            free_tpu_chips=list(range(reg.num_tpu_chips)),
            links=list(reg.link_groups or ()),
            worker_id="node:" + reg.node_id)
        with self.lock:
            old = self.nodes.get(reg.node_id)
            readopted_actors = set(reg.actors or {})
            if old is not None:
                node.proc = old.proc
                # seq dedupe spans registrations of the same daemon
                # process: the replayed ring must not re-apply messages
                # the old channel already delivered
                node.last_seq = old.last_seq
                # The superseded registration must never drive teardown:
                # if its reader later sees EOF (channel blip + reconnect),
                # _on_node_death would otherwise pass the alive-guard and
                # rip down the LIVE node's actors/objects by node_id.
                old.alive = False
                # Migrate still-running leases: the daemon process
                # survived the blip and will report their completion on
                # the NEW channel — _on_node_task_done must find them
                # here, and their resource holds must be re-debited from
                # this fresh (fully-available) registration so the
                # eventual release balances. PG-task CPU holds are
                # covered by the whole-bundle re-debit below; a creating
                # actor's hold is covered by the ready-actor re-attach
                # below iff the daemon re-reported it.
                # A lease ABSENT from reg.leases was swallowed by the
                # blip (or its outcome already delivered): the daemon
                # will never report it, so re-dispatch instead of
                # migrating a wait-forever entry.
                known = (None if reg.leases is None else set(reg.leases))
                requeue = []
                # SHARE the table (don't copy): an old-channel reader that
                # passed the alive/seq guard just before this supersede
                # applies its terminal against the same dict the new
                # channel serves — with a copy, that in-flight apply would
                # pop an orphaned table and the completion would be lost
                # on both channels (its seq is already marked seen).
                node.inflight = old.inflight
                for tid, t in list(node.inflight.items()):
                    spec = t.spec
                    if known is not None and tid not in known:
                        requeue.append(t)
                        del node.inflight[tid]
                        continue
                    if spec.actor_creation:
                        a = self.actors.get(spec.actor_id)
                        if (a is not None
                                and spec.actor_id not in readopted_actors
                                and not spec.placement_group_id):
                            _sub(node.available, a.resources)
                    elif spec.actor_id is None \
                            and not spec.placement_group_id:
                        _sub(node.available, spec.resources)
                    for chip in t.tpu_chips:
                        if chip in node.free_tpu_chips:
                            node.free_tpu_chips.remove(chip)
                for t in requeue:
                    # release credits the superseded object (discarded)
                    # for node-pool holds and the persistent PG bundles
                    # for PG holds — the new registration starts fully
                    # available, so the books balance either way
                    spec = t.spec
                    if spec.actor_creation:
                        a = self.actors.get(spec.actor_id)
                        if a is None or a.dead:
                            continue
                        self._release_actor_resources(a)
                        if t in a.inflight:
                            a.inflight.remove(t)
                        t.tpu_chips = []
                        t.node = None
                        self.task_events.requeued(spec)
                        self.pending.append(t)
                    elif spec.actor_id is not None:
                        a = self.actors.get(spec.actor_id)
                        if a is None or a.dead:
                            continue
                        if t in a.inflight:
                            a.inflight.remove(t)
                        t.node = None
                        a.queue.insert(0, t)
                    else:
                        self._release_task_resources(t)
                        t.node = None
                        self.task_events.requeued(spec)
                        self.pending.append(t)
            self.nodes[reg.node_id] = node
            # RE-registration after a head restart: re-attach the actors
            # still alive on that daemon and re-hold their resources +
            # any placement-group bundles reserved there (reference:
            # NotifyGCSRestart resource resync). Only actors the head
            # still maps to THIS node re-attach — if the head stayed up
            # and already restarted an actor elsewhere (the channel blip
            # case), the daemon's copy is stale and gets killed below,
            # never a split-brain rebind.
            stale_actors = []
            for aid in (reg.actors or {}):
                a = self.actors.get(aid)
                if a is not None and not a.dead and a.node == reg.node_id:
                    a.ready = True
                    a.pending_restart = False
                    if not a.creation_spec.placement_group_id:
                        # PG actors were debited from pg.available, which
                        # the snapshot preserved; the bundle re-debit
                        # below covers node.available for them
                        _sub(node.available, a.resources)
                    for chip in a.tpu_chips:
                        if chip in node.free_tpu_chips:
                            node.free_tpu_chips.remove(chip)
                else:
                    stale_actors.append(aid)
            for pg in self.placement_groups.values():
                for b, nid in zip(pg.bundles, pg.bundle_nodes):
                    if nid == reg.node_id:
                        _sub(node.available, b)
            self.cv.notify_all()
        for aid in stale_actors:
            node.send(protocol.KillActorOnNode(aid))
        # rebuild the object directory from the daemon's surviving store;
        # refcount state died with the old head, so these are pinned
        # (escaped) rather than risking a premature free
        for oid, desc in (reg.objects or {}).items():
            with self.lock:
                known = oid in self.directory
            if not known:
                self.ref_escape(oid)
                self.register_object(oid, desc,
                                     origin="node:" + reg.node_id)
        logger.info("node %s registered: %s", reg.node_id, reg.resources)
        self._schedule()
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError, TypeError):
                # TypeError: conn closed out from under us locally
                # (mp.connection raises it instead of OSError); the death
                # path must still run, never a silent reader crash
                try:
                    self._on_node_death(node)
                except Exception:
                    logger.exception("node death handling failed for %s",
                                     node.node_id)
                return
            try:
                if isinstance(msg, protocol.NodeSeq):
                    # Reliability envelope: drop blip-replay duplicates.
                    # Under the lock, and only while THIS registration is
                    # current: once superseded (alive=False, set under
                    # the same lock that copies last_seq into the new
                    # registration), late messages buffered on the old
                    # channel are discarded here and owned by the new
                    # channel's ring replay — otherwise a message applied
                    # after the last_seq snapshot would be applied twice.
                    with self.lock:
                        if not node.alive or msg.seq <= node.last_seq:
                            continue
                        node.last_seq = msg.seq
                    msg = msg.inner
                self._handle_node(node, msg)
            except Exception:
                logger.exception("error handling %r from node %s",
                                 type(msg), reg.node_id)

    def _handle_node(self, node: _RemoteNode, msg):
        if isinstance(msg, protocol.NodeTaskDone):
            self._on_node_task_done(node, msg)
        elif isinstance(msg, protocol.NodeTaskFailed):
            self._on_node_task_failed(node, msg)
        elif isinstance(msg, protocol.NodeActorDied):
            self._on_node_actor_died(node, msg)
        elif isinstance(msg, protocol.NodeWorkerBlocked):
            self._on_node_worker_blocked(node, msg)
        elif isinstance(msg, protocol.NodeWorkerGone):
            self._drop_ref_holder(msg.worker_id)
        elif isinstance(msg, protocol.StackDumpReply):
            self._on_stack_reply(msg)
        elif isinstance(msg, protocol.LogBatch):
            self._publish_logs(replace(msg, node_id=node.node_id))
        elif isinstance(msg, protocol.ObjectCopyNote):
            with self.lock:
                if msg.object_id in self.directory:
                    self.copy_nodes.setdefault(
                        msg.object_id, {})[msg.node_id] = msg.desc
        elif isinstance(msg, protocol.PullRequest):
            threading.Thread(target=self._serve_pull, args=(node, msg),
                             daemon=True).start()
        elif isinstance(msg, protocol.PullChunk):
            if msg.data is None:
                # raw body frame follows NOW on this channel (we're in
                # the node reader, synchronously before the next recv)
                self._pull_client.on_chunk_raw(msg, node.conn)
            else:
                self._pull_client.on_chunk(msg)
        elif isinstance(msg, protocol.PutRequest):
            if msg.origin:
                self.ref_hold(msg.object_id, msg.origin)
            self.register_object(msg.object_id, msg.desc,
                                 origin="node:" + node.node_id)
        elif isinstance(msg, protocol.GetRequest):
            threading.Thread(target=self._serve_get, args=(node, msg),
                             daemon=True).start()
        elif isinstance(msg, protocol.WaitRequest):
            threading.Thread(target=self._serve_wait, args=(node, msg),
                             daemon=True).start()
        elif isinstance(msg, protocol.SubmitRequest):
            # req_id < 0 marks a pipelined submission the daemon already
            # deduped and forwarded on the reliable (NodeSeq) channel:
            # apply it, never reply — failures become error objects
            # under the spec's return ids.
            try:
                self.submit(msg.spec,
                            submitter=msg.submitter or node.worker_id)
                if msg.req_id >= 0:
                    node.send(protocol.SubmitReply(msg.req_id, ok=True))
            except Exception as e:
                if msg.req_id >= 0:
                    node.send(protocol.SubmitReply(msg.req_id, ok=False,
                                                   error=repr(e)))
                else:
                    if not isinstance(e, RayTpuError):
                        e = RayTpuError(f"submit failed: {e!r}")
                    self._store_error(msg.spec.return_ids, e,
                                      spec=msg.spec)
        elif isinstance(msg, protocol.ActorCallRequest):
            self._dispatch_control(node, msg)
        else:
            logger.warning("unknown node message %r", type(msg))

    def _drop_ref_holder(self, holder: str) -> None:
        with self.lock:
            affected = [oid for oid, holders in self.ref_holders.items()
                        if holder in holders]
            for oid in affected:
                self.ref_holders[oid].discard(holder)
                self._maybe_free_locked(oid)

    # ------------------------------------------------------------------
    # control-plane RPCs (named actors, KV, kill, ...)
    # ------------------------------------------------------------------

    def _control(self, method: str, payload, w):
        if method == "get_actor":
            return self.get_named_actor(payload)
        if method == "kill_actor":
            return self.kill_actor(payload["actor_id"],
                                   no_restart=payload.get("no_restart", True))
        if method == "kv_put":
            ns, key, val = payload
            with self.lock:
                self.kv[(ns, key)] = val
            return True
        if method == "kv_get":
            ns, key = payload
            with self.lock:
                return self.kv.get((ns, key))
        if method == "kv_del":
            ns, key = payload
            with self.lock:
                return self.kv.pop((ns, key), None) is not None
        if method == "kv_list":
            ns, prefix = payload
            with self.lock:
                return [k for (n, k) in self.kv if n == ns
                        and k.startswith(prefix)]
        if method == "cluster_resources":
            with self.lock:
                out = dict(self.total_resources)
                for n in self.nodes.values():
                    if n.alive:
                        _add(out, n.total)
                return out
        if method == "available_resources":
            with self.lock:
                out = dict(self.available)
                for n in self.nodes.values():
                    if n.alive:
                        _add(out, n.available)
                return out
        if method == "node_address":
            with self.lock:
                n = self.nodes.get(payload)
                return n.address if n is not None and n.alive else None
        if method == "add_node":
            p = payload or {}
            return self.add_node(p.get("resources"),
                                 int(p.get("num_tpus", 0)))
        if method == "kill_node":
            p = payload or {}
            return self.kill_node(p["node_id"], force=p.get("force", True))
        if method == "attach_autoscaler":
            return self.attach_autoscaler(payload or {})
        if method == "autoscaler_status":
            return self.autoscaler_status()
        if method == "autoscaler_teardown":
            return self.autoscaler_teardown()
        if method == "stack":
            p = payload or {}
            return self.collect_stacks(p.get("worker_id"),
                                       float(p.get("timeout", 5.0)))
        if method == "pubsub_publish":
            return self.pubsub_publish(payload["channel"],
                                       payload["message"])
        if method == "pubsub_poll":
            t = float(payload.get("timeout", 30.0))
            # attach clients enforce a transport deadline
            # (ATTACH_CONTROL_TIMEOUT_S) that a full-length server poll
            # would race into a spurious ConnectionError on an idle
            # channel; cap their blocking window safely below it
            if w is not None and w.worker_id.startswith("attach_"):
                # max() guards an env-shrunk ATTACH_CONTROL_TIMEOUT_S
                # from turning long-polls into a busy loop
                t = min(t, max(1.0,
                               constants.ATTACH_CONTROL_TIMEOUT_S - 5.0))
            return self.pubsub_poll(payload["channel"],
                                    int(payload.get("after", 0)), t)
        if method == "log_subscribe":
            return self._log_subscribe(w)
        if method == "list_logs":
            return self._log_ring.sources()
        if method == "get_log":
            p = payload or {}
            return self._log_ring.tail(p["source"],
                                       int(p.get("lines", 200)))
        if method == "create_pg":
            return self.create_placement_group(**payload)
        if method == "remove_pg":
            return self.remove_placement_group(payload)
        if method == "cancel":
            return self.cancel(payload["object_id"], payload.get("force", False))
        if method == "list_tasks":
            return self.task_events.snapshot(
                filters=(payload or {}).get("filters"),
                limit=(payload or {}).get("limit", 10_000))
        if method == "summarize_tasks":
            return self.task_events.summary()
        if method == "timeline":
            # ONE merged chrome://tracing view: task events (cat="task")
            # interleaved with the telemetry plane — per-request engine
            # flight-recorder spans (cat="request") and application
            # tracing spans (cat="span"), including every span workers
            # drained up to this ring. All use epoch-µs timestamps, so
            # they line up on the same axis. Optional payload
            # {"trace": <trace_id>} narrows to one distributed trace.
            from ray_tpu.util import telemetry as _telemetry
            events = (self.task_events.chrome_trace()
                      + _telemetry.chrome_trace_events())
            trace = (payload or {}).get("trace")
            if trace:
                events = [e for e in events
                          if (e.get("args") or {}).get("trace_id") == trace]
            return events
        if method == "list_actors":
            with self.lock:
                return [{
                    "actor_id": a.actor_id,
                    "class_name": a.creation_spec.function_desc,
                    "name": a.name,
                    "state": ("DEAD" if a.dead else
                              "ALIVE" if a.ready else "PENDING_CREATION"),
                    "death_cause": a.death_cause or None,
                    "pending_tasks": len(a.queue),
                    "resources": dict(a.resources),
                    "worker_id": a.worker.worker_id if a.worker else None,
                } for a in itertools.islice(
                    self.actors.values(),
                    (payload or {}).get("limit", 10_000))]
        if method == "list_objects":
            with self.lock:
                return [{
                    "object_id": oid, "size_bytes": desc.size,
                    "store": ("inline" if desc.inline is not None else
                              "arena" if desc.arena else "file"),
                } for oid, desc in itertools.islice(
                    self.directory.items(),
                    (payload or {}).get("limit", 10_000))]
        if method == "list_workers":
            with self.lock:
                return [{
                    "worker_id": w.worker_id, "kind": w.kind,
                    "alive": w.alive, "idle": w.idle,
                    "current_task": (w.current.spec.task_id
                                     if w.current else None),
                    "pid": getattr(w.proc, "pid", None),
                } for w in itertools.islice(
                    self.workers.values(),
                    (payload or {}).get("limit", 10_000))]
        if method == "list_placement_groups":
            with self.lock:
                return [{
                    "placement_group_id": pg.pg_id,
                    "strategy": pg.strategy,
                    "bandwidth": pg.bandwidth,
                    "bundles": [dict(b) for b in pg.bundles],
                    "available": [dict(b) for b in pg.available],
                } for pg in itertools.islice(
                    self.placement_groups.values(),
                    (payload or {}).get("limit", 10_000))]
        if method == "list_nodes":
            with self.lock:
                out = [{
                    "node_id": self.node_id, "alive": True, "head": True,
                    "resources_total": dict(self.total_resources),
                    "resources_available": dict(self.available),
                    "session_dir": self.session_dir,
                }]
                out += [{
                    "node_id": n.node_id, "alive": n.alive, "head": False,
                    "resources_total": dict(n.total),
                    "resources_available": dict(n.available),
                    "inflight_tasks": len(n.inflight),
                } for n in self.nodes.values()]
                return out
        if method.startswith("job_"):
            jm = self._job_manager()
            if method == "job_submit":
                return jm.submit(payload["entrypoint"],
                                 job_id=payload.get("job_id"),
                                 runtime_env=payload.get("runtime_env"),
                                 metadata=payload.get("metadata"))
            if method == "job_status":
                return jm.status(payload)
            if method == "job_list":
                return jm.list()
            if method == "job_logs":
                return jm.logs(payload)
            if method == "job_stop":
                return jm.stop(payload)
        if method == "ref_update":
            # Events are applied in their original order: a worker that
            # releases and then re-holds an oid inside one flush window
            # must not have the hold applied first (which would net to
            # holder-removed and free an object with a live ref).
            holder = payload["holder"]
            with self.lock:
                for kind, oid in payload.get("events", ()):
                    if kind == "escape":
                        self.escaped_refs.add(oid)
                    elif kind == "hold":
                        self.ref_holders.setdefault(oid, set()).add(holder)
                    else:  # release
                        holders = self.ref_holders.get(oid)
                        if holders is not None:
                            holders.discard(holder)
                        self._maybe_free_locked(oid)
            return True
        if method == "push_metrics":
            wid, snap = payload
            with self.lock:
                self.metrics_by_proc[wid] = snap
            return True
        if method == "push_spans":
            # worker→head span drain (piggybacked on the metrics flush)
            _wid, spans = payload
            from ray_tpu.util import tracing as _tracing
            return _tracing.ingest(spans)
        if method == "stage_breakdown":
            return self.task_events.stage_breakdown()
        if method == "enable_tracing":
            return self.enable_tracing_broadcast()
        if method == "dashboard_snapshot":
            return self.dashboard_snapshot()
        if method == "free_objects":
            return self.free_objects(payload or [])
        if method == "get_metrics":
            from ray_tpu.util import metrics as _metrics
            with self.lock:
                snaps = list(self.metrics_by_proc.values())
            # driver-process metrics participate directly
            snaps.append(_metrics.snapshot())
            return _metrics.merge_snapshots(snaps)
        if method == "actor_state":
            with self.lock:
                a = self.actors.get(payload)
                if a is None:
                    return None
                return {"ready": a.ready, "dead": a.dead,
                        "cause": a.death_cause}
        raise ValueError(f"unknown control method {method}")

    def enable_tracing_broadcast(self) -> bool:
        """Turn span recording on in every live process of the session:
        this one, the head's workers, and remote daemons (which fan the
        protocol.SetTracing on to their workers). Future spawns inherit
        the RAY_TPU_TRACING env var instead."""
        from ray_tpu.util import tracing as _tracing
        _tracing._enable_local()
        msg = protocol.SetTracing(enabled=True)
        with self.lock:
            workers = [w for w in self.workers.values() if w.alive]
            nodes = [n for n in self.nodes.values() if n.alive]
        for w in workers:
            w.send(msg)
        for n in nodes:
            n.send(msg)
        return True

    # ------------------------------------------------------------------
    # object directory
    # ------------------------------------------------------------------

    def _job_manager(self):
        if not hasattr(self, "_jobs"):
            from ray_tpu.job_submission import JobManager
            with self.lock:
                if not hasattr(self, "_jobs"):
                    self._jobs = JobManager(
                        os.path.join(self.session_dir, "jobs"))
        return self._jobs

    # ------------------------------------------------------------------
    # reference counting
    # ------------------------------------------------------------------

    def ref_hold(self, oid: str, holder: str) -> None:
        with self.lock:
            self.ref_holders.setdefault(oid, set()).add(holder)

    def ref_release(self, oid: str, holder: str) -> None:
        with self.lock:
            holders = self.ref_holders.get(oid)
            if holders is not None:
                holders.discard(holder)
            self._maybe_free_locked(oid)

    def ref_escape(self, oid: str) -> None:
        with self.lock:
            self.escaped_refs.add(oid)

    def free_objects(self, oids) -> int:
        """Explicit unconditional release (reference:
        `_private/internal_api.py free()`): drops the escape pin and all
        holder records so the normal free path runs. The caller asserts
        nothing will read these refs again — the API exists for
        bulk-intermediate lifecycles (shuffle shards) whose nested refs
        otherwise escape to session lifetime."""
        n = 0
        with self.lock:
            for oid in oids:
                self.escaped_refs.discard(oid)
                self.ref_holders.pop(oid, None)
                if oid in self.directory:
                    n += 1
                self._maybe_free_locked(oid)
        return n

    def _pin_task_args_locked(self, spec) -> None:
        for kind, v in list(spec.args) + list(spec.kwargs.values()):
            if kind == "ref":
                self.task_arg_refs[v] = self.task_arg_refs.get(v, 0) + 1

    def _release_task_args(self, spec) -> None:
        """Exactly-once per task: its ref args are no longer needed by
        this consumer. Called from every terminal path."""
        with self.lock:
            if spec.task_id in self._args_released:
                return
            self._args_released[spec.task_id] = True
            while len(self._args_released) > constants.ARGS_RELEASED_CAP:
                self._args_released.popitem(last=False)
            for kind, v in list(spec.args) + list(spec.kwargs.values()):
                if kind == "ref":
                    n = self.task_arg_refs.get(v, 0) - 1
                    if n <= 0:
                        self.task_arg_refs.pop(v, None)
                        self._maybe_free_locked(v)
                    else:
                        self.task_arg_refs[v] = n

    def _maybe_free_locked(self, oid: str) -> None:
        """Free the object if nothing can reach it anymore (caller holds
        the lock)."""
        if oid in self.escaped_refs:
            return
        if self.ref_holders.get(oid):
            return
        if self.task_arg_refs.get(oid, 0) > 0:
            return
        desc = self.directory.get(oid)
        if desc is None:
            # released before the producing task finished: free on arrival
            self.dead_pending.add(oid)
            return
        del self.directory[oid]
        self.ref_holders.pop(oid, None)
        self.dead_pending.discard(oid)
        self.freed_refs[oid] = True
        self._poke_get_waiters(oid)
        while len(self.freed_refs) > constants.FREED_REFS_CAP:
            self.freed_refs.popitem(last=False)
        origin = self.obj_origin.pop(oid, "driver")
        dropped = self.lineage.pop(oid, None)
        if dropped is not None:
            self._lineage_bytes -= _lineage_size(dropped)
        self.reconstructions.pop(oid, None)
        # head-local cached copy of a remote object
        lc = self.local_copies.pop(oid, None)
        if lc is not None:
            self.store.delete(lc)
        copies = self.copy_nodes.pop(oid, ())
        if desc.node is None:
            self.store.delete(desc)
            # every LOCAL worker that read the object holds a pinned
            # arena view (or a cached mmap for file-backed descs); the
            # block's offset can't recycle until they all drop it —
            # origin-only fanout leaked reader pins and grew the arena
            # cold forever. Sends ride the outbox thread: O(workers)
            # blocking writes under self.lock would stall the head.
            targets = [w for w in self.workers.values()
                       if w.alive and not w.remote and w.kind != "attach"]
            if targets:
                self._free_outbox.append(
                    (targets, protocol.FreeObject(oid, desc)))
                self._free_event.set()
        else:
            node = self.nodes.get(desc.node)
            if node is not None and node.alive:
                node.send(protocol.FreeObjectNode(oid))
        for nid in copies:
            if nid == desc.node:
                continue
            n2 = self.nodes.get(nid)
            if n2 is not None and n2.alive:
                n2.send(protocol.FreeObjectNode(oid))
        self.cv.notify_all()   # wake racing gets so they fail fast

    def _register_locked(self, object_id: str, desc: Descriptor,
                         origin: str):
        """Directory insert + origin + dead_pending + dependent-task wakeup
        (single implementation for put, task returns, and error stores).
        Caller holds the lock; returns True if tasks were unblocked."""
        self.directory[object_id] = desc
        self.obj_origin[object_id] = origin
        self.lost_objects.pop(object_id, None)
        self.reconstructing.discard(object_id)
        if object_id in self.dead_pending:
            self.dead_pending.discard(object_id)
            self._maybe_free_locked(object_id)
        waiting = self.obj_waiting_tasks.pop(object_id, ())
        for t in waiting:
            t.deps.discard(object_id)
            if not t.deps:
                # last dependency resolved: the task is now runnable
                self.task_events.queued(t.spec.task_id)
        for waiter in self._get_waiters.pop(object_id, ()):
            waiter["n"] -= 1
            if waiter["n"] <= 0:
                ev = waiter.get("ev")
                if ev is not None:
                    ev.set()
        self.cv.notify_all()
        return bool(waiting)

    def _free_fanout_loop(self):
        while not self._shutdown:
            self._free_event.wait(timeout=1.0)
            self._free_event.clear()
            while self._free_outbox:
                try:
                    targets, msg = self._free_outbox.popleft()
                except IndexError:
                    break
                for w in targets:
                    w.send(msg)     # safe_send: dead workers are a no-op

    def _poke_get_waiters(self, oid: str) -> None:
        """Flag blocked get()s that `oid` was freed/lost so they re-check
        and raise promptly instead of waiting for a 1s timeout tick (which
        registration wakeups can starve indefinitely). Caller holds lock."""
        for waiter in self._get_waiters.get(oid, ()):
            waiter["dirty"] = True
            ev = waiter.get("ev")
            if ev is not None:
                ev.set()
        self.cv.notify_all()

    def register_object(self, object_id: str, desc: Descriptor,
                        origin: str = "driver"):
        with self.lock:
            waiting = self._register_locked(object_id, desc, origin)
        if waiting:
            self._schedule()

    def put_value(self, value) -> str:
        oid = ids.new_object_id()
        desc = self.store.put(oid, value)
        # Owner fast path: a FRESH object id cannot have get/wait
        # waiters, dependent tasks, or lost/reconstructing/dead-pending
        # state (its ObjectRef does not exist until this returns), so a
        # bare directory insert replaces the full registration sweep —
        # no waiter walk, no notify_all herd, nothing to schedule.
        with self.lock:
            self.directory[oid] = desc
            self.obj_origin[oid] = "driver"
        return oid

    def get_locations(self, object_ids, timeout=None, localize=True) -> dict:
        """Block until every id has a descriptor. With `localize` (the
        default), remote descriptors are pulled into the head's store first
        so the returned locations are all readable here. Blocking rides a
        COUNTER waiter that registrations decrement — a get() over 100k
        refs costs O(ids), not O(ids) per wakeup."""
        deadline = None if timeout is None else time.monotonic() + timeout
        # Fast path: everything already registered (the common shape for
        # put-then-get and for draining completed results) — one dict
        # sweep under the lock, no waiter bookkeeping.
        with self.cv:
            directory = self.directory
            locs = {}
            for o in object_ids:
                d = directory.get(o)
                if d is None:
                    locs = None
                    break
                locs[o] = d
        if locs is not None:
            self.task_events.mark_got(object_ids)
            if localize:
                locs = self._localize(locs, deadline=deadline)
            return locs
        while True:
            with self.lock:
                missing = [o for o in object_ids
                           if o not in self.directory]
                freed = [o for o in missing if o in self.freed_refs]
                if freed:
                    raise ObjectFreedError(
                        f"object {freed[0]} was freed by reference "
                        "counting before this get()")
                lost = [o for o in missing if o in self.lost_objects]
                if lost:
                    raise ObjectLostError(
                        f"object {lost[0]} was lost: "
                        f"{self.lost_objects[lost[0]]}")
                if not missing:
                    locs = {o: self.directory[o] for o in object_ids}
                    break
                # Private wakeup channel: registrations decrement the
                # counter and set the event only when it reaches ZERO
                # (free/loss paths set `dirty` + the event), so the
                # per-completion notify herd never lands on a blocked
                # get — draining N results wakes this thread once, not
                # once per TaskDone. The 1s tick stays as the
                # belt-and-braces re-check path.
                waiter = {"n": len(missing), "ev": threading.Event()}
                for o in missing:
                    self._get_waiters.setdefault(o, []).append(waiter)
            ev = waiter["ev"]
            try:
                while True:
                    if deadline is not None:
                        rem = deadline - time.monotonic()
                        if rem <= 0:
                            raise GetTimeoutError(
                                f"get() timed out waiting for "
                                f"{missing[:3]}...")
                        notified = ev.wait(min(rem, 1.0))
                    else:
                        notified = ev.wait(1.0)
                    with self.lock:
                        if waiter["n"] <= 0 or waiter.get("dirty"):
                            break
                        if not notified and any(
                                o in self.freed_refs
                                or o in self.lost_objects
                                for o in missing
                                if o not in self.directory):
                            break
            finally:
                with self.lock:
                    for o in missing:
                        lst = self._get_waiters.get(o)
                        if lst is not None:
                            try:
                                lst.remove(waiter)
                            except ValueError:
                                pass
                            if not lst:
                                self._get_waiters.pop(o, None)
            # loop back: re-verify everything under the lock (an object
            # may have been freed between registration and this read —
            # the outer while handles it)
        self.task_events.mark_got(object_ids)   # close the `got` stage
        if localize:
            locs = self._localize(locs, deadline=deadline)
        return locs

    def wait_objects(self, object_ids, num_returns, timeout):
        deadline = None if timeout is None else time.monotonic() + timeout
        with self.cv:
            while True:
                ready = [o for o in object_ids if o in self.directory]
                freed = [o for o in object_ids
                         if o not in self.directory and o in self.freed_refs]
                if freed:
                    from ray_tpu.exceptions import ObjectFreedError
                    raise ObjectFreedError(
                        f"object {freed[0]} was freed by reference "
                        "counting before this wait()")
                if len(ready) >= num_returns:
                    break
                if deadline is not None:
                    rem = deadline - time.monotonic()
                    if rem <= 0:
                        break
                    self.cv.wait(min(rem, 1.0))
                else:
                    self.cv.wait(1.0)
            ready_set = set(ready[:max(num_returns, 0)] if len(ready) >
                            num_returns else ready)
            ready_list = [o for o in object_ids if o in ready_set]
            not_ready = [o for o in object_ids if o not in ready_set]
            return ready_list, not_ready

    def _serve_get(self, w, msg: protocol.GetRequest):
        # Release the blocked worker's resources so nested tasks can run
        # (the reference releases the worker's lease while it blocks in get).
        with self.lock:
            if w.current is not None and not w.released:
                held = dict(w.current.spec.resources)
                if held:
                    _add(self.available, held)
                    w.released = held
        try:
            # Daemons localize to their own store themselves; local workers
            # need descriptors readable in the head's store.
            locs = self.get_locations(msg.object_ids, msg.timeout,
                                      localize=(w.kind != "node"))
            if w.remote:
                # cross-machine client: no shared memory with this host, so
                # ship the serialized envelopes inside the reply itself
                locs = {oid: (d if d.inline is not None else replace(
                    d, inline=self.store.raw_bytes(d), arena=False,
                    path=None)) for oid, d in locs.items()}
            reply = protocol.GetReply(msg.req_id, locs)
        except GetTimeoutError:
            reply = protocol.GetReply(msg.req_id, {}, timed_out=True)
        except (ObjectFreedError, ObjectLostError, OSError) as e:
            # OSError: a path-backed object freed/moved between the
            # directory read and raw_bytes for a remote client — must
            # still answer or the client's get() hangs forever
            name = type(e).__name__ if not isinstance(e, OSError) \
                else "ObjectLostError"
            reply = protocol.GetReply(msg.req_id, {},
                                      error=f"{name}: {e}")
        with self.lock:
            if w.released:
                _sub(self.available, w.released)  # may dip below zero briefly
                w.released = {}
        w.send(reply)
        self._schedule()

    def _serve_wait(self, w, msg: protocol.WaitRequest):
        ready, not_ready = self.wait_objects(
            msg.object_ids, msg.num_returns, msg.timeout)
        w.send(protocol.WaitReply(msg.req_id, ready, not_ready))

    # ------------------------------------------------------------------
    # cross-node object data plane (object_manager.h:117 equivalent)
    # ------------------------------------------------------------------

    def _localize(self, locs: dict, deadline: float | None = None) -> dict:
        """Return locations readable in the head's store, pulling remote
        primaries into a head-local cached copy as needed. `deadline`
        (monotonic) bounds the whole pass: a caller's get(timeout=) covers
        the transfer, not just the directory wait."""
        out = dict(locs)
        for oid, desc in locs.items():
            if desc.inline is not None or desc.node is None:
                continue
            if deadline is not None and time.monotonic() >= deadline:
                raise GetTimeoutError(
                    f"get() timed out pulling {oid} to the head")
            out[oid] = self._pull_to_head(oid, desc, deadline)
        return out

    def _pull_to_head(self, oid: str, desc: Descriptor,
                      deadline: float | None = None) -> Descriptor:
        def budget(default: float) -> float:
            if deadline is None:
                return default
            return max(min(default, deadline - time.monotonic()), 0.01)

        with self.cv:
            while True:
                lc = self.local_copies.get(oid)
                if lc is not None:
                    return lc
                if oid not in self._head_pulling:
                    self._head_pulling.add(oid)
                    break
                if deadline is not None and time.monotonic() >= deadline:
                    raise GetTimeoutError(
                        f"get() timed out awaiting pull of {oid}")
                self.cv.wait(0.2)
        try:
            for _attempt in range(constants.PULL_RETRY_ATTEMPTS):
                if deadline is not None and time.monotonic() >= deadline:
                    raise GetTimeoutError(
                        f"get() timed out pulling {oid}")
                try:
                    with self.lock:
                        node = self.nodes.get(desc.node)
                    if node is None or not node.alive:
                        raise ObjectLostError(
                            f"object {oid} lives on dead node {desc.node}")
                    seal_box = {}

                    def alloc(total: int, _oid=oid):
                        buf, seal = self.store.create_serialized(
                            _oid, total)
                        if buf is not None:
                            seal_box["seal"] = seal
                        return buf

                    # failure-path release belongs to the PullClient (a
                    # late frame may still be landing in the buffer)
                    payload, in_arena = self._pull_bytes(
                        node, oid, alloc=alloc,
                        cleanup=lambda _oid=oid:
                            self.store.abort_create(_oid),
                        timeout=budget(constants.PULL_TIMEOUT_S))
                    if in_arena:
                        local = seal_box["seal"]()
                    else:
                        local = self.store.put_serialized(oid, payload)
                    with self.lock:
                        # freed while we pulled? drop the stray copy now
                        if oid in self.freed_refs:
                            self.store.delete(local)
                            raise ObjectFreedError(
                                f"object {oid} was freed during pull")
                        self.local_copies[oid] = local
                    return local
                except ObjectLostError:
                    if (deadline is not None
                            and time.monotonic() >= deadline):
                        with self.lock:
                            n = self.nodes.get(desc.node)
                            source_alive = n is not None and n.alive
                        if source_alive:
                            # the caller's budget expired mid-transfer of
                            # a healthy object: that's a timeout, not loss
                            raise GetTimeoutError(
                                f"get() timed out pulling {oid}")
                    # the source died mid-pull: wait for a promoted copy
                    # or a reconstructed re-registration, then retry
                    desc = self._await_fresh_desc(
                        oid, desc,
                        timeout=budget(constants.OBJECT_REPLACEMENT_WAIT_S))
                    if desc.node is None or desc.inline is not None:
                        return desc     # now head-local (or error value)
            raise ObjectLostError(f"pull of {oid} kept failing")
        finally:
            with self.cv:
                self._head_pulling.discard(oid)
                self.cv.notify_all()

    def _await_fresh_desc(self, oid: str, stale: Descriptor,
                          timeout: float = 60.0) -> Descriptor:
        """Block until the directory carries a different descriptor for
        `oid` (promotion to a surviving copy, or lineage reconstruction);
        raise ObjectLostError if it is terminally lost."""
        deadline = time.monotonic() + timeout
        with self.cv:
            while True:
                if oid in self.lost_objects:
                    raise ObjectLostError(
                        f"object {oid} lost: {self.lost_objects[oid]}")
                d = self.directory.get(oid)
                if d is not None and d != stale:
                    return d
                rem = deadline - time.monotonic()
                if rem <= 0:
                    raise ObjectLostError(
                        f"object {oid} unavailable: source died and no "
                        "replacement appeared")
                self.cv.wait(min(rem, 0.5))

    def _pull_bytes(self, node: _RemoteNode, oid: str,
                    timeout: float | None = None, alloc=None,
                    cleanup=None):
        return self._pull_client.pull_into(
            node.send, oid, timeout=timeout, alloc=alloc, cleanup=cleanup,
            abort_check=lambda: None if node.alive
            else f"hit dead node {node.node_id}")

    def _serve_pull(self, node: _RemoteNode, msg: protocol.PullRequest):
        """A daemon asked for an object's bytes held by the head."""
        from ray_tpu._private.pull_plane import serve_pull
        with self.lock:
            desc = self.directory.get(msg.object_id)
            if desc is not None and desc.node is not None:
                desc = self.local_copies.get(msg.object_id)
        if desc is None:
            serve_pull((node.conn, node.send_lock), msg, None)
            return
        try:
            payload = self.store.raw_view(desc)
        except (ObjectLostError, OSError) as e:
            payload = e
        serve_pull((node.conn, node.send_lock), msg, payload)

    # ------------------------------------------------------------------
    # leased-task lifecycle + node failure (raylet-side events)
    # ------------------------------------------------------------------

    def _on_node_task_done(self, node: _RemoteNode, msg: protocol.NodeTaskDone):
        if msg.spans:
            # merge the remote host's drained spans (relayed by its daemon)
            from ray_tpu.util import tracing as _tracing
            _tracing.ingest(msg.spans)
        with self.lock:
            t = node.inflight.pop(msg.task_id, None)
            if t is None:
                logger.warning("NodeTaskDone for unknown task %s",
                               msg.task_id)
                return
            spec = t.spec
            a = self.actors.get(spec.actor_id) if spec.actor_id else None
            if (msg.error and t.retry_exceptions and t.retries_left > 0
                    and not spec.actor_creation):
                t.retries_left -= 1
                self.task_events.requeued(spec)
                if a is None:
                    self._release_task_resources(t)
                    t.node = None
                    self.pending.append(t)
                else:
                    if t in a.inflight:
                        a.inflight.remove(t)
                    a.queue.insert(0, t)
            else:
                self.task_events.finished(
                    msg.task_id,
                    error="application_error" if msg.error else None,
                    exec_start_ts=msg.exec_start_ts,
                    exec_end_ts=msg.exec_end_ts,
                    return_ids=spec.return_ids)
                self._release_task_args(spec)
                for oid, desc in zip(spec.return_ids, msg.return_descs):
                    self._register_locked(oid, desc,
                                          origin="node:" + node.node_id)
                self.cv.notify_all()
                if a is not None:
                    if t in a.inflight:
                        a.inflight.remove(t)
                    if spec.actor_creation:
                        if msg.error:
                            a.dead = True
                            a.death_cause = "constructor raised"
                            self._release_actor_resources(a)
                            failed, a.queue = a.queue, []
                            for qt in failed:
                                self._store_error(
                                    qt.spec.return_ids,
                                    ActorDiedError(
                                        f"actor {a.actor_id} constructor "
                                        "raised"),
                                    spec=qt.spec)
                        else:
                            a.ready = True
                else:
                    self._release_task_resources(t)
                    t.node = None
        self._schedule()

    def _on_node_task_failed(self, node: _RemoteNode,
                             msg: protocol.NodeTaskFailed):
        """A leased task's worker died on the node (actor-worker deaths
        arrive as NodeActorDied instead)."""
        with self.lock:
            t = node.inflight.pop(msg.task_id, None)
            if t is None:
                return
            spec = t.spec
            if spec.actor_creation or spec.actor_id is not None:
                # actor path (resources incl.) is driven by NodeActorDied
                retry = False
                t = None
            elif (msg.error.startswith("dependency pull failed")
                  and t.dep_failures < 10):
                # not the task's fault: requeue WITHOUT consuming a retry,
                # re-blocking on args whose directory entry is gone (they
                # may be reconstructing; if terminally lost, the stored
                # ObjectLostError value fails the task through normal dep
                # poisoning on the next dispatch). dep_failures caps a
                # persistent pull failure with an intact directory entry —
                # otherwise this would hot-loop forever.
                t.dep_failures += 1
                self._release_task_resources(t)
                t.node = None
                for kind, v in (list(spec.args)
                                + list(spec.kwargs.values())):
                    if kind == "ref" and v not in self.directory:
                        t.deps.add(v)
                        self.obj_waiting_tasks.setdefault(v, []).append(t)
                self.pending.append(t)
                self.task_events.requeued(spec)
                retry = True
            else:
                self._release_task_resources(t)
                t.node = None
                if t.retries_left > 0:
                    t.retries_left -= 1
                    self.pending.append(t)
                    self.task_events.requeued(spec)
                    retry = True
                else:
                    retry = False
        if t is not None and not retry:
            self._store_error(
                t.spec.return_ids,
                WorkerCrashedError(
                    f"worker died on {node.node_id} while running "
                    f"{t.spec.function_desc}: {msg.error}"),
                spec=t.spec)
        self._schedule()

    def _on_node_actor_died(self, node: _RemoteNode,
                            msg: protocol.NodeActorDied):
        with self.lock:
            a = self.actors.get(msg.actor_id)
            if a is None:
                return
            if msg.cause and not a.death_cause:
                a.death_cause = msg.cause
            for tid in [tid for tid, t in node.inflight.items()
                        if t.spec.actor_id == msg.actor_id]:
                node.inflight.pop(tid)
        self._on_actor_death(a)
        with self.lock:
            rid = a.creation_spec.return_ids[0]
            # an actor that died terminally WITHOUT ever becoming ready
            # must still resolve its creation ref (wait_for_actor_ready
            # would otherwise hang; the local path's _fail_actor does this)
            stranded = (a.dead and rid not in self.directory
                        and rid not in self.freed_refs)
        if stranded:
            self._store_error(
                [rid], ActorDiedError(
                    f"actor {a.actor_id} died: "
                    f"{a.death_cause or msg.cause or 'unknown'}"))

    def _on_node_worker_blocked(self, node: _RemoteNode,
                                msg: protocol.NodeWorkerBlocked):
        with self.lock:
            t = node.inflight.get(msg.task_id)
            if t is None:
                return
            if t.spec.placement_group_id:
                # PG tasks debited a bundle, not node.available; releasing
                # into the node pool would leak the bundle slot on death
                return
            held = dict(t.spec.resources)
            if msg.blocked and not t.node_released:
                t.node_released = True
                if held:
                    _add(node.available, held)
            elif not msg.blocked and t.node_released:
                t.node_released = False
                if held:
                    _sub(node.available, held)
        self._schedule()

    def _on_node_death(self, node: _RemoteNode):
        to_fail = []
        dead_actors = []
        lost_oids = []
        rebuild_oids = []
        with self.lock:
            if not node.alive:
                return
            if self.nodes.get(node.node_id) is not node:
                # a newer registration has replaced this object; only the
                # current one may tear down node state
                node.alive = False
                return
            node.alive = False
            logger.warning("node %s died", node.node_id)
            inflight, node.inflight = dict(node.inflight), {}
            dead_actors = [a for a in self.actors.values()
                           if a.node == node.node_id and not a.dead]
            dead_actor_ids = {a.actor_id for a in dead_actors}
            for t in inflight.values():
                if t.spec.actor_creation or t.spec.actor_id is not None:
                    continue    # handled via the actor restart path
                self._release_task_resources(t)
                t.node = None
                if t.retries_left > 0:
                    t.retries_left -= 1
                    self.pending.append(t)
                    self.task_events.requeued(t.spec)
                else:
                    to_fail.append(t)
            # drop ref-holders owned by the dead node's workers wholesale:
            # their ids are unknown here, but every holder whose holds came
            # through this node died with it — conservative: leave them;
            # the daemon reported NodeWorkerGone for orderly deaths, and
            # leaked holds from a killed node only delay frees.
            # Objects whose primary copy lived on the dead node: promote a
            # surviving copy (head cache first, then another node), else
            # mark lost (object_recovery_manager.h:41 recovery-from-copy).
            for oid, desc in list(self.directory.items()):
                if desc.node != node.node_id:
                    continue
                lc = self.local_copies.get(oid)
                if lc is not None:
                    self.directory[oid] = lc
                    self.obj_origin[oid] = "driver"
                    continue
                survivors = [
                    (nid, d) for nid, d in self.copy_nodes.get(
                        oid, {}).items()
                    if nid != node.node_id and d is not None
                    and (n2 := self.nodes.get(nid)) is not None and n2.alive]
                if survivors:
                    # promote the survivor's own descriptor — its backing
                    # (arena vs file) can differ from the dead primary's
                    nid, d = survivors[0]
                    self.directory[oid] = d
                    self.obj_origin[oid] = "node:" + nid
                    continue
                del self.directory[oid]
                self.obj_origin.pop(oid, None)
                if (oid in self.lineage
                        and self.reconstructions.get(oid, 0)
                        < constants.MAX_OBJECT_RECONSTRUCTIONS):
                    # rebuildable: leave a directory hole (readers keep
                    # waiting) and resubmit the producing task below
                    rebuild_oids.append(oid)
                else:
                    self.lost_objects[oid] = f"node {node.node_id} died"
                    self._poke_get_waiters(oid)
                    lost_oids.append(oid)
            for oid, copies in list(self.copy_nodes.items()):
                copies.pop(node.node_id, None)
            if rebuild_oids:
                # tasks whose deps were already satisfied would otherwise
                # dispatch into the directory hole and fail; re-block them
                # until the reconstructed object re-registers
                rb = set(rebuild_oids)

                def _reblock(t):
                    for kind, v in (list(t.spec.args)
                                    + list(t.spec.kwargs.values())):
                        if kind == "ref" and v in rb and v not in t.deps:
                            t.deps.add(v)
                            self.obj_waiting_tasks.setdefault(
                                v, []).append(t)
                for t in self.pending:
                    _reblock(t)
                for a2 in self.actors.values():
                    for t in a2.queue:
                        _reblock(t)
            # placement-group bundles reserved on the node can no longer
            # host anything (the reference reschedules bundles; v1 marks
            # them unavailable so dispatch skips them)
            for pg in self.placement_groups.values():
                for i, nid in enumerate(pg.bundle_nodes):
                    if nid == node.node_id:
                        pg.available[i] = {}
            self.cv.notify_all()    # wake gets blocked on now-lost objects
        self._pull_client.abort_all()    # wake pulls targeting the node
        # Every surviving reference to a lost object now resolves to an
        # ObjectLostError *value*: gets raise it, and tasks that consume
        # the object fail with it through the normal dep-poisoning path —
        # no pending task can reach a directory hole and wedge dispatch.
        # (Lineage reconstruction will replace this with resubmission.)
        for oid in lost_oids:
            self._store_error(
                [oid],
                ObjectLostError(
                    f"object {oid} lost: node {node.node_id} died and no "
                    "other copy exists"))
        for oid in rebuild_oids:
            self._reconstruct(oid)
        for a in dead_actors:
            self._on_actor_death(a)
        for t in to_fail:
            self._store_error(
                t.spec.return_ids,
                WorkerCrashedError(
                    f"node {node.node_id} died while running "
                    f"{t.spec.function_desc}"),
                spec=t.spec)
        self._schedule()

    # ------------------------------------------------------------------
    # object spilling (LocalObjectManager equivalent,
    # local_object_manager.h:110): above the arena high-water mark, sealed
    # head-primary objects move to disk; their directory descriptor flips
    # to file-backed, and the arena block is released (origin worker drops
    # its owner pin via FreeObject).
    # ------------------------------------------------------------------

    def _spill_loop(self):
        while not self._shutdown:
            time.sleep(constants.SPILL_PASS_INTERVAL_S)
            try:
                self._maybe_spill()
            except Exception:
                logger.exception("spill pass failed")

    def _maybe_spill(self):
        from ray_tpu._private.spill import run_spill_pass

        def candidates():
            with self.lock:
                return [(oid, desc) for oid, desc in self.directory.items()
                        if desc.node is None and desc.arena]

        def try_swap(oid, old, new):
            with self.lock:
                if self.directory.get(oid) != old:
                    return False
                self.directory[oid] = new
                origin = self.obj_origin.get(oid, "driver")
                self.obj_origin[oid] = "driver"
                if origin == "driver" or origin.startswith("node:"):
                    return None
                return self.workers.get(origin)

        run_spill_pass(self.store, candidates, try_swap)

    def _reconstruct(self, oid: str) -> bool:
        """Rebuild a lost task-produced object by re-executing its
        producing task (lineage resubmission, object_recovery_manager.h:41
        + TaskResubmissionInterface, task_manager.h:173). Walks the lost
        lineage chain iteratively (a long x = f.remote(x) chain must not
        overflow the Python stack). Returns False if the object cannot be
        rebuilt (an ObjectLostError value is stored instead)."""
        plan: list = []         # clones, discovery order (parents first)
        failed: list = []       # (oid, cause)
        stack = [oid]
        seen: set = set()
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            with self.lock:
                if cur in self.directory or cur in self.reconstructing:
                    continue    # present, or a resubmission is in flight
                spec = self.lineage.get(cur)
                n = self.reconstructions.get(cur, 0)
                if (spec is None
                        or n >= constants.MAX_OBJECT_RECONSTRUCTIONS):
                    failed.append((cur, "no lineage" if spec is None
                                   else f"exceeded {n} reconstructions"))
                    self.lost_objects[cur] = failed[-1][1]
                    self._poke_get_waiters(cur)
                    continue
                # one resubmit rebuilds ALL the task's returns
                for rid in spec.return_ids:
                    self.reconstructions[rid] = max(
                        self.reconstructions.get(rid, 0), n + 1)
                    self.reconstructing.add(rid)
                # fresh task_id so event records and the exactly-once
                # arg-release guard treat this as a new execution
                clone = protocol.TaskSpec(
                    **{**spec.__dict__, "task_id": ids.new_task_id()})
                missing = [
                    v for kind, v in (list(clone.args)
                                      + list(clone.kwargs.values()))
                    if kind == "ref" and v not in self.directory]
            plan.append(clone)
            stack.extend(missing)
        for lost_oid, cause in failed:
            self._store_error(
                [lost_oid],
                ObjectLostError(f"object {lost_oid} lost: {cause}"))
        for clone in reversed(plan):    # inputs resubmit first
            logger.warning("reconstructing %s by re-running %s",
                           clone.return_ids[0], clone.function_desc)
            self.submit(clone)
        return bool(plan) and not any(f[0] == oid for f in failed)

    # ------------------------------------------------------------------
    # node management (add/kill; the Cluster fixture + autoscaler seam)
    # ------------------------------------------------------------------

    def add_node(self, resources: dict | None = None,
                 num_tpus: int = 0) -> str:
        """Spawn a HostDaemon subprocess for a new (possibly fake-resource)
        node and wait for it to register — the one-host multi-daemon
        fixture of the reference (python/ray/cluster_utils.py:99)."""
        import json as _json
        from ray_tpu._private import spawn as _spawn
        node_id = ids.new_node_id()
        res = {str(k): float(v) for k, v in (resources or {}).items()}
        res.setdefault("CPU", 1.0)
        if num_tpus:
            res["TPU"] = float(num_tpus)
        env = _spawn.propagate_pythonpath(dict(os.environ))
        env["RAY_TPU_AUTHKEY"] = self._authkey.hex()
        head_addr = self.tcp_address or self._address
        if self.tcp_address is not None:
            # same-host TCP tier: keep the node dir under the session dir
            # so shutdown/GC sweeps it like the UDS tier
            env["RAY_TPU_NODE_DIR"] = os.path.join(
                self.session_dir, "nodes", node_id)
        cmd = [sys.executable, "-m", "ray_tpu._private.daemon",
               head_addr, node_id, _json.dumps(res), str(int(num_tpus))]
        logf = _spawn.worker_log_file(
            os.path.join(self.session_dir, "logs"), "daemon-" + node_id[5:])
        try:
            proc = subprocess.Popen(
                cmd, env=env, stdin=subprocess.DEVNULL,
                stdout=logf or None,
                stderr=subprocess.STDOUT if logf else None)
        finally:
            if logf is not None:
                logf.close()
        deadline = time.monotonic() + constants.WORKER_REGISTER_TIMEOUT_S
        with self.cv:
            while node_id not in self.nodes:
                if self._shutdown or time.monotonic() > deadline \
                        or proc.poll() is not None:
                    try:
                        proc.terminate()
                    except OSError:
                        pass
                    raise RuntimeError(
                        f"node daemon {node_id} failed to register")
                self.cv.wait(0.2)
            self.nodes[node_id].proc = proc
        return node_id

    def kill_node(self, node_id: str, force: bool = True) -> bool:
        with self.lock:
            node = self.nodes.get(node_id)
            if node is None:
                return False
            proc = node.proc
        if force:
            if proc is not None:
                try:
                    proc.kill()     # SIGKILL: chaos-test path; EOF on the
                except OSError:     # channel triggers _on_node_death
                    pass
            else:
                self._on_node_death(node)
        else:
            node.send(protocol.KillNode())
        return True

    # ------------------------------------------------------------------
    # task submission + scheduling
    # ------------------------------------------------------------------

    def submit(self, spec: protocol.TaskSpec, submitter=None):
        t = _TaskState(spec=spec, submitter=submitter,
                       retries_left=spec.max_retries,
                       retry_exceptions=spec.retry_exceptions)
        with self.lock:
            ref_args = [v for kind, v in spec.args if kind == "ref"]
            ref_args += [v for kind, v in spec.kwargs.values()
                         if kind == "ref"]
            for v in ref_args:
                if v not in self.directory and v in self.freed_refs:
                    from ray_tpu.exceptions import ObjectFreedError
                    self._store_error(
                        spec.return_ids,
                        ObjectFreedError(
                            f"task argument {v} was already freed by "
                            "reference counting"),
                        spec=spec)
                    return
            for v in ref_args:
                if v not in self.directory:
                    t.deps.add(v)
                    self.obj_waiting_tasks.setdefault(v, []).append(t)
            self.task_events.submitted(spec, bool(t.deps))
            self._pin_task_args_locked(spec)
            if not spec.actor_creation and spec.actor_id is None:
                # lineage: remember how to rebuild these returns (actor
                # method outputs are not reconstructable, as in the
                # reference)
                size = _lineage_size(spec)
                for oid in spec.return_ids:
                    old = self.lineage.pop(oid, None)
                    if old is not None:
                        # reconstruction resubmits overwrite their entry;
                        # without the subtract, phantom bytes accumulate
                        # until eviction disables lineage entirely
                        self._lineage_bytes -= _lineage_size(old)
                    self.lineage[oid] = spec
                    self._lineage_bytes += size
                while self.lineage and (
                        len(self.lineage) > constants.MAX_LINEAGE_ENTRIES
                        or self._lineage_bytes
                        > constants.MAX_LINEAGE_BYTES):
                    _old_oid, old_spec = self.lineage.popitem(last=False)
                    self._lineage_bytes -= _lineage_size(old_spec)
            submitter_id = (submitter if isinstance(submitter, str)
                            else getattr(submitter, "worker_id", None))
            if submitter_id is not None:
                # worker-submitted task: the submitter holds the return
                # refs it just minted, but its batched hold report may
                # lag — record implicit holds (see PutRequest handler)
                for oid in spec.return_ids:
                    self.ref_holders.setdefault(oid, set()).add(
                        submitter_id)
            if spec.actor_creation:
                opts = spec.actor_options or {}
                _name = opts.get("name")
                if _name and _name in self.named_actors:
                    raise ValueError(f"actor name {_name!r} already taken")
                a = _ActorState(
                    actor_id=spec.actor_id, creation_spec=spec,
                    max_concurrency=opts.get("max_concurrency", 1),
                    max_restarts=opts.get("max_restarts", 0),
                    max_task_retries=opts.get("max_task_retries", 0),
                    name=_name,
                    resources=dict(spec.resources),
                    method_meta=opts.get("method_meta", {}),
                )
                self.actors[spec.actor_id] = a
                if a.name:
                    self.named_actors[a.name] = spec.actor_id
                if t.deps:
                    self.pending.append(t)
            elif spec.actor_id is not None:
                a = self.actors.get(spec.actor_id)
                if a is None or a.dead:
                    cause = a.death_cause if a else "unknown actor"
                    self._store_error(
                        spec.return_ids,
                        ActorDiedError(f"actor {spec.actor_id} is dead: "
                                       f"{cause}"),
                        spec=spec)
                    return
                a.queue.append(t)
            else:
                if t.deps:
                    self.pending.append(t)
            had_deps = bool(t.deps)
        if not had_deps:
            self._submit_fastpath(t, spec)

    def _submit_fastpath(self, t: _TaskState, spec) -> None:
        """Dispatch attempt scoped to the JUST-submitted work instead of
        rescanning the whole backlog (which turns a deep queue of
        unschedulable tasks into O(n^2) submission — the reference's
        submit path also only queue-and-schedules the new task,
        cluster_task_manager.cc:44 QueueAndScheduleTask). Only called
        for tasks with no deps at submit time (the task is NOT in
        self.pending here, so no racing pass can double-dispatch it);
        full scheduler passes drain the backlog on capacity events."""
        if spec.actor_id is not None and not spec.actor_creation:
            # actor method: pump just that actor's queue
            to_send = []
            with self.lock:
                a = self.actors.get(spec.actor_id)
                if a is not None:
                    self._pump_actor(a, to_send)
            for w, msg in to_send:
                w.send(msg)
            return
        with self.lock:
            if self._shutdown or t.cancelled:
                return
            if not spec.actor_creation and \
                    len(self.pending) > constants.SUBMIT_INLINE_BACKLOG:
                # Deep backlog: the inline dispatch attempt is almost
                # always futile (older tasks are already waiting on the
                # same capacity), and every completion pulls from the
                # backlog directly (_dispatch_freed_fastpath). Skipping
                # the scan makes saturated submission a pure enqueue —
                # the reference's submit path is queue-and-schedule for
                # the same reason (cluster_task_manager.cc:44).
                self.pending.append(t)
                # pending may be deep with dep-BLOCKED tasks while
                # capacity sits idle: the scheduler thread must still
                # look at this task now, not at its 1 s safety tick.
                # But ONLY when the task could actually go somewhere —
                # during a submit storm with the local pool saturated
                # (the common saturated-bench shape) an unconditional
                # wake keeps the scheduler thread scanning the backlog
                # full-time, stealing the core from the submitters and
                # executors. If the shape doesn't fit the local free
                # pool and there are no remote nodes, no pass can
                # dispatch or spawn for it now; the capacity-freeing
                # event that changes that fires its own _schedule().
                if self.nodes or _fits(self.available, spec.resources):
                    self._sched_event.set()
                return
            to_send = []
            if spec.actor_creation:
                disp = self._try_dispatch_actor_creation(t, to_send)
            else:
                disp = self._try_dispatch_generic(t, to_send)
            if disp is not True:
                # False/"localizing": nothing to rescan — the backlog is
                # unchanged. None: resources fit but no idle worker —
                # the scheduler pass owns the spawn logic, wake it.
                self.pending.append(t)
        for w, msg in to_send:
            w.send(msg)
        if disp is None:
            self._schedule()

    def _schedule(self):
        """Signal the scheduler thread: dispatch work soon. Call sites
        fire this after any capacity- or queue-changing event; the
        dedicated thread coalesces bursts of signals into bounded
        passes (reference: the raylet's ScheduleAndDispatchTasks loop
        runs on its own io_service the same way,
        cluster_task_manager.cc:130)."""
        self._sched_event.set()

    def _scheduler_loop(self):
        """Run window-bounded passes until the backlog stops yielding
        dispatches. The rotation in _schedule_pass walks a different
        backlog segment each time, so continuation passes guarantee
        every queued task is (re)examined without any single pass
        paying O(backlog)."""
        window = constants.SCHEDULER_DISPATCH_WINDOW
        while not self._shutdown:
            self._sched_event.wait(timeout=1.0)   # 1s tick = safety net
            if self._shutdown:
                return
            self._sched_event.clear()
            futile = 0
            while not self._shutdown:
                try:
                    dispatched, tripped = self._schedule_pass()
                except Exception:
                    logger.exception("scheduler pass failed")
                    break
                if self._sched_event.is_set():
                    self._sched_event.clear()
                    futile = 0
                    continue        # new capacity arrived mid-pass
                futile = 0 if dispatched else futile + 1
                if not tripped:
                    break           # whole backlog examined this pass
                with self.lock:
                    n = len(self.pending)
                if futile * window >= n:
                    break           # one full rotation, no progress
            # wait for the next signal

    def _schedule_pass(self):
        """One bounded dispatch pass. -> (n_dispatched, window_tripped)."""
        to_send = []   # (worker, message) executed outside the lock
        retired = []   # over-cap idle workers killed outside the lock
        n_dispatched = 0
        tripped = False
        with self.lock:
            if self._shutdown:
                return 0, False
            # --- generic + actor-creation tasks ---
            still = []
            want_spawn = 0
            # `sim` tracks how much concurrency the resource pool could
            # actually absorb, so we never spawn more workers than could
            # run at once (reference: prestart-on-backlog is similarly
            # resource-capped, node_manager.cc:1885).
            sim = dict(self.available)
            # Dispatch WINDOW: stop examining the queue after this many
            # consecutive tasks fail to dispatch (cluster saturated).
            # Without it every submit's schedule pass rescans the whole
            # backlog and a 100k-task queue turns submission O(n^2) —
            # the reference bounds its dispatch loop the same way
            # (cluster_task_manager dispatch caps per iteration).
            window = constants.SCHEDULER_DISPATCH_WINDOW
            misses = 0
            # Per-pass memo: once a PLAIN task (no affinity/PG) with
            # resource shape R failed to dispatch, every later plain-R
            # task in the same pass fails identically — skip the
            # placement scan (the backlog is usually many copies of one
            # shape, so this turns the rescan O(shapes), not O(tasks)).
            # The deque scan is IN PLACE: examined-and-kept tasks go
            # back to the front, the untouched tail never moves, so a
            # pass costs O(window), not O(backlog).
            unfit: dict = {}
            examined = 0
            n0 = len(self.pending)
            while self.pending and examined < n0 and misses < window:
                t = self.pending.popleft()
                examined += 1
                if t.cancelled:
                    continue
                if t.deps:
                    still.append(t)
                    continue
                if t.spec.actor_creation:
                    disp = self._try_dispatch_actor_creation(t, to_send)
                else:
                    plain = (not t.spec.placement_group_id
                             and not t.spec.scheduling_strategy)
                    sig = (frozenset(t.spec.resources.items())
                           if plain else None)
                    if sig is not None and sig in unfit:
                        disp = unfit[sig]
                    else:
                        disp = self._try_dispatch_generic(t, to_send)
                        # memoize only SHAPE-level outcomes; "localizing"
                        # is task-specific and must not poison the shape
                        if sig is not None and (disp is False
                                                or disp is None):
                            unfit[sig] = disp
                    if disp is True:
                        _sub(sim, t.spec.resources)
                    elif disp is None:   # resources fit but no idle worker
                        if _fits(sim, t.spec.resources):
                            _sub(sim, t.spec.resources)
                            want_spawn += 1
                        still.append(t)
                        misses += 1
                        continue
                if disp is True:
                    n_dispatched += 1
                else:
                    still.append(t)
                    misses += 1
            tripped = misses >= window and bool(self.pending)
            if tripped:
                # window tripped with tasks left unexamined: ROTATE the
                # examined-but-kept prefix to the back so successive
                # passes walk different segments of the backlog (no
                # starvation for shapes stuck behind other shapes)
                self.pending.extend(still)
            else:
                self.pending.extendleft(reversed(still))
            # --- actor method calls ---
            for a in self.actors.values():
                self._pump_actor(a, to_send)
            # --- worker pool scale-up ---
            # `_spawning` counts workers from Popen until registration (or
            # failure); without it every schedule pass would re-spawn for the
            # same pending tasks while the first worker is still importing.
            # Workers blocked in get() (w.released) gave their lease back,
            # so they don't count against the cap either: a nested/reduce
            # task blocked on an upstream result must never pin the last
            # pool slot, or the producer can never run (the reference
            # spawns replacement workers past the soft cap for exactly
            # this reason, worker_pool.cc's blocked-worker accounting).
            n_generic = sum(1 for w in self.workers.values()
                            if w.kind == "generic" and w.alive
                            and not w.released)
            can = constants.MAX_WORKERS_CAP - n_generic - self._spawning
            for _ in range(max(0, min(want_spawn - self._spawning, can))):
                self._spawning += 1
                threading.Thread(target=self._spawn_generic_worker,
                                 daemon=True).start()
            # --- worker pool scale-down ---
            # Inverse of the blocked-worker carve-out above: once the
            # blocked workers resume, the pool can sit over the cap.
            # Retire idle surplus (never a busy or blocked worker, and
            # only with an empty backlog) so one storm of nested gets
            # doesn't leave extra worker processes around for the rest
            # of the session.
            if not self.pending:
                alive_generic = [w for w in self.workers.values()
                                 if w.kind == "generic" and w.alive]
                excess = len(alive_generic) - constants.MAX_WORKERS_CAP
                for w in alive_generic:
                    if excess <= 0:
                        break
                    if w.idle and not w.released and w.current is None:
                        w.idle = False
                        w.alive = False
                        self.workers.pop(w.worker_id, None)
                        retired.append(w)
                        excess -= 1
        for w in retired:
            w.send(protocol.KillWorker())
        for w, msg in to_send:
            if not w.send(msg):
                if isinstance(w, _RemoteNode):
                    self._on_node_death(w)
                else:
                    self._on_worker_death(w)
        return n_dispatched, tripped

    def _pick_node(self, spec) -> str | None:
        """Cluster scheduling policy (counterpart of
        ClusterResourceScheduler::GetBestSchedulableNode + the hybrid
        pack-then-spread policy, hybrid_scheduling_policy.h:50): hard/soft
        node affinity first, then SPREAD round-robin when requested, then
        locality (most argument bytes), then pack head-first. Returns
        "head", a node id, or None (nothing fits now). Caller holds lock."""
        req = spec.resources
        n_tpu = int(req.get("TPU", 0))

        def head_fits():
            return (_fits(self.available, req)
                    and len(self.free_tpu_chips) >= n_tpu)

        def node_fits(node):
            return (node.alive and _fits(node.available, req)
                    and len(node.free_tpu_chips) >= n_tpu)

        strategy = spec.scheduling_strategy
        if isinstance(strategy, dict) and strategy.get("node_id"):
            nid = strategy["node_id"]
            if nid in ("head", self.node_id):
                if head_fits():
                    return "head"
            else:
                node = self.nodes.get(nid)
                if node is not None and node_fits(node):
                    return nid
                if not strategy.get("soft", False) and (
                        node is None or not node.alive):
                    # hard affinity to a node that can never come back:
                    # fail fast instead of pending forever
                    return "__infeasible__"
            if not strategy.get("soft", False):
                return None     # hard affinity: wait for the target
        candidates = []
        if head_fits():
            candidates.append("head")
        candidates += [nid for nid, node in self.nodes.items()
                       if node_fits(node)]
        if not candidates:
            return None
        if strategy == "SPREAD":
            self._spread_rr += 1
            return candidates[self._spread_rr % len(candidates)]
        arg_bytes: dict[str, int] = {}
        for kind, v in list(spec.args) + list(spec.kwargs.values()):
            if kind != "ref":
                continue
            d = self.directory.get(v)
            if d is None or d.inline is not None:
                continue
            where = d.node or "head"
            arg_bytes[where] = arg_bytes.get(where, 0) + d.size
        if arg_bytes:
            best = max(candidates, key=lambda c: arg_bytes.get(c, 0))
            if arg_bytes.get(best, 0) > 0:
                return best
        return candidates[0]

    def _needs_localize_locked(self, t: _TaskState) -> bool:
        """Head-local dispatch needs every ref arg readable in the head's
        store; kick off a background pull for remote ones. Caller holds
        the lock. True = not ready yet (stay pending)."""
        remote = {}
        for kind, v in list(t.spec.args) + list(t.spec.kwargs.values()):
            if kind != "ref":
                continue
            d = self.directory.get(v)
            if (d is None or d.inline is not None or d.node is None
                    or v in self.local_copies):
                continue
            remote[v] = d
        if not remote:
            return False
        if not t.localizing:
            t.localizing = True

            def _pull_all():
                try:
                    self._localize(remote)
                except Exception as e:
                    logger.warning("arg localization failed: %s", e)
                finally:
                    t.localizing = False
                    self._schedule()
            threading.Thread(target=_pull_all, daemon=True).start()
        return True

    def _lease_to_node(self, node: _RemoteNode, t: _TaskState, to_send):
        """Hand a scheduled task to a HostDaemon (caller holds the lock and
        has already debited resources/chips)."""
        spec = t.spec
        locs = {}
        for kind, v in list(spec.args) + list(spec.kwargs.values()):
            if kind == "ref":
                d = self.directory.get(v)
                if d is None:
                    # can't happen while task_arg_refs pins the entry, but a
                    # hole must fail the lease (daemon pull error -> retry/
                    # error), never KeyError the scheduler mid-pass
                    logger.error("arg %s missing from directory at lease "
                                 "time for %s", v, spec.task_id)
                    continue
                locs[v] = d
        peer_addrs = {nid: n.address for nid, n in self.nodes.items()
                      if n.alive and n.address}
        t.node = node.node_id
        node.inflight[spec.task_id] = t
        self.task_events.running(spec, "node:" + node.node_id)
        to_send.append((node, protocol.LeaseTask(
            spec=spec, arg_locations=locs, peer_addrs=peer_addrs,
            tpu_chips=list(t.tpu_chips))))

    def _pick_bundle_target(self, req: dict, n_tpu: int, pg):
        """Pick the first placement-group bundle that fits `req` and whose
        node can also supply the TPU chips; the chosen bundle pins the
        node (bundles were placed at PG creation; the 2PC of
        placement_group_resource_manager.h:46 collapses to this
        reservation). Returns (target, bundle_idx) or (None, None).
        Caller holds the lock."""
        for i, b in enumerate(pg.available):
            if not _fits(b, req):
                continue
            cand = pg.bundle_nodes[i] or "head"
            if cand == "head":
                if len(self.free_tpu_chips) >= n_tpu:
                    return "head", i
            else:
                node = self.nodes.get(cand)
                if (node is not None and node.alive
                        and len(node.free_tpu_chips) >= n_tpu):
                    return cand, i
        return None, None

    def _choose_target(self, t: _TaskState, req: dict, n_tpu: int, pg):
        """Resolve where a task/actor should run: ("head"|node_id|
        "__infeasible__"|None, bundle_idx|None). Caller holds the lock."""
        if pg is not None:
            return self._pick_bundle_target(req, n_tpu, pg)
        return self._pick_node(t.spec), None

    def _debit_target(self, target: str, idx, req: dict, n_tpu: int,
                      pg) -> list:
        """Debit `req` from the chosen pool (PG bundle, node, or head) and
        carve TPU chips from the target host; returns the chip list.
        Caller holds the lock and has verified fit (incl. chip count)."""
        if pg is not None:
            _sub(pg.available[idx], req)
        elif target == "head":
            _sub(self.available, req)
        else:
            _sub(self.nodes[target].available, req)
        pool = (self.free_tpu_chips if target == "head"
                else self.nodes[target].free_tpu_chips)
        chips = pool[:n_tpu]
        del pool[:n_tpu]
        return chips

    def _try_dispatch_generic(self, t: _TaskState, to_send):
        """True=dispatched, False=doesn't fit anywhere right now,
        None=head has the resources but no idle worker (caller spawns)."""
        req = t.spec.resources
        n_tpu = int(req.get("TPU", 0))
        pg = self.placement_groups.get(t.spec.placement_group_id or "")
        target, idx = self._choose_target(t, req, n_tpu, pg)
        if target is None:
            return False
        if target == "__infeasible__":
            self._store_error(
                t.spec.return_ids,
                SchedulingError(
                    f"task {t.spec.function_desc} has hard node "
                    "affinity to a dead or unknown node"),
                spec=t.spec)
            return True     # consumed: removed from pending as failed
        if target != "head":
            t.tpu_chips = self._debit_target(target, idx, req, n_tpu, pg)
            self._lease_to_node(self.nodes[target], t, to_send)
            return True
        if self._needs_localize_locked(t):
            return "localizing"   # task-specific wait: NEVER memoized
        from ray_tpu._private.runtime_env import is_trivial
        if n_tpu > 0 or not is_trivial(t.spec.runtime_env):
            # TPU tasks need TPU_VISIBLE_CHIPS in the environment BEFORE the
            # process initializes JAX (the reference's CUDA_VISIBLE_DEVICES
            # is equally process-birth-scoped for safety); runtime-env tasks
            # need their env materialized pre-exec. Both run on a dedicated
            # fresh worker that retires afterwards, not the pool.
            t.tpu_chips = self._debit_target("head", idx, req, n_tpu, pg)
            threading.Thread(target=self._spawn_dedicated_worker,
                             args=(t,), daemon=True).start()
            return True
        worker = next((w for w in self.workers.values()
                       if w.kind == "generic" and w.idle and w.alive), None)
        if worker is None:
            return None
        t.tpu_chips = self._debit_target("head", idx, req, 0, pg)
        worker.idle = False
        worker.current = t
        to_send.append((worker, self._push_msg(worker, t)))
        return True

    def _spawn_dedicated_worker(self, t: _TaskState):
        """Fresh single-task worker: used for TPU tasks (chip visibility is
        process-birth-scoped) and for tasks with a non-trivial runtime
        environment (the pool's workers have none)."""
        from ray_tpu._private import spawn as spawn_mod
        from ray_tpu.exceptions import RuntimeEnvSetupError
        worker_id = ids.new_worker_id()
        w = _WorkerConn(worker_id, None, proc=None, kind="dedicated",
                        idle=False, alive=False)
        with self.lock:
            self.workers[worker_id] = w
        try:
            env = self._worker_env(chips=t.tpu_chips,
                                   runtime_env=t.spec.runtime_env)
            env, python_exe, cwd, cmd_prefix = \
                spawn_mod.setup_runtime_env(t.spec.runtime_env, env)
            w.proc = spawn_mod.spawn_worker_proc(
                self._address, self._authkey, worker_id, env,
                python_exe, cwd,
                log_dir=os.path.join(self.session_dir, "logs"),
                cmd_prefix=cmd_prefix)
        except RuntimeEnvSetupError as e:
            with self.lock:
                self._release_task_resources(t)
                self.workers.pop(worker_id, None)
            self._store_error(t.spec.return_ids, e, spec=t.spec)
            return
        if not self._await_registration(w):
            with self.lock:
                self._release_task_resources(t)
                self.workers.pop(worker_id, None)
            self._store_error(
                t.spec.return_ids,
                WorkerCrashedError("dedicated worker failed to start"),
                spec=t.spec)
            return
        with self.lock:
            w.current = t
            msg = self._push_msg(w, t)
        w.send(msg)

    def _push_msg(self, worker: _WorkerConn, t: _TaskState):
        spec = t.spec
        if spec.function_id in worker.known_functions:
            spec = protocol.TaskSpec(**{**spec.__dict__, "function_blob": None})
        else:
            worker.known_functions.add(spec.function_id)
        locs = {}
        for kind, v in list(spec.args) + list(spec.kwargs.values()):
            if kind == "ref":
                d = self.directory.get(v)
                if d is not None and d.node is not None:
                    # remote primary: the dispatch gate (_needs_localize_
                    # locked) guaranteed a head-local copy exists
                    d = self.local_copies.get(v, d)
                if d is None:
                    # directory hole (should be unreachable): let the
                    # worker fail the task; never KeyError the scheduler
                    logger.error("arg %s missing from directory at push "
                                 "time for %s", v, spec.task_id)
                    continue
                locs[v] = d
        self.task_events.running(t.spec, worker.worker_id)
        return protocol.PushTask(spec=spec, arg_locations=locs)

    def _try_dispatch_actor_creation(self, t: _TaskState, to_send):
        a = self.actors[t.spec.actor_id]
        req = a.resources
        n_tpu = int(req.get("TPU", 0))
        pg = self.placement_groups.get(t.spec.placement_group_id or "")
        target, idx = self._choose_target(t, req, n_tpu, pg)
        if target is None:
            return False
        if target == "__infeasible__":
            self._fail_actor(
                a, "actor has hard node affinity to a dead or unknown node")
            return True         # consumed: removed from pending as failed
        if target != "head":
            a.tpu_chips = self._debit_target(target, idx, req, n_tpu, pg)
            a.node = target
            t.tpu_chips = list(a.tpu_chips)
            a.inflight.append(t)
            self._lease_to_node(self.nodes[target], t, to_send)
            return True
        if self._needs_localize_locked(t):
            return False
        a.tpu_chips = self._debit_target("head", idx, req, n_tpu, pg)
        if not a.tpu_chips and not t.spec.runtime_env:
            # Serve the creation from an idle pooled worker when one
            # exists (reference: the raylet's PopWorker hands actor
            # creations pooled workers the same way) — skips the whole
            # fork+init+register round (~15ms/actor on a 1-core box).
            # TPU/runtime-env actors still get dedicated spawns.
            w = next((w for w in self.workers.values()
                      if w.alive and w.idle and not w.remote
                      and w.kind == "generic"), None)
            if w is not None:
                w.kind = "actor"
                w.pooled_actor = True
                w.idle = False
                w.current = t
                a.worker = w
                a.inflight.append(t)
                to_send.append((w, self._push_msg(w, t)))
                return True
        threading.Thread(target=self._spawn_actor_worker, args=(a, t),
                        daemon=True).start()
        return True

    def _pump_actor(self, a: _ActorState, to_send):
        if a.dead or not a.ready:
            return
        if a.node is not None:
            node = self.nodes.get(a.node)
            if node is None or not node.alive:
                return
            while a.queue and len(a.inflight) < a.max_concurrency:
                t = a.queue[0]
                if t.deps:
                    break   # preserve submission order per actor
                if t.cancelled:
                    a.queue.pop(0)
                    continue
                a.queue.pop(0)
                a.inflight.append(t)
                self._lease_to_node(node, t, to_send)
            return
        if a.worker is None or not a.worker.alive:
            return
        while a.queue and len(a.inflight) < a.max_concurrency:
            t = a.queue[0]
            if t.deps:
                break   # preserve submission order per actor
            if t.cancelled:
                a.queue.pop(0)
                continue
            if self._needs_localize_locked(t):
                break
            a.queue.pop(0)
            a.inflight.append(t)
            to_send.append((a.worker, self._push_msg(a.worker, t)))

    # ------------------------------------------------------------------
    # worker processes
    # ------------------------------------------------------------------

    def _worker_env(self, chips=None, runtime_env=None):
        from ray_tpu._private import spawn
        return spawn.worker_env(chips=chips, runtime_env=runtime_env)

    def _spawn_proc(self, worker_id, env):
        from ray_tpu._private import spawn
        return spawn.spawn_worker_proc(
            self._address, self._authkey, worker_id, env,
            log_dir=os.path.join(self.session_dir, "logs"))

    def _spawn_generic_worker(self):
        worker_id = ids.new_worker_id()
        # Record the worker BEFORE Popen so a fast-registering child finds
        # its slot in _serve_conn instead of racing us into a duplicate.
        w = _WorkerConn(worker_id, None, proc=None, kind="generic",
                        idle=False, alive=False)
        with self.lock:
            self.workers[worker_id] = w
        w.proc = self._spawn_proc(worker_id, self._worker_env())
        ok = self._await_registration(w)
        with self.lock:
            self._spawning -= 1
            if ok:
                w.idle = True
                self._spawn_failures = 0
            else:
                self.workers.pop(worker_id, None)
                self._spawn_failures += 1
                if self._spawn_failures >= 3:
                    # Startup is systematically broken (bad env, missing
                    # package): fail queued work instead of a respawn storm.
                    failed, self.pending = self.pending, deque()
                    for t in failed:
                        if not t.spec.actor_creation:
                            self._store_error(
                                t.spec.return_ids,
                                WorkerCrashedError(
                                    "worker processes repeatedly failed to "
                                    "start; check worker logs"),
                                spec=t.spec)
        self._schedule()

    def _spawn_actor_worker(self, a: _ActorState, creation_task: _TaskState):
        from ray_tpu._private import spawn as spawn_mod
        from ray_tpu.exceptions import RuntimeEnvSetupError
        worker_id = ids.new_worker_id()
        w = _WorkerConn(worker_id, None, proc=None, kind="actor",
                        idle=False, alive=False)
        with self.lock:
            self.workers[worker_id] = w
        try:
            env = self._worker_env(
                chips=a.tpu_chips,
                runtime_env=a.creation_spec.runtime_env)
            env, python_exe, cwd, cmd_prefix = \
                spawn_mod.setup_runtime_env(
                    a.creation_spec.runtime_env, env)
            w.proc = spawn_mod.spawn_worker_proc(
                self._address, self._authkey, worker_id, env,
                python_exe, cwd,
                log_dir=os.path.join(self.session_dir, "logs"),
                cmd_prefix=cmd_prefix)
        except RuntimeEnvSetupError as e:
            with self.lock:
                self.workers.pop(worker_id, None)
            self._fail_actor(a, f"runtime env setup failed: {e}")
            return
        if not self._await_registration(w):
            self._fail_actor(a, "actor worker failed to start")
            return
        to_send = []
        with self.lock:
            a.worker = w
            w.current = creation_task
            a.inflight.append(creation_task)
            to_send.append((w, self._push_msg(w, creation_task)))
        for w2, msg in to_send:
            w2.send(msg)

    def _await_registration(self, w: _WorkerConn) -> bool:
        deadline = time.monotonic() + constants.WORKER_REGISTER_TIMEOUT_S
        while not w.alive:
            rem = deadline - time.monotonic()
            if rem <= 0 or self._shutdown:
                return False
            if w.proc is not None and w.proc.poll() is not None:
                return False
            # per-worker event: registration wakes exactly this waiter
            # (the global cv would thundering-herd under creation bursts)
            w.reg_event.wait(min(rem, 0.2))
        return True

    # ------------------------------------------------------------------
    # completion + failure
    # ------------------------------------------------------------------

    def _on_task_done(self, w: _WorkerConn, msg: protocol.TaskDone):
        if msg.spans:
            # merge the worker's drained spans before taking the node lock
            from ray_tpu.util import tracing as _tracing
            _tracing.ingest(msg.spans)
        retire = None
        with self.lock:
            t = w.current if (w.current and w.current.spec.task_id ==
                              msg.task_id) else None
            a = None
            if t is None:
                # actor task completing (possibly out of submission order
                # when max_concurrency > 1)
                for cand in self.actors.values():
                    for inf in cand.inflight:
                        if inf.spec.task_id == msg.task_id:
                            a, t = cand, inf
                            break
                    if a:
                        break
            if t is None:
                logger.warning("TaskDone for unknown task %s", msg.task_id)
                return
            spec = t.spec
            if a is None and spec.actor_id is not None:
                a = self.actors.get(spec.actor_id)
            # Retry on application error if requested.
            if (msg.error and t.retry_exceptions and t.retries_left > 0
                    and not spec.actor_creation):
                t.retries_left -= 1
                self.task_events.requeued(spec)
                self._requeue_after_failure(w, t, a)
                return
            self.task_events.finished(
                msg.task_id, error="application_error" if msg.error else None,
                exec_start_ts=msg.exec_start_ts, exec_end_ts=msg.exec_end_ts,
                return_ids=spec.return_ids)
            self._release_task_args(spec)
            for oid, desc in zip(spec.return_ids, msg.return_descs):
                # _register_locked already notifies waiters per oid; a
                # second notify_all here was pure herd overhead
                self._register_locked(oid, desc, origin=w.worker_id)
            if a is not None:
                if t in a.inflight:
                    a.inflight.remove(t)
                if spec.actor_creation:
                    if msg.error:
                        a.dead = True
                        a.death_cause = "constructor raised"
                        self._release_actor_resources(a)
                        failed, a.queue = a.queue, []
                        for qt in failed:
                            self._store_error(
                                qt.spec.return_ids,
                                ActorDiedError(
                                    f"actor {a.actor_id} constructor raised"),
                                spec=qt.spec)
                        if w.pooled_actor:
                            # the worker came from the pool and is still
                            # healthy (only the user constructor raised):
                            # hand it back instead of stranding it
                            w.pooled_actor = False
                            w.kind = "generic"
                            w.idle = True
                            a.worker = None
                            # a.worker was just nulled, so the `a.worker
                            # is w` check below can't clear w.current —
                            # do it here, or the recycled worker keeps
                            # pointing at the dead actor's creation task
                            # and a later worker death re-credits its
                            # resources / re-queues it.
                            w.current = None
                            self._sched_event.set()
                    else:
                        a.ready = True
                if a.worker is w:
                    w.current = None
            else:
                w.current = None
                if not w.released:
                    self._release_task_resources(t)
                w.released = {}
                if w.kind == "dedicated":
                    # Dedicated workers retire with their task: the TPU
                    # runtime (and a task-specific env) can't be re-scoped
                    # in a live process.
                    w.idle = False
                    w.alive = False
                    retire = w
                else:
                    w.idle = True
        if retire is not None:
            retire.send(protocol.KillWorker())
            with self.lock:
                self.workers.pop(retire.worker_id, None)
        # Completion fastpath (the submit path has the same shortcut,
        # _submit_fastpath; reference: cluster_task_manager.cc:44
        # QueueAndScheduleTask scoping): a completion frees exactly one
        # slot, so fill exactly that slot instead of waking the full
        # scheduler pass — on a deep homogeneous backlog the pass
        # examines a whole dispatch window per completion, which caps
        # drain throughput.
        if a is not None:
            # actor slot freed: pump exactly that actor's queue
            to_send = []
            with self.lock:
                self._pump_actor(a, to_send)
            for w2, m2 in to_send:
                w2.send(m2)
        elif self._dispatch_freed_fastpath():
            return
        self._schedule()

    def _dispatch_freed_fastpath(self) -> bool:
        """Hand freed slots the head-of-line pending tasks. Batched:
        dequeue -> match -> dispatch for up to SCHEDULER_FREED_BATCH
        plain tasks under ONE lock acquisition — concurrent completions
        free several slots at once, and the first reader through the
        lock fills them all instead of paying an acquire/release per
        task. Anything trickier (deps, actors, placement groups,
        scheduling strategies) falls back to the scheduler pass.
        Returns True iff the freed capacity was cleanly consumed (or
        nothing is runnable) so the scheduler event can be skipped —
        the next completion continues the chain."""
        to_send = []
        ok = False
        need_pass = False
        filled = 0
        with self.lock:
            if self._shutdown:
                return True
            for _ in range(64):        # bound: pops + dispatch attempts
                if filled >= constants.SCHEDULER_FREED_BATCH:
                    break
                if not self.pending:
                    ok = True          # nothing queued: slot stays free
                    break
                t = self.pending[0]
                if t.cancelled:
                    self.pending.popleft()
                    continue
                if (t.deps or t.spec.actor_creation
                        or t.spec.actor_id is not None
                        or t.spec.placement_group_id
                        or t.spec.scheduling_strategy):
                    need_pass = True   # needs the real pass
                    break
                if (filled and not self.nodes
                        and not _fits(self.available, t.spec.resources)):
                    # freed slot(s) already refilled and the local pool
                    # can't absorb another of this shape: stop before
                    # paying a full placement scan that must fail
                    break
                self.pending.popleft()
                n_before = len(to_send)
                if self._try_dispatch_generic(t, to_send) is True:
                    # "consumed" is not "slot filled": infeasible tasks
                    # return True with nothing sent, and a remote
                    # dispatch leaves the LOCAL slot idle — keep going,
                    # a later queued task may fill it
                    if any(isinstance(w, _WorkerConn)
                           for w, _ in to_send[n_before:]):
                        filled += 1
                        ok = True
                else:
                    # No capacity left (or needs localization). If we
                    # already filled the freed slot(s), the backlog is
                    # simply deeper than the capacity — the next
                    # completion continues the chain and a full pass
                    # would be pure overhead. Only an UNFILLED freed
                    # slot needs the real pass.
                    self.pending.appendleft(t)
                    if filled == 0:
                        need_pass = True
                    break
        for w, msg in to_send:
            if not w.send(msg):
                if isinstance(w, _RemoteNode):
                    self._on_node_death(w)
                else:
                    self._on_worker_death(w)
                ok = False
        return ok and not need_pass

    def _requeue_after_failure(self, w, t, a):
        """Re-run a failed task (called under lock)."""
        if a is not None:
            if t in a.inflight:
                a.inflight.remove(t)
            a.queue.insert(0, t)
            if a.worker is w:
                w.current = None
        else:
            w.idle = True
            w.current = None
            if not w.released:
                self._release_task_resources(t)
            w.released = {}
            self.pending.append(t)

    def _release_task_resources(self, t: _TaskState):
        if not t.node_released:
            pg = self.placement_groups.get(t.spec.placement_group_id or "")
            if t.spec.placement_group_id and pg is None:
                # The group was already removed (remove_pg credits the
                # FULL bundles back wholesale); crediting the node again
                # here would double-count — kill() is async, so actor/
                # task death often lands after the PG teardown.
                pass
            elif pg is not None:
                # return to the first bundle with headroom vs its spec
                for b, orig in zip(pg.available, pg.bundles):
                    if all(b.get(k, 0) + v <= orig.get(k, 0) + _EPS
                           for k, v in t.spec.resources.items()):
                        _add(b, t.spec.resources)
                        break
                else:
                    if pg.available:
                        _add(pg.available[0], t.spec.resources)
            elif t.node is not None:
                node = self.nodes.get(t.node)
                if node is not None:
                    _add(node.available, t.spec.resources)
            else:
                _add(self.available, t.spec.resources)
        t.node_released = False
        chips, t.tpu_chips = t.tpu_chips, []
        if chips:
            if t.node is not None:
                node = self.nodes.get(t.node)
                if node is not None:
                    node.free_tpu_chips.extend(chips)
            else:
                self.free_tpu_chips.extend(chips)

    def _release_actor_resources(self, a: _ActorState):
        pg = self.placement_groups.get(
            a.creation_spec.placement_group_id or "")
        if pg is not None and pg.available:
            _add(pg.available[0], a.resources)
        elif a.creation_spec.placement_group_id:
            # PG already removed; its bundles were credited wholesale
            # (see _release_task_resources) — don't double-credit.
            pass
        elif pg is None:
            if a.node is not None:
                node = self.nodes.get(a.node)
                if node is not None:
                    _add(node.available, a.resources)
            else:
                _add(self.available, a.resources)
        if a.tpu_chips:
            if a.node is not None:
                node = self.nodes.get(a.node)
                if node is not None:
                    node.free_tpu_chips.extend(a.tpu_chips)
            else:
                self.free_tpu_chips.extend(a.tpu_chips)
            a.tpu_chips = []
        a.node = None

    def _store_error(self, return_ids, exc, spec=None):
        """Store `exc` as the value of every return id (under or out of lock).
        `spec` records the terminal FAILED transition in the state API and
        releases the task's pinned args — this is the chokepoint every
        failure path goes through."""
        if spec is not None:
            self.task_events.finished(spec.task_id,
                                      error=type(exc).__name__)
            self._release_task_args(spec)
        for oid in return_ids:
            desc = self.store.put(oid, exc)
            with self.lock:
                self._register_locked(oid, desc, origin="driver")

    def _on_worker_death(self, w: _WorkerConn):
        with self.lock:
            if w.kind == "attach":
                # external CLI/monitoring connection: reap the entry, no
                # task/actor state to recover
                self.workers.pop(w.worker_id, None)
                return
            if not w.alive and w.current is None:
                return
            w.alive = False
            w.idle = False
            t = w.current
            w.current = None
            actor = next((a for a in self.actors.values()
                          if a.worker is w), None)
            # drop the dead process's ref holds (its ObjectRefs died with
            # it); objects it alone held become freeable
            affected = [oid for oid, holders in self.ref_holders.items()
                        if w.worker_id in holders]
            for oid in affected:
                self.ref_holders[oid].discard(w.worker_id)
                self._maybe_free_locked(oid)
            # Reclaim the dead process's shared-arena pins (plasma releases
            # a disconnected client's references the same way): first adopt
            # the owner pin of every live object it put — so force-release
            # can't leave them evictable — then drop everything the pid
            # still holds (reader pins, condemned pins, unsealed creations).
            pid = getattr(w.proc, "pid", None)
            if pid is not None:
                for oid, origin in list(self.obj_origin.items()):
                    if origin != w.worker_id:
                        continue
                    desc = self.directory.get(oid)
                    if desc is not None and desc.arena:
                        self.store.adopt(oid)
                    self.obj_origin[oid] = "driver"
                self.store.release_all_pins(pid)
        if actor is not None:
            self._on_actor_worker_death(actor)
        elif t is not None:
            with self.lock:
                if not w.released:
                    self._release_task_resources(t)
                w.released = {}
                if t.retries_left > 0:
                    t.retries_left -= 1
                    self.pending.append(t)
                    self.task_events.requeued(t.spec)
                    retry = True
                else:
                    retry = False
            if not retry:
                self._store_error(
                    t.spec.return_ids,
                    WorkerCrashedError(
                        f"worker died while running {t.spec.function_desc}"),
                    spec=t.spec)
        self._schedule()

    def _on_actor_worker_death(self, a: _ActorState):
        with self.lock:
            a.ready = False
            a.worker = None
            inflight, a.inflight = a.inflight, []
            can_restart = (not a.dead and
                           (a.max_restarts == -1 or
                            a.restarts_used < a.max_restarts))
            if can_restart:
                a.restarts_used += 1
                # Return the dead incarnation's resources/chips; the
                # re-queued creation task re-subtracts them on dispatch.
                self._release_actor_resources(a)
                # retry in-flight tasks if allowed, else fail them
                retry_tasks, fail_tasks = [], []
                for t in inflight:
                    if t.spec.actor_creation:
                        continue
                    if a.max_task_retries != 0:
                        retry_tasks.append(t)
                    else:
                        fail_tasks.append(t)
                a.queue[:0] = retry_tasks
                creation = _TaskState(spec=a.creation_spec)
                self.pending.append(creation)
            else:
                a.dead = True
                a.death_cause = a.death_cause or "worker process died"
                fail_tasks = [t for t in inflight
                              if not t.spec.actor_creation]
                fail_tasks.extend(a.queue)
                a.queue = []
                self._release_actor_resources(a)
        for t in fail_tasks:
            self._store_error(
                t.spec.return_ids,
                ActorDiedError(f"actor {a.actor_id} died"
                               f" ({a.death_cause or 'restarting'})"),
                spec=t.spec)
        self._schedule()

    # the same restart/fail state machine serves remote actors, whose
    # worker lives under a HostDaemon (we only hear NodeActorDied)
    _on_actor_death = _on_actor_worker_death

    def _fail_actor(self, a: _ActorState, cause: str):
        with self.lock:
            a.dead = True
            a.death_cause = cause
            tasks = list(a.inflight) + list(a.queue)
            a.inflight, a.queue = [], []
            self._release_actor_resources(a)
        for t in tasks:
            self._store_error(t.spec.return_ids, ActorDiedError(cause),
                              spec=t.spec)
        # creation return id too
        self._store_error(a.creation_spec.return_ids, ActorDiedError(cause),
                          spec=a.creation_spec)

    # ------------------------------------------------------------------
    # actor control
    # ------------------------------------------------------------------

    def get_named_actor(self, name: str):
        with self.lock:
            actor_id = self.named_actors.get(name)
            if actor_id is None:
                return None
            a = self.actors.get(actor_id)
            if a is None or a.dead:
                return None
            return {"actor_id": actor_id, "method_meta": a.method_meta,
                    "creation_return": a.creation_spec.return_ids[0]}

    def kill_actor(self, actor_id: str, no_restart=True):
        with self.lock:
            a = self.actors.get(actor_id)
            if a is None:
                return False
            if no_restart:
                a.dead = True
                a.death_cause = "killed via kill()"
                if a.name:
                    self.named_actors.pop(a.name, None)
            w = a.worker
            node = self.nodes.get(a.node) if a.node is not None else None
        if node is not None:
            node.send(protocol.KillActorOnNode(actor_id))
        elif w is not None and w.proc is not None:
            try:
                w.proc.terminate()
            except OSError:
                pass
        return True

    def cancel(self, object_id: str, force: bool = False):
        with self.lock:
            for t in self.pending:
                if object_id in t.spec.return_ids:
                    t.cancelled = True
                    self.pending.remove(t)
                    self._store_error(t.spec.return_ids,
                                      TaskCancelledError("task cancelled"),
                                      spec=t.spec)
                    return True
            for a in self.actors.values():
                for t in a.queue:
                    if object_id in t.spec.return_ids:
                        t.cancelled = True
                        a.queue.remove(t)
                        self._store_error(t.spec.return_ids,
                                          TaskCancelledError("task cancelled"),
                                          spec=t.spec)
                        return True
        return False

    # ------------------------------------------------------------------
    # placement groups: bundles are placed onto nodes at creation time by
    # strategy (PACK/SPREAD/STRICT_*), reserving resources on each node —
    # the reference's bundle scheduling policies
    # (policy/bundle_scheduling_policy.h:82-106) with the 2PC
    # (placement_group_resource_manager.h:46) collapsed into the head's
    # single resource ledger.
    # ------------------------------------------------------------------

    def _pool_links_locked(self) -> dict:
        """pool id -> link-group ids, for the contention model. The head's
        own links come from its env; daemons advertised theirs in
        RegisterNode."""
        links = {"head": tuple(
            s for s in config.get("LINK_GROUPS").split(",") if s)}
        for nid, n in self.nodes.items():
            if n.alive:
                links[nid] = tuple(n.links)
        return links

    def _link_load_locked(self, pool_links: dict) -> dict:
        """link id -> count of live bandwidth-tagged gangs touching it.
        Recomputed from the placement-group table at gang-creation time
        (rare), so the remove/failure paths carry no extra bookkeeping."""
        load: dict = {}
        for pg in self.placement_groups.values():
            if not pg.bandwidth:
                continue
            touched = set()
            for nid in pg.bundle_nodes:
                touched.update(pool_links.get(
                    "head" if nid is None else nid, ()))
            for link in touched:
                load[link] = load.get(link, 0) + 1
        return load

    def _assign_bundles(self, bundles, strategy, bandwidth=0.0):
        """Pick a node for every bundle. Returns list of node ids (None =
        head) or None if infeasible. Caller holds the lock. The head pool
        is keyed "head" internally so it can't collide with the "no
        fitting pool" sentinel; planning itself is the pure module-level
        plan_gang_placement."""
        pools = [("head", self.available)]
        pools += [(nid, n.available) for nid, n in self.nodes.items()
                  if n.alive]
        pool_links = self._pool_links_locked()
        assignment = plan_gang_placement(
            pools, bundles, strategy, links=pool_links,
            link_load=self._link_load_locked(pool_links),
            bandwidth=bandwidth)
        if assignment is None:
            return None
        return [None if pid == "head" else pid for pid in assignment]

    def _try_reserve_pg_locked(self, bundles, strategy, bandwidth=0.0):
        """Assign + debit atomically (caller holds the lock); returns the
        new pg_id or None if currently infeasible."""
        assignment = self._assign_bundles(bundles, strategy, bandwidth)
        if assignment is None:
            return None
        for b, nid in zip(bundles, assignment):
            if nid is None:
                _sub(self.available, b)
            else:
                _sub(self.nodes[nid].available, b)
        pg_id = ids.new_placement_group_id()
        self.placement_groups[pg_id] = _PlacementGroup(
            pg_id, bundles, strategy, bundle_nodes=list(assignment),
            bandwidth=float(bandwidth or 0.0))
        return pg_id

    def create_placement_group(self, bundles, strategy="PACK", name="",
                               bandwidth=0.0):
        bundles = [dict(b) for b in bundles]
        with self.lock:
            pg_id = self._try_reserve_pg_locked(bundles, strategy,
                                                bandwidth)
        if pg_id is not None:
            return pg_id
        if getattr(self, "_autoscaler", None) is not None:
            # With an autoscaler attached an infeasible group is DEMAND,
            # not an error: park it on the gang queue (visible to
            # LoadMetrics) and retry as capacity arrives (reference:
            # PENDING placement groups feed the autoscaler). Reservation
            # happens under the lock inside the loop, so a concurrent
            # task debiting fresh capacity just sends us back to waiting
            # instead of failing the group early.
            deadline = time.monotonic() + config.get("PG_AUTOSCALE_WAIT_S")
            with self.cv:
                self._pending_gangs.append(bundles)
            try:
                while True:
                    with self.cv:
                        pg_id = self._try_reserve_pg_locked(
                            bundles, strategy, bandwidth)
                        if pg_id is not None:
                            return pg_id
                        rem = deadline - time.monotonic()
                        if rem <= 0 or self._shutdown:
                            break
                        self.cv.wait(min(rem, 0.5))
            finally:
                with self.cv:
                    self._pending_gangs.remove(bundles)
        raise PlacementGroupError(
            f"infeasible placement group ({strategy}): bundles {bundles}")

    def remove_placement_group(self, pg_id: str):
        with self.lock:
            pg = self.placement_groups.pop(pg_id, None)
            if pg is None:
                return False
            for b, nid in zip(pg.bundles, pg.bundle_nodes):
                if nid is None:
                    _add(self.available, b)
                else:
                    node = self.nodes.get(nid)
                    if node is not None and node.alive:
                        _add(node.available, b)
        self._schedule()
        return True

    # ------------------------------------------------------------------
    # shutdown
    # ------------------------------------------------------------------

    def shutdown(self):
        with self.lock:
            if self._shutdown:
                return
            self._shutdown = True
            workers = list(self.workers.values())
            nodes = list(self.nodes.values())
        try:
            self._usage_reporter.stop()
        except AttributeError:
            pass
        self._sched_event.set()   # release the scheduler thread
        for node in nodes:
            node.alive = False
            node.send(protocol.KillNode())
        for w in workers:
            w.send(protocol.KillWorker())
        for node in nodes:
            if node.proc is not None:
                try:
                    node.proc.wait(2.0)
                except Exception:
                    try:
                        node.proc.kill()
                    except OSError:
                        pass
        for lst in (self._listener, self._tcp_listener):
            if lst is None:
                continue
            try:
                lst.close()
            except OSError:
                pass
        deadline = time.monotonic() + 3.0
        for w in workers:
            if w.proc is None:
                continue
            try:
                while w.proc.poll() is None and time.monotonic() < deadline:
                    time.sleep(0.02)
                if w.proc.poll() is None:
                    w.proc.terminate()
                    try:
                        w.proc.wait(1.0)
                    except Exception:
                        w.proc.kill()
            except OSError:
                pass
        self.store.purge_spill()
        for node in nodes:
            # SIGKILLed daemons can't purge their own spill dirs
            shutil.rmtree(os.path.join(constants.OBJECT_SPILL_ROOT,
                                       node.node_id), ignore_errors=True)
        self.store.close()
        shutil.rmtree(self.session_dir, ignore_errors=True)
        atexit.unregister(self.shutdown)
