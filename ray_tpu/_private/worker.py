"""Process-global client state + the ObjectRef type.

Counterpart of the reference's `python/ray/_private/worker.py` global
`Worker` (the object `ray.init` populates and every API call goes through)
— but here the "core worker" has two concrete shapes sharing one interface:

- `DriverClient`: in-process calls straight into the NodeServer (the driver
  embeds its node, so `get`/`put` skip any socket hop);
- `WorkerClient`: the socket channel of `worker_main.WorkerRuntime`.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Sequence

from ray_tpu._private import ids
from ray_tpu.exceptions import RayTpuError, TaskError

# ---------------------------------------------------------------------------
# Reference counting (the distributed-refcount seam, reference:
# `src/ray/core_worker/reference_count.h:61` ReferenceCounter). Each process
# counts its live ObjectRef pythons per object id; the 0→1 and →0
# transitions are reported to the driver ("hold"/"release"), which frees an
# object once no process holds it, no queued/running task consumes it, and
# it never escaped. Escape = the ObjectRef was pickled into an arbitrary
# payload (nested in a value, stored in actor state, written to disk) — the
# pessimistic stand-in for the reference's borrower protocol: escaped
# objects live for the session. RAY_TPU_DISABLE_REFCOUNT=1 restores
# session-lifetime objects everywhere.
# ---------------------------------------------------------------------------

import os as _os

_REFCOUNT_DISABLED = _os.environ.get("RAY_TPU_DISABLE_REFCOUNT") == "1"
_track_lock = threading.Lock()
_local_counts: dict = {}
# __del__ may run re-entrantly mid-GC while _track_lock is held by the
# same thread, so decrements are only ever an atomic deque append; they
# are folded into the counts later from regular threads (_drain_decs).
import collections as _collections

_pending_decs: "_collections.deque[str]" = _collections.deque()


def _notify(kind: str, oid: str) -> None:
    client = _global_client
    if client is None:
        return
    try:
        if client.mode == "driver":
            if kind == "hold":
                client.node.ref_hold(oid, "driver")
            elif kind == "release":
                client.node.ref_release(oid, "driver")
            else:
                client.node.ref_escape(oid)
        elif client.mode == "worker":
            client.rt.enqueue_ref_event(kind, oid)
    except Exception:
        pass  # teardown races: losing a release only delays a free


def _drain_decs() -> None:
    """Fold queued __del__ decrements into the counts; emit releases."""
    if not _pending_decs:
        return
    released = []
    with _track_lock:
        while True:
            try:
                oid = _pending_decs.popleft()
            except IndexError:
                break
            n = _local_counts.get(oid, 0) - 1
            if n <= 0:
                _local_counts.pop(oid, None)
                if n == 0:
                    released.append(oid)
            else:
                _local_counts[oid] = n
    for oid in released:
        _notify("release", oid)


def _track_inc(oid: str) -> None:
    if _REFCOUNT_DISABLED:
        return
    _drain_decs()
    with _track_lock:
        n = _local_counts.get(oid, 0)
        _local_counts[oid] = n + 1
    if n == 0:
        _notify("hold", oid)


def _track_dec(oid: str) -> None:
    if _REFCOUNT_DISABLED:
        return
    try:
        _pending_decs.append(oid)   # GIL-atomic; folded in _drain_decs
    except Exception:
        pass  # interpreter shutdown


def _mark_escaped(oid: str) -> None:
    if _REFCOUNT_DISABLED:
        return
    _notify("escape", oid)


class ObjectRef:
    """A future for a task return or `put` value (reference: ObjectRef in
    `python/ray/includes/object_ref.pxi`). Identity is the object id string.
    Instances participate in distributed refcounting (above)."""

    __slots__ = ("_id",)

    def __init__(self, object_id: str):
        self._id = object_id
        _track_inc(object_id)

    def hex(self) -> str:
        return self._id

    def __del__(self):
        _track_dec(self._id)

    def __reduce__(self):
        # Pickling a ref means it may re-materialize anywhere (inside a
        # stored value, actor state, a file): mark it escaped so the
        # driver never frees it. Top-level task args bypass this — they
        # are encoded as ("ref", id) without pickling the ObjectRef.
        _mark_escaped(self._id)
        return (ObjectRef, (self._id,))

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id})"

    def future(self):
        """concurrent.futures.Future view (reference: ObjectRef.future)."""
        import concurrent.futures
        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _poll():
            try:
                fut.set_result(get(self))
            except BaseException as e:
                fut.set_exception(e)
        threading.Thread(target=_poll, daemon=True).start()
        return fut


def _stamp_trace_ctx(spec) -> None:
    """Stamp the submitter's trace context onto the outgoing TaskSpec.
    Central for BOTH client shapes, so driver submits, nested worker
    submits, and every actor-method call (serve handle→replica included)
    propagate the same way. The no-trace path is one ContextVar read —
    no env lookup, nothing recorded."""
    if spec.trace_ctx is None:
        from ray_tpu.util import tracing as _tracing
        spec.trace_ctx = _tracing.propagation_context()


class BaseClient:
    mode = "none"

    def get(self, refs, timeout=None):
        raise NotImplementedError

    def put(self, value) -> str:
        raise NotImplementedError

    def wait(self, object_ids, num_returns, timeout, fetch_local):
        raise NotImplementedError

    def submit(self, spec) -> None:
        raise NotImplementedError

    def control(self, method: str, payload=None,
                timeout: float | None = None):
        # `timeout` is a client-side transport deadline; in-process and
        # worker-channel clients have none and ignore it, the attach
        # client uses it so long-polls (pubsub) can outlast its default.
        raise NotImplementedError


class DriverClient(BaseClient):
    mode = "driver"

    def __init__(self, node):
        self.node = node
        self.job_id = ids.new_job_id()

    def get_values(self, object_ids, timeout=None):
        from ray_tpu.exceptions import ObjectLostError
        locs = self.node.get_locations(object_ids, timeout)
        out = []
        for o in object_ids:
            try:
                out.append(self.node.store.get(locs[o]))
            except ObjectLostError:
                # the descriptor went stale under us (spill/promotion
                # swapped the directory entry): one fresh lookup
                fresh = self.node.get_locations([o], timeout)
                out.append(self.node.store.get(fresh[o]))
        return out

    def put(self, value):
        return self.node.put_value(value)

    def put_serialized(self, payload: bytes) -> str:
        oid = ids.new_object_id()
        desc = self.node.store.put_serialized(oid, payload)
        self.node.register_object(oid, desc)
        return oid

    def wait(self, object_ids, num_returns, timeout, fetch_local):
        return self.node.wait_objects(object_ids, num_returns, timeout)

    def submit(self, spec):
        _stamp_trace_ctx(spec)
        self.node.submit(spec)

    def control(self, method, payload=None, timeout=None):
        return self.node._control(method, payload, None)


class WorkerClient(BaseClient):
    mode = "worker"

    def __init__(self, runtime):
        self.rt = runtime

    def get_values(self, object_ids, timeout=None):
        return self.rt.get_objects(object_ids, timeout)

    def put(self, value):
        return self.rt.put_object(value)

    def put_serialized(self, payload: bytes) -> str:
        from ray_tpu._private import protocol
        oid = ids.new_object_id()
        desc = self.rt.store.put_serialized(oid, payload)
        self.rt.send(protocol.PutRequest(oid, desc))
        return oid

    def wait(self, object_ids, num_returns, timeout, fetch_local):
        return self.rt.wait_objects(object_ids, num_returns, timeout,
                                    fetch_local)

    def submit(self, spec):
        _stamp_trace_ctx(spec)
        self.rt.submit_spec(spec)

    def control(self, method, payload=None, timeout=None):
        return self.rt.control(method, payload)


_global_client: BaseClient | None = None
_init_lock = threading.Lock()


def get_client() -> BaseClient:
    if _global_client is None:
        raise RayTpuError(
            "ray_tpu.init() has not been called in this process")
    if _pending_decs:
        _drain_decs()   # piggyback refcount housekeeping on API activity
    return _global_client


def is_initialized() -> bool:
    return _global_client is not None


def connect_driver_mode(node) -> DriverClient:
    global _global_client
    _global_client = DriverClient(node)
    return _global_client


def connect_worker_mode(runtime) -> WorkerClient:
    global _global_client
    _global_client = WorkerClient(runtime)
    return _global_client


def disconnect():
    global _global_client
    _global_client = None


# ---------------------------------------------------------------------------
# get / put / wait over the global client
# ---------------------------------------------------------------------------

def _raise_if_error(value):
    if isinstance(value, TaskError):
        raise value.as_instanceof_cause()
    if isinstance(value, RayTpuError):
        raise value
    return value


def get(refs, *, timeout: float | None = None):
    client = get_client()
    single = isinstance(refs, ObjectRef)
    ref_list: Sequence[ObjectRef] = [refs] if single else list(refs)
    for r in ref_list:
        if not isinstance(r, ObjectRef):
            raise TypeError(
                f"get() expects ObjectRef(s), got {type(r).__name__}")
    values = client.get_values([r._id for r in ref_list], timeout)
    values = [_raise_if_error(v) for v in values]
    return values[0] if single else values


def put(value) -> ObjectRef:
    if isinstance(value, ObjectRef):
        raise TypeError("put() of an ObjectRef is not allowed")
    return ObjectRef(get_client().put(value))


def wait(refs, *, num_returns: int = 1, timeout: float | None = None,
         fetch_local: bool = True):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    ref_list = list(refs)
    if len(set(r._id for r in ref_list)) != len(ref_list):
        raise ValueError("wait() got duplicate ObjectRefs")
    if num_returns > len(ref_list):
        raise ValueError("num_returns exceeds the number of refs")
    ready, not_ready = get_client().wait(
        [r._id for r in ref_list], num_returns, timeout, fetch_local)
    by_id = {r._id: r for r in ref_list}
    return [by_id[i] for i in ready], [by_id[i] for i in not_ready]
