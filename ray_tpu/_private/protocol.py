"""Control-plane message types between driver node and worker processes.

Counterpart of the reference's protobuf contracts (`src/ray/protobuf/
common.proto` TaskSpec, `core_worker.proto` PushTask, `node_manager.proto`
RequestWorkerLease). We use plain dataclasses over a length-framed pickle
channel (multiprocessing.connection); the field set intentionally mirrors the
reference's TaskSpec so a future gRPC/C++ transport can adopt it 1:1.
"""

from dataclasses import dataclass, field
from typing import Any

from ray_tpu._private.object_store import Descriptor


@dataclass
class TaskSpec:
    """Everything needed to run one task invocation (common.proto TaskSpec)."""
    task_id: str
    # Function: either a cached id (worker looks up its function table) plus
    # optional serialized bytes on first use (function_manager.py pattern).
    function_id: str
    function_blob: bytes | None  # cloudpickled callable; None if cached
    function_desc: str           # human-readable "module.fn" for errors/logs
    # Positional/keyword args: values are either ("v", inline_envelope_bytes)
    # or ("ref", object_id) — top-level ObjectRefs are resolved before the
    # task runs, like the reference's dependency_resolver.h.
    args: list = field(default_factory=list)
    kwargs: dict = field(default_factory=dict)
    num_returns: int = 1
    return_ids: list = field(default_factory=list)
    resources: dict = field(default_factory=dict)
    # Actor fields
    actor_id: str | None = None          # target actor for method calls
    actor_creation: bool = False         # this spec constructs the actor
    method_name: str | None = None
    max_retries: int = 0
    retry_exceptions: bool = False
    runtime_env: dict | None = None
    placement_group_id: str | None = None
    # Name shown in state API / dashboards.
    name: str = ""


# ---- driver -> worker -----------------------------------------------------

@dataclass
class PushTask:
    """Dispatch one task to a leased worker (core_worker.proto PushTask).

    `arg_locations` maps object_id -> Descriptor for every ref argument, so
    the worker can mmap dependencies without a round trip.
    """
    spec: TaskSpec
    arg_locations: dict[str, Descriptor] = field(default_factory=dict)


@dataclass
class KillWorker:
    graceful: bool = True


@dataclass
class FreeObject:
    """Driver -> origin worker: all references to this object are gone;
    drop your put-time owner pin and delete it from the shared store
    (the reference's FreeObjects / out-of-scope deletion path)."""
    object_id: str
    desc: Descriptor


# ---- worker -> driver -----------------------------------------------------

@dataclass
class RegisterWorker:
    worker_id: str
    pid: int


@dataclass
class TaskDone:
    """Task finished; returns are sealed. Error is a serialized TaskError
    envelope stored as the return value (reference stores error objects in
    plasma the same way)."""
    task_id: str
    return_descs: list  # list[Descriptor], parallel to spec.return_ids
    error: bool = False
    # For actor creation tasks: advertises readiness.
    actor_ready: bool = False


@dataclass
class PutRequest:
    """Worker already wrote the object into the store; register it."""
    object_id: str
    desc: Descriptor


@dataclass
class GetRequest:
    """Blocking fetch of object locations; driver replies GetReply when all
    are ready (or timeout). Issuing worker's CPU resources are released while
    blocked, as in the reference (worker blocked-on-get releases its lease)."""
    req_id: int
    object_ids: list
    timeout: float | None = None


@dataclass
class GetReply:
    req_id: int
    locations: dict          # object_id -> Descriptor
    timed_out: bool = False


@dataclass
class WaitRequest:
    req_id: int
    object_ids: list
    num_returns: int
    timeout: float | None = None
    fetch_local: bool = True


@dataclass
class WaitReply:
    req_id: int
    ready: list
    not_ready: list


@dataclass
class SubmitRequest:
    """Nested task/actor submission from inside a worker."""
    req_id: int
    spec: TaskSpec


@dataclass
class SubmitReply:
    req_id: int
    ok: bool = True
    error: str | None = None


@dataclass
class ActorCallRequest:
    """Generic control-plane RPC: named-actor lookup, kill, KV ops, etc.
    `method` selects a NodeServer handler; `payload` is method-specific."""
    req_id: int
    method: str
    payload: Any = None


@dataclass
class ActorCallReply:
    req_id: int
    result: Any = None
    error: str | None = None
