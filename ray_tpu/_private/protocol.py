"""Control-plane message types between driver node and worker processes.

Counterpart of the reference's protobuf contracts (`src/ray/protobuf/
common.proto` TaskSpec, `core_worker.proto` PushTask, `node_manager.proto`
RequestWorkerLease). We use plain dataclasses over a length-framed pickle
channel (multiprocessing.connection); the field set intentionally mirrors the
reference's TaskSpec so a future gRPC/C++ transport can adopt it 1:1.
"""

import threading
from dataclasses import dataclass, field
from typing import Any

from ray_tpu._private.object_store import Descriptor


def safe_send(conn, lock, msg) -> bool:
    """Best-effort locked send on an mp.Connection: False on a dead/absent
    peer instead of raising. The single implementation behind every
    channel's `send` (head<->worker, head<->daemon, daemon<->peer)."""
    with lock:
        if conn is None:
            return False
        try:
            conn.send(msg)
            return True
        except (OSError, ValueError, BrokenPipeError):
            return False


class SafeConn:
    """Callable wrapper bundling a connection with its send lock."""

    def __init__(self, conn):
        self.conn = conn
        self._lock = threading.Lock()

    def __call__(self, msg) -> bool:
        return safe_send(self.conn, self._lock, msg)

    send = __call__


@dataclass
class TaskSpec:
    """Everything needed to run one task invocation (common.proto TaskSpec)."""
    task_id: str
    # Function: either a cached id (worker looks up its function table) plus
    # optional serialized bytes on first use (function_manager.py pattern).
    function_id: str
    function_blob: bytes | None  # cloudpickled callable; None if cached
    function_desc: str           # human-readable "module.fn" for errors/logs
    # Positional/keyword args: values are either ("v", inline_envelope_bytes)
    # or ("ref", object_id) — top-level ObjectRefs are resolved before the
    # task runs, like the reference's dependency_resolver.h.
    args: list = field(default_factory=list)
    kwargs: dict = field(default_factory=dict)
    num_returns: int = 1
    return_ids: list = field(default_factory=list)
    resources: dict = field(default_factory=dict)
    # Actor fields
    actor_id: str | None = None          # target actor for method calls
    actor_creation: bool = False         # this spec constructs the actor
    method_name: str | None = None
    max_retries: int = 0
    retry_exceptions: bool = False
    # User runtime environment ONLY (env_vars/working_dir/pip/py_modules —
    # _private/runtime_env.py schema). Actor options live in
    # `actor_options`, scheduling hints in `scheduling_strategy`; they
    # were previously smuggled through runtime_env as _-prefixed keys.
    runtime_env: dict | None = None
    placement_group_id: str | None = None
    # Actor creation options: max_concurrency, max_restarts,
    # max_task_retries, name, method_meta.
    actor_options: dict | None = None
    # "SPREAD" | {"node_id": ..., "soft": ...} | None (node.py _pick_node).
    scheduling_strategy: object = None
    # Name shown in state API / dashboards.
    name: str = ""
    # Distributed tracing: the submitter's `util.tracing`
    # propagation_context() — {"trace_id", "span_id"} — stamped by the
    # client submit paths (_private/worker.py) when a trace is active.
    # The executing worker attaches it and opens a `task.execute` span,
    # so one trace id survives every process hop (the reference carries
    # the OTel context in TaskSpec the same way).
    trace_ctx: dict | None = None


# ---- driver -> worker -----------------------------------------------------

@dataclass
class PushTask:
    """Dispatch one task to a leased worker (core_worker.proto PushTask).

    `arg_locations` maps object_id -> Descriptor for every ref argument, so
    the worker can mmap dependencies without a round trip.
    """
    spec: TaskSpec
    arg_locations: dict[str, Descriptor] = field(default_factory=dict)


@dataclass
class KillWorker:
    graceful: bool = True


@dataclass
class SetTracing:
    """Head -> worker/daemon broadcast: flip span recording in processes
    that were already running when the driver called
    `tracing.enable_tracing()` (later spawns inherit the env var)."""
    enabled: bool = True


@dataclass
class FreeObject:
    """Driver -> origin worker: all references to this object are gone;
    drop your put-time owner pin and delete it from the shared store
    (the reference's FreeObjects / out-of-scope deletion path)."""
    object_id: str
    desc: Descriptor


# ---- worker -> driver -----------------------------------------------------

@dataclass
class RegisterWorker:
    worker_id: str
    pid: int


@dataclass
class TaskDone:
    """Task finished; returns are sealed. Error is a serialized TaskError
    envelope stored as the return value (reference stores error objects in
    plasma the same way)."""
    task_id: str
    return_descs: list  # list[Descriptor], parallel to spec.return_ids
    error: bool = False
    # For actor creation tasks: advertises readiness.
    actor_ready: bool = False
    # Worker-side execution timestamps (epoch seconds): the head's
    # TaskEventRecorder turns dispatched→start→end into the dispatch /
    # execute stage latencies (worker-buffered task events in the
    # reference carry the same state timestamps).
    exec_start_ts: float | None = None
    exec_end_ts: float | None = None
    # Tracing spans drained from this worker's ring, piggybacked so the
    # head's merged timeline is current the moment the task completes
    # (long gaps between completions are covered by the metrics flush).
    spans: list | None = None


@dataclass
class PutRequest:
    """Worker already wrote the object into the store; register it.
    `origin` carries the putting worker's id when the request is relayed
    through a HostDaemon (the implicit ref-hold must be keyed by the worker
    whose later release event clears it)."""
    object_id: str
    desc: Descriptor
    origin: str | None = None


@dataclass
class GetRequest:
    """Blocking fetch of object locations; driver replies GetReply when all
    are ready (or timeout). Issuing worker's CPU resources are released while
    blocked, as in the reference (worker blocked-on-get releases its lease)."""
    req_id: int
    object_ids: list
    timeout: float | None = None


@dataclass
class GetReply:
    req_id: int
    locations: dict          # object_id -> Descriptor
    timed_out: bool = False
    # "ExceptionClassName: message" when the get failed terminally (object
    # freed by refcounting or lost with a node); the worker re-raises.
    error: str | None = None


@dataclass
class WaitRequest:
    req_id: int
    object_ids: list
    num_returns: int
    timeout: float | None = None
    fetch_local: bool = True


@dataclass
class WaitReply:
    req_id: int
    ready: list
    not_ready: list


@dataclass
class SubmitRequest:
    """Nested task/actor submission from inside a worker. `submitter`
    carries the submitting worker's id when relayed through a HostDaemon
    (implicit holds on the fresh return refs must be keyed by it).

    Two delivery modes share this type:

    * classic (``seq is None``): one blocking round trip, the receiver
      answers with a SubmitReply keyed by ``req_id``;
    * pipelined (``seq >= 0``): the worker streams specs without
      per-task acks under a credit window. ``seq`` is the per-channel
      monotone sequence number; the receiver applies in-order arrivals,
      drops duplicates (replays), nacks gaps (SubmitNack), and returns
      flow-control credit (SubmitCredit). ``req_id`` is ``-1`` — no
      reply is ever sent for a pipelined submission; failures surface
      as error objects stored under the spec's return ids.
    """
    req_id: int
    spec: TaskSpec
    submitter: str | None = None
    seq: int | None = None


@dataclass
class SubmitReply:
    req_id: int
    ok: bool = True
    error: str | None = None


@dataclass
class SubmitCredit:
    """Head/daemon -> worker: every pipelined SubmitRequest with
    ``seq <= ack_seq`` has been applied (or deduped); the worker prunes
    its replay ring and opens the submit window."""
    ack_seq: int


@dataclass
class SubmitNack:
    """Head/daemon -> worker: a pipelined SubmitRequest arrived out of
    order (a frame was lost); replay the ring from ``expected_seq`` in
    order. Out-of-order arrivals past the gap are dropped, so replay
    restores contiguity without reordering."""
    expected_seq: int


@dataclass
class ActorCallRequest:
    """Generic control-plane RPC: named-actor lookup, kill, KV ops, etc.
    `method` selects a NodeServer handler; `payload` is method-specific."""
    req_id: int
    method: str
    payload: Any = None


@dataclass
class ActorCallReply:
    req_id: int
    result: Any = None
    error: str | None = None


@dataclass
class ErrorReply:
    """Type-agnostic failure reply for an in-flight request whose real
    reply can never come (e.g. the head restarted and lost the req id).
    Request issuers treat it as a terminal error regardless of which
    reply type they expected."""
    req_id: int
    error: str


# ---- multi-node control plane (head <-> per-host daemon) ------------------
#
# The head process keeps the cluster store + cluster scheduler (the
# reference's GCS, gcs_server.h:78); each additional host runs a HostDaemon
# (the raylet, node_manager.h:117) owning its local object store, worker
# pool, and task execution. These messages are the raylet<->GCS and
# object-manager (object_manager.h:130,139 Push/Pull) contracts.

@dataclass
class RegisterNode:
    """Daemon -> head: first message on the node channel. On RE-register
    (daemon reconnecting after a head restart — reference:
    NotifyGCSRestart, node_manager.proto:358) `actors`/`objects` carry
    the daemon's surviving state so the head can re-attach live actors
    and rebuild its object directory."""
    node_id: str
    pid: int
    resources: dict
    num_tpu_chips: int = 0
    address: str = ""            # daemon's own listener, for peer pulls
    actors: dict | None = None   # actor_id -> {} live on this node
    objects: dict | None = None  # oid -> tagged Descriptor sealed here
    # On RE-register: every lease task id this daemon received and whose
    # outcome the head will still learn (running, or terminal message
    # retained in the NodeSeq replay ring). A lease the head holds
    # inflight that is NOT listed was swallowed by the channel blip —
    # the head must re-dispatch it instead of waiting forever.
    leases: list | None = None
    # Interconnect link groups (ICI ring / DCN pod ids) this node hangs
    # off, from RAY_TPU_LINK_GROUPS — the contention-aware gang
    # placement model (2207.07817) scores PACK/SPREAD candidates by
    # per-link load from already-placed bandwidth-hungry gangs.
    link_groups: list | None = None


@dataclass
class NodeSeq:
    """Daemon -> head reliability envelope. TCP gives no delivery
    guarantee across a channel blip (the first send() into a half-closed
    socket succeeds silently), so every reliable daemon->head message
    carries a per-daemon monotone seq; the daemon retains a replay ring
    and re-sends it after reconnect-and-reregister, and the head drops
    seq <= last_seq duplicates. Lossy streams (LogBatch, PullChunk) ride
    unwrapped. Reference analogue: gRPC request/retry semantics on the
    raylet->GCS edges."""
    seq: int
    inner: object


@dataclass
class LeaseTask:
    """Head -> daemon: run this task on your node (the lease+push pipeline
    of the reference collapsed into one hop, direct_task_transport.h:75).

    `arg_locations` carries the directory's descriptors, which may point at
    other nodes; the daemon pulls whatever isn't local before dispatch.
    `peer_addrs` maps node_id -> daemon listener address for those pulls.
    """
    spec: TaskSpec
    arg_locations: dict = field(default_factory=dict)
    peer_addrs: dict = field(default_factory=dict)
    tpu_chips: list = field(default_factory=list)


@dataclass
class NodeTaskDone:
    """Daemon -> head: a leased task finished; returns are sealed in the
    daemon's store (descriptors tagged with its node id). Carries the
    worker's execution timestamps and drained tracing spans up the relay
    (TaskDone -> daemon -> head) unchanged."""
    task_id: str
    return_descs: list
    error: bool = False
    actor_ready: bool = False
    exec_start_ts: float | None = None
    exec_end_ts: float | None = None
    spans: list | None = None


@dataclass
class NodeTaskFailed:
    """Daemon -> head: a leased task's worker died or its deps were lost;
    the head decides retry vs error (task_manager.h:173)."""
    task_id: str
    error: str = ""


@dataclass
class NodeActorDied:
    """Daemon -> head: an actor's dedicated worker process died while idle
    (in-flight deaths also arrive as NodeTaskFailed per task)."""
    actor_id: str
    cause: str = ""


@dataclass
class NodeWorkerGone:
    """Daemon -> head: a worker process on this node exited; drop its
    ref-holder entries (the head does the same for local worker deaths)."""
    worker_id: str


@dataclass
class NodeWorkerBlocked:
    """Daemon -> head: the worker running `task_id` blocked in get()
    (blocked=True) or resumed (False); the head releases/re-takes its
    resources like the local blocked-on-get path."""
    task_id: str
    blocked: bool


@dataclass
class PullRequest:
    """Ask the receiving node for an object's serialized bytes
    (object_manager.h:139 HandlePull)."""
    req_id: int
    object_id: str


@dataclass
class PullChunk:
    """Chunked reply to PullRequest (object_manager.h:130 HandlePush uses
    the same chunking; ObjectBufferPool's chunk size analog). `total`
    rides the first chunk so the receiver preallocates one buffer
    instead of accumulating parts + a join copy.

    Zero-copy framing: when `data is None` and `nbytes >= 0`, this
    header is immediately followed on the SAME channel by a raw
    `send_bytes` frame of nbytes (written under one send-lock hold);
    the receiver lands it with `recv_bytes_into` straight into the
    pull's destination buffer — no pickle copy on either side. Error
    and empty-object chunks keep `data=b""`."""
    req_id: int
    seq: int
    data: bytes | None
    last: bool = False
    error: str | None = None
    total: int = -1
    nbytes: int = -1
    offset: int = 0


@dataclass
class DumpStack:
    """Head/daemon -> worker: report every thread's Python stack
    (reference: on-demand py-spy/`ray stack` profiling,
    dashboard/modules/reporter/profile_manager.py:10-25 — here the
    worker samples itself via sys._current_frames, no ptrace needed).
    `worker_id` filters when fanned out through a daemon (None = all)."""
    req_id: int
    worker_id: str | None = None


@dataclass
class StackDumpReply:
    """Worker -> daemon -> head: the formatted stacks."""
    req_id: int
    worker_id: str
    pid: int
    text: str


@dataclass
class LogBatch:
    """Daemon -> head (and head -> subscribed drivers): new stdout/stderr
    lines tailed from one process's log file (reference: log_monitor.py
    publishing to the driver via GCS pubsub)."""
    source: str              # e.g. "worker-<id>" | "daemon-<node_id>"
    node_id: str | None      # None = head host
    lines: list = None


@dataclass
class RegisterPeer:
    """Daemon -> daemon: first message on a peer data channel; the
    connecting side then issues PullRequests on it."""
    node_id: str


@dataclass
class ObjectCopyNote:
    """Daemon -> head: this node cached a copy of the object (enables
    promotion to primary if the owner node dies — object recovery from
    another copy, object_recovery_manager.h:41). `desc` is the copy's OWN
    descriptor (tagged with node_id): the copy's backing (arena vs file)
    can differ from the primary's, so promotion must use it verbatim."""
    object_id: str
    node_id: str
    desc: Descriptor | None = None


@dataclass
class FreeObjectNode:
    """Head -> daemon: drop this object (primary or cached copy) from your
    store; forward the owner-pin release to the origin worker."""
    object_id: str


@dataclass
class KillActorOnNode:
    """Head -> daemon: terminate the worker hosting this actor."""
    actor_id: str


@dataclass
class KillNode:
    """Head -> daemon: graceful node shutdown."""
    graceful: bool = True
