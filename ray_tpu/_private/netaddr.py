"""Address plumbing for the control/data channels.

Channels ride `multiprocessing.connection` with HMAC authkey handshakes;
this module lets every channel be EITHER a UNIX socket (same-host: workers
to their daemon, single-host sessions) or TCP ("host:port" — daemons and
client drivers joining a head across machines, peer-to-peer object pulls
between hosts). The reference splits the same way: UDS to the local
raylet, gRPC over TCP for everything cross-host.
"""

from __future__ import annotations

import socket
from multiprocessing import connection


def is_tcp(address) -> bool:
    if isinstance(address, tuple):
        return True
    return (isinstance(address, str) and ":" in address
            and not address.startswith("/"))


def parse(address):
    """'host:port' -> (host, port); path/tuple passes through."""
    if isinstance(address, tuple) or not is_tcp(address):
        return address
    host, _, port = address.rpartition(":")
    return (host or "127.0.0.1", int(port))


def fmt(address) -> str:
    if isinstance(address, tuple):
        return f"{address[0]}:{address[1]}"
    return address


def client(address, authkey: bytes):
    addr = parse(address)
    family = "AF_INET" if isinstance(addr, tuple) else "AF_UNIX"
    conn = connection.Client(addr, family=family, authkey=authkey)
    # Fault injection seam: while a FaultPlan with netaddr.* sites is
    # installed, new outbound channels get the delay/drop proxy (the
    # authkey handshake above always runs on the raw socket).
    from ray_tpu.util import faults
    return faults.maybe_wrap_connection(conn, "netaddr")


def listener(address, authkey: bytes):
    addr = parse(address)
    family = "AF_INET" if isinstance(addr, tuple) else "AF_UNIX"
    return connection.Listener(addr, family=family, authkey=authkey)


def bound_address(listener) -> str:
    """'host:port' (or path) a peer should dial for this listener; resolves
    ephemeral ports and 0.0.0.0 binds to the advertised host."""
    addr = listener.address
    if isinstance(addr, tuple):
        host, port = addr
        if host in ("0.0.0.0", ""):
            host = advertise_host()
        return f"{host}:{port}"
    return addr


def local_endpoint_host(conn) -> str | None:
    """The local IP of an established TCP connection — exactly the
    interface that routes to the remote side, so it's the right host for
    this machine to advertise back to it."""
    import os
    try:
        fd = os.dup(conn.fileno())
        s = socket.socket(fileno=fd)
        try:
            name = s.getsockname()
        finally:
            s.close()
        if isinstance(name, tuple):
            return name[0]
    except OSError:
        pass
    return None


def advertise_host() -> str:
    """The address other machines should dial for listeners bound on
    0.0.0.0 (reference: node_ip_address detection in services.py)."""
    from ray_tpu._private import config
    override = config.get("NODE_IP")
    if override:
        return override
    try:
        # a UDP "connection" to a public address picks the outbound iface
        # without sending anything
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 80))
        host = s.getsockname()[0]
        s.close()
        return host
    except OSError:
        return "127.0.0.1"
