"""Address plumbing + batched framing for the control/data channels.

Channels ride `multiprocessing.connection` with HMAC authkey handshakes;
this module lets every channel be EITHER a UNIX socket (same-host: workers
to their daemon, single-host sessions) or TCP ("host:port" — daemons and
client drivers joining a head across machines, peer-to-peer object pulls
between hosts). The reference splits the same way: UDS to the local
raylet, gRPC over TCP for everything cross-host.

Every channel built here additionally carries the coalescing frame layer
(`BatchedConnection`): logical `send()`s land in an outbound queue that a
per-channel flusher drains into ONE wire pickle per flush, and `recv()`
unpacks frames back into individual messages. Bursts (completion storms,
lease fan-outs, metrics piggybacks) collapse from N syscalls + N pickles
into one of each, while per-channel FIFO order and per-logical-message
fault injection (`faults.maybe_wrap_connection` wraps OUTSIDE the frame
layer) are preserved. `RAY_TPU_CHANNEL_BATCHING=0` turns coalescing off;
the receive side always understands both framings, so mixed settings
across processes stay wire-compatible.
"""

from __future__ import annotations

import collections
import socket
import threading
import time
from multiprocessing import connection

from ray_tpu._private import config
from ray_tpu._private.constants import CHANNEL_QUEUE_CAP
from ray_tpu.util import faults


def is_tcp(address) -> bool:
    if isinstance(address, tuple):
        return True
    return (isinstance(address, str) and ":" in address
            and not address.startswith("/"))


def parse(address):
    """'host:port' -> (host, port); path/tuple passes through."""
    if isinstance(address, tuple) or not is_tcp(address):
        return address
    host, _, port = address.rpartition(":")
    return (host or "127.0.0.1", int(port))


def fmt(address) -> str:
    if isinstance(address, tuple):
        return f"{address[0]}:{address[1]}"
    return address


class _Batch:
    """Wire frame carrying several logical messages in one send. Plain
    pickle-friendly holder; both ends of every channel run this module,
    so the class is always importable at unpickle time."""

    __slots__ = ("msgs",)

    def __init__(self, msgs):
        self.msgs = msgs


class BatchedConnection:
    """Coalescing wrapper over one mp.Connection.

    Send side: `send()` appends to an outbound deque and wakes the
    flusher thread, which drains the WHOLE deque into a single wire
    frame (`_Batch`) per pass — so messages queued while a previous
    frame is on the wire ride the next frame together. `send_bytes`
    (the PullChunk zero-copy raw frame) first flushes pending logical
    messages under the wire lock, then writes the raw frame under the
    same hold: a chunk header queued immediately before is guaranteed
    to be the wire frame right before its payload.

    Recv side: single-reader (every channel here has exactly one reader
    thread). Frames are unpacked into an inbound deque that `recv()`
    drains FIFO; `recv_bytes`/`recv_bytes_into` bypass the deque and
    read the wire directly, which is exactly the raw-frame adjacency
    the pull plane relies on.

    Wire errors on the flusher are latched and re-raised from the next
    `send()` so `protocol.safe_send` sees the usual OSError surface.
    """

    def __init__(self, conn, coalesce: bool | None = None):
        self._raw = conn
        if coalesce is None:
            coalesce = config.get("CHANNEL_BATCHING")
        self._coalesce = bool(coalesce)
        self._in: collections.deque = collections.deque()
        self._out: collections.deque = collections.deque()
        self._qcv = threading.Condition()
        self._wire_lock = threading.Lock()
        self._err: BaseException | None = None
        self._closed = False
        self._flushing = False   # a popped batch is still on the wire
        if self._coalesce:
            threading.Thread(target=self._flush_loop, daemon=True,
                             name="netaddr-flush").start()

    # ---- send side --------------------------------------------------------

    def send(self, msg) -> None:
        if not self._coalesce:
            self._raw.send(msg)
            return
        direct = False
        with self._qcv:
            if self._err is not None:
                raise self._err
            if self._closed:
                raise OSError("connection is closed")
            # Opportunistic direct write: when nothing is queued and no
            # popped batch is in flight (`_flushing` covers the window
            # where the flusher holds messages that are no longer in
            # `_out`), the wire is keeping up — write inline and skip
            # the flusher handoff entirely. Sparse senders (a worker's
            # one TaskDone per task, the head's per-dispatch PushTask)
            # pay zero thread wakes; only senders that outrun the wire
            # fall into the queue, which is exactly when coalescing
            # pays. The try-acquire is deadlock-free against the
            # flusher's wire->queue order, and FIFO holds: the wire
            # lock is taken while the queue is provably empty, so no
            # earlier logical message can be written after this one.
            if (not self._out and not self._flushing
                    and self._wire_lock.acquire(blocking=False)):
                direct = True
            else:
                while len(self._out) >= CHANNEL_QUEUE_CAP:
                    # a raw full pipe would block the sender here too
                    self._qcv.wait(0.05)
                    if self._err is not None:
                        raise self._err
                    if self._closed:
                        raise OSError("connection is closed")
                self._out.append(msg)
                self._qcv.notify_all()
        if direct:
            try:
                self._raw.send(msg)
            except Exception as e:
                err = e if isinstance(e, OSError) else OSError(str(e))
                with self._qcv:
                    self._err = err
                    self._qcv.notify_all()
                raise err
            finally:
                self._wire_lock.release()

    def _pop_pending(self) -> list:
        with self._qcv:
            if not self._out:
                return []
            batch = list(self._out)
            self._out.clear()
            self._flushing = True
            self._qcv.notify_all()   # backpressure waiters
            return batch

    def _done_flushing(self) -> None:
        with self._qcv:
            self._flushing = False
            self._qcv.notify_all()

    def _send_frame_locked(self, batch: list) -> None:
        if len(batch) == 1:
            self._raw.send(batch[0])
        else:
            self._raw.send(_Batch(batch))

    def _flush_loop(self) -> None:
        while True:
            with self._qcv:
                while not self._out and not self._closed:
                    self._qcv.wait()
                if self._closed and not self._out:
                    return
            while True:
                batch = self._pop_pending()
                if not batch:
                    break
                try:
                    with self._wire_lock:
                        self._send_frame_locked(batch)
                except Exception as e:
                    with self._qcv:
                        self._err = (e if isinstance(e, OSError)
                                     else OSError(str(e)))
                        self._flushing = False
                        self._qcv.notify_all()
                    return
                finally:
                    self._done_flushing()

    def send_bytes(self, buf, offset: int = 0, size=None) -> None:
        with self._wire_lock:
            batch = self._pop_pending()
            try:
                if batch:
                    self._send_frame_locked(batch)
                if size is None:
                    self._raw.send_bytes(buf, offset)
                else:
                    self._raw.send_bytes(buf, offset, size)
            finally:
                if batch:
                    self._done_flushing()

    def flush(self, timeout: float = 1.0) -> None:
        """Best-effort: wait until queued messages reached the wire."""
        deadline = time.monotonic() + timeout
        with self._qcv:
            while (self._out or self._flushing) and self._err is None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return
                self._qcv.wait(remaining)

    # ---- recv side (single reader) ----------------------------------------

    def recv(self):
        if self._in:
            return self._in.popleft()
        msg = self._raw.recv()
        if type(msg) is _Batch:
            self._in.extend(msg.msgs)
            return self._in.popleft()
        return msg

    def poll(self, timeout: float = 0.0) -> bool:
        if self._in:
            return True
        return self._raw.poll(timeout)

    def recv_bytes(self, maxlength=None):
        if maxlength is None:
            return self._raw.recv_bytes()
        return self._raw.recv_bytes(maxlength)

    def recv_bytes_into(self, buf, offset: int = 0) -> int:
        return self._raw.recv_bytes_into(buf, offset)

    # ---- lifecycle --------------------------------------------------------

    def close(self) -> None:
        self.flush(timeout=0.5)
        with self._qcv:
            self._closed = True
            self._qcv.notify_all()
        self._raw.close()

    def fileno(self) -> int:
        return self._raw.fileno()

    @property
    def closed(self):
        return getattr(self._raw, "closed", self._closed)

    def __getattr__(self, name):
        return getattr(self._raw, name)


class _BatchingListener:
    """netaddr.listener wrapper: accepted connections get the frame
    layer, so the server side of every channel can unpack `_Batch`
    frames regardless of the client's coalescing setting."""

    def __init__(self, inner):
        self._inner = inner

    def accept(self):
        return BatchedConnection(self._inner.accept())

    @property
    def address(self):
        return self._inner.address

    def close(self):
        return self._inner.close()

    def __getattr__(self, name):
        return getattr(self._inner, name)


def client(address, authkey: bytes):
    addr = parse(address)
    family = "AF_INET" if isinstance(addr, tuple) else "AF_UNIX"
    conn = connection.Client(addr, family=family, authkey=authkey)
    # Frame layer first, fault proxy OUTSIDE it: while a FaultPlan with
    # netaddr.* sites is installed, drop/delay decisions and visit
    # numbering stay per LOGICAL message (the batch framing underneath
    # is invisible to the plan). The authkey handshake above always
    # runs on the raw socket.
    return faults.maybe_wrap_connection(BatchedConnection(conn), "netaddr")


def listener(address, authkey: bytes):
    addr = parse(address)
    family = "AF_INET" if isinstance(addr, tuple) else "AF_UNIX"
    return _BatchingListener(
        connection.Listener(addr, family=family, authkey=authkey))


def bound_address(listener) -> str:
    """'host:port' (or path) a peer should dial for this listener; resolves
    ephemeral ports and 0.0.0.0 binds to the advertised host."""
    addr = listener.address
    if isinstance(addr, tuple):
        host, port = addr
        if host in ("0.0.0.0", ""):
            host = advertise_host()
        return f"{host}:{port}"
    return addr


def local_endpoint_host(conn) -> str | None:
    """The local IP of an established TCP connection — exactly the
    interface that routes to the remote side, so it's the right host for
    this machine to advertise back to it."""
    import os
    try:
        fd = os.dup(conn.fileno())
        s = socket.socket(fileno=fd)
        try:
            name = s.getsockname()
        finally:
            s.close()
        if isinstance(name, tuple):
            return name[0]
    except OSError:
        pass
    return None


# advertise_host is on the connect path of every channel; the UDP-socket
# interface probe is memoized (it cannot change without the host's
# routing table changing) and the NODE_IP override is re-read per call —
# an env read, not a socket. config.reset_caches() flushes the probe.
_advertise_lock = threading.Lock()
_advertised: str | None = None


@config.on_reset
def _reset_advertise_cache() -> None:
    global _advertised
    with _advertise_lock:
        _advertised = None


def advertise_host() -> str:
    """The address other machines should dial for listeners bound on
    0.0.0.0 (reference: node_ip_address detection in services.py)."""
    override = config.get("NODE_IP")
    if override:
        return override
    global _advertised
    host = _advertised
    if host is not None:
        return host
    with _advertise_lock:
        if _advertised is None:
            try:
                # a UDP "connection" to a public address picks the
                # outbound iface without sending anything
                s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                s.connect(("8.8.8.8", 80))
                _advertised = s.getsockname()[0]
                s.close()
            except OSError:
                _advertised = "127.0.0.1"
        return _advertised
