"""Warm-fork worker factory: pay interpreter + module import once.

Counterpart of the reference's prestarted worker pool
(`src/ray/raylet/worker_pool.h:80` + prestart-on-backlog
`node_manager.cc:1885`): cold worker exec on this image costs ~140ms of
imports (and ~2.3s where the platform sitecustomize pulls jax), which
caps actor creation at a few per second. This process imports the worker
module tree ONCE under the CPU-worker site hook, then forks per request
— a child is live in milliseconds and initializes its own jax backend
lazily if user code ever imports it (fork happens strictly before any
backend exists, the one ordering that makes fork+jax safe).

Only the common case forks: CPU workers with no runtime-env interpreter/
cwd/path overrides. TPU-chip workers (env must gate plugin registration
pre-import) and venv workers (different interpreter) still exec.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
from multiprocessing import connection


def _proc_start(pid: int):
    """Kernel start ticks of `pid` (/proc stat f22, paren-safe), or
    None if it is already gone."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            data = f.read()
        return int(data.rsplit(b")", 1)[1].split()[19])
    except (OSError, ValueError, IndexError):
        return None


def _reap(signum, frame):
    try:
        while True:
            pid, _ = os.waitpid(-1, os.WNOHANG)
            if pid == 0:
                break
    except ChildProcessError:
        pass


def _spawn_child(req: dict) -> int:
    import warnings
    with warnings.catch_warnings():
        # CPython warns on fork-from-multithreaded generically; the
        # factory's extra threads (parent watcher, per-spawner serve
        # loops) only sleep/recv and hold no locks the child touches —
        # the child immediately re-execs worker_main.run on fresh state
        warnings.simplefilter("ignore", DeprecationWarning)
        pid = os.fork()
    if pid != 0:
        return pid
    # ---- child ----
    try:
        signal.signal(signal.SIGCHLD, signal.SIG_DFL)
        os.setsid()                      # own group: group kills don't
        # reach the factory or siblings
        log_path = req.get("log_path")
        devnull = os.open(os.devnull, os.O_RDONLY)
        os.dup2(devnull, 0)
        if log_path:
            fd = os.open(log_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                         0o644)
            os.dup2(fd, 1)
            os.dup2(fd, 2)
        os.environ.clear()
        os.environ.update(req["env"])
        from ray_tpu._private import ids as _ids
        _ids.reseed()       # forked children must not replay the
        # factory's id stream (duplicate object ids across siblings)
        from ray_tpu._private import worker_main
        worker_main.run(req["address"], req["worker_id"])
        os._exit(0)
    except BaseException:
        import traceback
        traceback.print_exc()
        os._exit(1)


def _watch_parent(ppid: int, sock_path: str):
    """The factory must not outlive its spawner (head/daemon): orphaned
    factories would leak across sessions."""
    import time
    while os.getppid() == ppid:
        time.sleep(1.0)
    try:
        os.unlink(sock_path)
    except OSError:
        pass
    os._exit(0)


def main():
    sock_path = sys.argv[1]
    authkey = bytes.fromhex(os.environ["RAY_TPU_AUTHKEY"])
    # Preload the full worker import tree (the fork dividend). Worker
    # site hook + FORCE_CPU in our env keep accelerator plugins out.
    # asyncio matters measurably: this image ships no stdlib .pyc cache,
    # so a cold `import asyncio` (async actor runtime, main_loop) costs
    # ~85ms of bytecode compilation per child without the preload.
    import asyncio  # noqa: F401
    from ray_tpu._private import worker_main  # noqa: F401
    signal.signal(signal.SIGCHLD, _reap)
    threading.Thread(target=_watch_parent,
                     args=(os.getppid(), sock_path), daemon=True).start()
    if os.path.exists(sock_path):
        os.unlink(sock_path)
    with connection.Listener(family="AF_UNIX", address=sock_path,
                             authkey=authkey) as listener:
        # children must not inherit the listener
        os.set_inheritable(listener._listener._socket.fileno(), False)
        # no "ready" print: the factory inherits the spawner's stdio so
        # children without a log file keep a REAL stdout (a pipe nobody
        # drains would deadlock a chatty worker); readiness is simply
        # the socket accepting connections
        while True:
            try:
                conn = listener.accept()
            except (OSError, EOFError):
                return
            threading.Thread(target=_serve, args=(conn,),
                             daemon=True).start()


def _serve(conn):
    """One spawner (head or daemon) per connection; requests are
    serialized per-connection by the caller."""
    while True:
        try:
            req = conn.recv()
        except (EOFError, OSError, TypeError):
            return
        if req is None:       # orderly shutdown
            os._exit(0)
        try:
            pid = _spawn_child(req)
            # start ticks = pid-reuse-proof identity (the factory reaps
            # children on SIGCHLD, so a bare pid is recyclable the
            # moment the child dies)
            conn.send({"pid": pid, "start": _proc_start(pid)})
        except BaseException as e:
            try:
                conn.send({"error": repr(e)})
            except (OSError, ValueError):
                return


if __name__ == "__main__":
    main()
