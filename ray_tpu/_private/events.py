"""Task lifecycle event recording (driver side), with stage attribution.

Counterpart of the reference's task-event pipeline: workers buffer task
state transitions (`src/ray/core_worker/task_event_buffer.h:193`
TaskEventBuffer), the GCS aggregates them (`gcs_task_manager.h:61`, with a
bounded in-memory ring), and the state API / chrome-trace timeline read
them back (`dashboard/state_aggregator.py:141`, `ray timeline`). Here the
driver process *is* the node, so transitions are recorded in place when the
NodeServer mutates task state — no buffering hop needed; the bounded-ring
retention policy is kept.

Stage attribution: each record carries the full per-stage timestamp chain
submitted→queued→dispatched→exec_start→exec_end→result_put→got, so the
control-plane overhead between `node.submit` and the driver's `get` is
attributable per stage instead of one opaque aggregate. Stage durations
feed a `task_stage_ms` histogram (Prometheus bridge) and bounded sample
rings for p50/p99 in `stage_breakdown()` / `summary()["__stages__"]`.
exec_start/exec_end come from the executing worker (they ride `TaskDone`),
all other clocks are the driver's.
"""

from __future__ import annotations

import collections
import threading
import time

# Reference keeps at most RAY_task_events_max_num_task_in_gcs (default 100k)
# tasks; same order of magnitude here.
MAX_TRACKED_TASKS = 100_000

# Pipeline stages, in order. Each is the interval between two adjacent
# timestamps of the chain; a stage is only observed when both ends exist.
STAGES = ("submit", "queue", "dispatch", "execute", "result_put", "got")
_STAGE_EDGES = (
    ("submit", "submitted_ts", "queued_ts"),          # dep wait
    ("queue", "queued_ts", "dispatched_ts"),          # scheduler queue
    ("dispatch", "dispatched_ts", "exec_start_ts"),   # wire + worker pickup
    ("execute", "exec_start_ts", "exec_end_ts"),      # user function
    ("result_put", "exec_end_ts", "result_put_ts"),   # seal + report
    ("got", "result_put_ts", "got_ts"),               # driver fetch lag
)

# Per-stage quantile window: enough for a stable p99 at bench scale
# without unbounded growth on long-running drivers.
STAGE_SAMPLE_CAP = 2048

# Histogram buckets in milliseconds (control-plane hops are sub-ms to
# seconds; the metrics default boundaries are tuned for seconds).
_STAGE_MS_BOUNDARIES = [
    0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
    1000, 5000]


def _pct(sorted_vals: list, q: float) -> float:
    """Nearest-rank percentile of an already-sorted list."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


class TaskEventRecorder:
    """Bounded table of per-task lifecycle records + transition log."""

    def __init__(self):
        self._lock = threading.Lock()
        # task_id -> record dict (insertion-ordered for FIFO trimming)
        self._tasks: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        self._stage_samples = {
            s: collections.deque(maxlen=STAGE_SAMPLE_CAP) for s in STAGES}
        self._stage_count = dict.fromkeys(STAGES, 0)
        # return object id -> task id, so the driver-side `get` can close
        # the chain ("got" stage). Bounded FIFO like the task table.
        self._ret2task: "collections.OrderedDict[str, str]" = \
            collections.OrderedDict()
        self._hist = None

    def _rec(self, task_id: str) -> dict:
        r = self._tasks.get(task_id)
        if r is None:
            r = {"task_id": task_id, "name": "", "state": "NIL",
                 "actor_id": None, "worker_id": None, "error": None,
                 "submitted_ts": None, "start_ts": None, "end_ts": None,
                 "queued_ts": None, "dispatched_ts": None,
                 "exec_start_ts": None, "exec_end_ts": None,
                 "result_put_ts": None, "got_ts": None,
                 "trace_id": None, "attempt": 0}
            self._tasks[task_id] = r
            while len(self._tasks) > MAX_TRACKED_TASKS:
                self._tasks.popitem(last=False)
        return r

    # -- stage plumbing ------------------------------------------------------

    def _stage_hist(self):
        """Lazily create the `task_stage_ms` histogram; the recorder must
        stay importable (and cheap) when the metrics plane is unused."""
        if self._hist is None:
            from ray_tpu.util import metrics
            self._hist = metrics.Histogram(
                "task_stage_ms",
                description=("Per-stage task latency (ms): "
                             "submit|queue|dispatch|execute|result_put|got"),
                boundaries=_STAGE_MS_BOUNDARIES,
                tag_keys=("stage",))
        return self._hist

    def _collect_stages_locked(self, r: dict,
                               only: str | None = None) -> list:
        """Durations (stage, ms) newly completed for record `r`; buffers
        quantile samples under the recorder lock, returns the list so the
        caller can feed the histogram AFTER releasing it (metrics hold
        their own lock; never nest it under ours)."""
        out = []
        for stage, a, b in _STAGE_EDGES:
            if only is not None and stage != only:
                continue
            ta, tb = r.get(a), r.get(b)
            if ta is None or tb is None:
                continue
            ms = max(0.0, (tb - ta) * 1e3)
            out.append((stage, ms))
            self._stage_samples[stage].append(ms)
            self._stage_count[stage] += 1
        return out

    def _observe(self, durations: list) -> None:
        """Feed collected durations to the histogram (outside the lock)."""
        if not durations:
            return
        try:
            hist = self._stage_hist()
            for stage, ms in durations:
                hist.observe(ms, tags={"stage": stage})
        except Exception:
            pass   # metrics plane unavailable; samples still recorded

    # -- transitions (called by NodeServer under its own lock) --------------

    def submitted(self, spec, waiting_args: bool) -> None:
        with self._lock:
            r = self._rec(spec.task_id)
            r["name"] = spec.name or spec.function_desc
            r["actor_id"] = spec.actor_id
            r["state"] = ("PENDING_ARGS_AVAIL" if waiting_args
                          else "PENDING_NODE_ASSIGNMENT")
            r["submitted_ts"] = time.time()
            if not waiting_args:
                r["queued_ts"] = r["submitted_ts"]   # runnable immediately
            ctx = getattr(spec, "trace_ctx", None)
            if ctx:
                r["trace_id"] = ctx.get("trace_id")

    def queued(self, task_id: str) -> None:
        """Dependencies resolved; the task entered the runnable queue."""
        with self._lock:
            r = self._tasks.get(task_id)
            if r is not None and r["queued_ts"] is None:
                r["queued_ts"] = time.time()

    def running(self, spec, worker_id: str) -> None:
        with self._lock:
            r = self._rec(spec.task_id)
            r["state"] = "RUNNING"
            r["worker_id"] = worker_id
            r["start_ts"] = time.time()
            r["dispatched_ts"] = r["start_ts"]

    def requeued(self, spec) -> None:
        with self._lock:
            r = self._rec(spec.task_id)
            r["state"] = "PENDING_NODE_ASSIGNMENT"
            r["attempt"] += 1
            # the old dispatch/exec clocks belong to the failed attempt
            r["dispatched_ts"] = None
            r["exec_start_ts"] = None
            r["exec_end_ts"] = None

    def finished(self, task_id: str, error: str | None = None,
                 exec_start_ts: float | None = None,
                 exec_end_ts: float | None = None,
                 return_ids=None) -> None:
        with self._lock:
            r = self._rec(task_id)
            r["state"] = "FAILED" if error else "FINISHED"
            r["error"] = error
            r["end_ts"] = time.time()
            r["result_put_ts"] = r["end_ts"]
            if exec_start_ts is not None:
                r["exec_start_ts"] = exec_start_ts
            if exec_end_ts is not None:
                r["exec_end_ts"] = exec_end_ts
            durations = self._collect_stages_locked(r)
            if error is None and return_ids:
                for oid in return_ids:
                    self._ret2task[oid] = task_id
                while len(self._ret2task) > MAX_TRACKED_TASKS:
                    self._ret2task.popitem(last=False)
        self._observe(durations)

    def mark_got(self, object_ids) -> None:
        """Driver-side fetch observed: close the `got` stage for every
        task whose return object is being located for a `get`."""
        durations = []
        now = time.time()
        with self._lock:
            for oid in object_ids:
                task_id = self._ret2task.pop(oid, None)
                if task_id is None:
                    continue
                r = self._tasks.get(task_id)
                if r is None or r["got_ts"] is not None:
                    continue
                r["got_ts"] = now
                durations += self._collect_stages_locked(r, only="got")
        self._observe(durations)

    # -- reads --------------------------------------------------------------

    def snapshot(self, filters: dict | None = None,
                 limit: int | None = None) -> list[dict]:
        if limit is None:
            from ray_tpu._private.constants import TASK_EVENT_QUERY_LIMIT
            limit = TASK_EVENT_QUERY_LIMIT
        with self._lock:
            out = []
            for r in reversed(self._tasks.values()):   # newest first
                if filters and any(r.get(k) != v for k, v in filters.items()):
                    continue
                out.append(dict(r))
                if len(out) >= limit:
                    break
            return out

    def _stage_breakdown_locked(self) -> dict:
        out = {}
        for stage in STAGES:
            vals = sorted(self._stage_samples[stage])
            out[stage] = {
                "count": self._stage_count[stage],
                "p50_ms": _pct(vals, 0.50),
                "p99_ms": _pct(vals, 0.99),
                "mean_ms": (sum(vals) / len(vals)) if vals else 0.0,
                "max_ms": vals[-1] if vals else 0.0,
            }
        return out

    def stage_breakdown(self) -> dict:
        """Per-stage latency quantiles over the recent sample window:
        stage -> {count, p50_ms, p99_ms, mean_ms, max_ms}."""
        with self._lock:
            return self._stage_breakdown_locked()

    def summary(self) -> dict:
        """Counts by (name, state) — `ray summary tasks` equivalent — plus
        a reserved ``__stages__`` key with the stage-latency breakdown."""
        with self._lock:
            counts: dict = {}
            for r in self._tasks.values():
                key = r["name"]
                per = counts.setdefault(key, {})
                per[r["state"]] = per.get(r["state"], 0) + 1
            counts["__stages__"] = self._stage_breakdown_locked()
            return counts

    def stats(self) -> dict:
        """Recorder occupancy counters.

        - ``tasks_tracked``: task records currently retained
        - ``stage_samples``: stage durations observed since start
        - ``got_pending``: finished tasks whose results were never fetched
        """
        with self._lock:
            return {
                "tasks_tracked": len(self._tasks),
                "stage_samples": sum(self._stage_count.values()),
                "got_pending": len(self._ret2task),
            }

    def chrome_trace(self) -> list[dict]:
        """Task spans in chrome://tracing 'complete event' format
        (`ray timeline` counterpart). Lanes are real process identities —
        pid = the executing worker (or "driver") — so merging with
        `tracing.spans_to_chrome_trace` output separates correctly."""
        now = time.time()
        with self._lock:
            events = []
            for r in self._tasks.values():
                if r["start_ts"] is None:
                    continue
                end = r["end_ts"] or now
                events.append({
                    "name": r["name"], "cat": "task", "ph": "X",
                    "ts": r["start_ts"] * 1e6,
                    "dur": (end - r["start_ts"]) * 1e6,
                    "pid": r["worker_id"] or "driver", "tid": "tasks",
                    "args": {"task_id": r["task_id"], "state": r["state"],
                             "actor_id": r["actor_id"],
                             "trace_id": r["trace_id"]},
                })
            return events
