"""Task lifecycle event recording (driver side).

Counterpart of the reference's task-event pipeline: workers buffer task
state transitions (`src/ray/core_worker/task_event_buffer.h:193`
TaskEventBuffer), the GCS aggregates them (`gcs_task_manager.h:61`, with a
bounded in-memory ring), and the state API / chrome-trace timeline read
them back (`dashboard/state_aggregator.py:141`, `ray timeline`). Here the
driver process *is* the node, so transitions are recorded in place when the
NodeServer mutates task state — no buffering hop needed; the bounded-ring
retention policy is kept.
"""

from __future__ import annotations

import collections
import threading
import time

# Reference keeps at most RAY_task_events_max_num_task_in_gcs (default 100k)
# tasks; same order of magnitude here.
MAX_TRACKED_TASKS = 100_000


class TaskEventRecorder:
    """Bounded table of per-task lifecycle records + transition log."""

    def __init__(self):
        self._lock = threading.Lock()
        # task_id -> record dict (insertion-ordered for FIFO trimming)
        self._tasks: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()

    def _rec(self, task_id: str) -> dict:
        r = self._tasks.get(task_id)
        if r is None:
            r = {"task_id": task_id, "name": "", "state": "NIL",
                 "actor_id": None, "worker_id": None, "error": None,
                 "submitted_ts": None, "start_ts": None, "end_ts": None,
                 "attempt": 0}
            self._tasks[task_id] = r
            while len(self._tasks) > MAX_TRACKED_TASKS:
                self._tasks.popitem(last=False)
        return r

    # -- transitions (called by NodeServer under its own lock) --------------

    def submitted(self, spec, waiting_args: bool) -> None:
        with self._lock:
            r = self._rec(spec.task_id)
            r["name"] = spec.name or spec.function_desc
            r["actor_id"] = spec.actor_id
            r["state"] = ("PENDING_ARGS_AVAIL" if waiting_args
                          else "PENDING_NODE_ASSIGNMENT")
            r["submitted_ts"] = time.time()

    def running(self, spec, worker_id: str) -> None:
        with self._lock:
            r = self._rec(spec.task_id)
            r["state"] = "RUNNING"
            r["worker_id"] = worker_id
            r["start_ts"] = time.time()

    def requeued(self, spec) -> None:
        with self._lock:
            r = self._rec(spec.task_id)
            r["state"] = "PENDING_NODE_ASSIGNMENT"
            r["attempt"] += 1

    def finished(self, task_id: str, error: str | None = None) -> None:
        with self._lock:
            r = self._rec(task_id)
            r["state"] = "FAILED" if error else "FINISHED"
            r["error"] = error
            r["end_ts"] = time.time()

    # -- reads --------------------------------------------------------------

    def snapshot(self, filters: dict | None = None,
                 limit: int | None = None) -> list[dict]:
        if limit is None:
            from ray_tpu._private.constants import TASK_EVENT_QUERY_LIMIT
            limit = TASK_EVENT_QUERY_LIMIT
        with self._lock:
            out = []
            for r in reversed(self._tasks.values()):   # newest first
                if filters and any(r.get(k) != v for k, v in filters.items()):
                    continue
                out.append(dict(r))
                if len(out) >= limit:
                    break
            return out

    def summary(self) -> dict:
        """Counts by (name, state) — `ray summary tasks` equivalent."""
        with self._lock:
            counts: dict = {}
            for r in self._tasks.values():
                key = r["name"]
                per = counts.setdefault(key, {})
                per[r["state"]] = per.get(r["state"], 0) + 1
            return counts

    def chrome_trace(self) -> list[dict]:
        """Task spans in chrome://tracing 'complete event' format
        (`ray timeline` counterpart)."""
        now = time.time()
        with self._lock:
            events = []
            for r in self._tasks.values():
                if r["start_ts"] is None:
                    continue
                end = r["end_ts"] or now
                events.append({
                    "name": r["name"], "cat": "task", "ph": "X",
                    "ts": r["start_ts"] * 1e6,
                    "dur": (end - r["start_ts"]) * 1e6,
                    "pid": "node", "tid": r["worker_id"] or "driver",
                    "args": {"task_id": r["task_id"], "state": r["state"],
                             "actor_id": r["actor_id"]},
                })
            return events
