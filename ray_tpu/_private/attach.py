"""Attach to a live session from another process (CLI, job inspection).

Counterpart of the reference's out-of-band clients: the `ray` CLI and state
API attach to a running cluster through GCS using the address + password in
the session files. Here attachment is a control-plane-only connection to
the driver's NodeServer socket: it registers with an `attach_` worker id
(the node never dispatches tasks to those) and speaks ActorCallRequest for
every `control()` verb. No object transfer — attach clients read state,
submit jobs, and fetch metrics.
"""

from __future__ import annotations

import glob
import os
import threading

from ray_tpu._private import protocol
from ray_tpu._private.constants import SESSION_PREFIX


def find_sessions(root: str = "/dev/shm") -> list[str]:
    """Live session dirs, newest first (a dir is live if its driver pid
    responds)."""
    out = []
    for d in sorted(glob.glob(os.path.join(root, SESSION_PREFIX + "*")),
                    key=os.path.getmtime, reverse=True):
        try:
            with open(os.path.join(d, "driver.pid")) as f:
                pid = int(f.read().strip())
            os.kill(pid, 0)
        except (OSError, ValueError):
            continue
        out.append(d)
    return out


class AttachClient:
    """Control-channel client for an existing session."""

    def __init__(self, session_dir: str, authkey: bytes | None = None):
        from ray_tpu._private import netaddr
        self.session_dir = session_dir
        if netaddr.is_tcp(session_dir):
            # remote head over TCP ("host:port"); secret from the caller
            # or RAY_TPU_AUTHKEY (hex)
            if authkey is None:
                key = os.environ.get("RAY_TPU_AUTHKEY")
                if not key:
                    raise ConnectionError(
                        "attaching over TCP requires RAY_TPU_AUTHKEY")
                authkey = bytes.fromhex(key)
            self._conn = netaddr.client(session_dir, authkey)
        else:
            if authkey is None:
                with open(os.path.join(session_dir, "authkey"), "rb") as f:
                    authkey = f.read()
            # via netaddr.client so the fault-injection wrap (delay/drop
            # of control messages) covers UDS attach channels too
            self._conn = netaddr.client(
                os.path.join(session_dir, "node.sock"), authkey)
        # unique per client, not per process: two AttachClients in one
        # process must not collide on the server's worker table
        import uuid
        self._wid = f"attach_{os.getpid()}_{uuid.uuid4().hex[:8]}"
        self._conn.send(protocol.RegisterWorker(self._wid, os.getpid()))
        self._lock = threading.Lock()
        self._req = 0
        self._replies: dict[int, object] = {}
        self._have = threading.Condition(self._lock)
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def _read_loop(self):
        while True:
            try:
                msg = self._conn.recv()
            except (EOFError, OSError, TypeError):
                with self._have:
                    self._replies[-1] = None   # poison: connection gone
                    self._have.notify_all()
                return
            if isinstance(msg, (protocol.ActorCallReply,
                                protocol.ErrorReply)):
                with self._have:
                    self._replies[msg.req_id] = msg
                    self._have.notify_all()
            # anything else (KillWorker on shutdown, pushes) is ignored

    def control(self, method: str, payload=None,
                timeout: float | None = None):
        if timeout is None:
            from ray_tpu._private.constants import ATTACH_CONTROL_TIMEOUT_S
            timeout = ATTACH_CONTROL_TIMEOUT_S
            # long-blocking server methods (pubsub_poll, wait, stack)
            # carry their server-side blocking window in the payload; the
            # transport deadline must sit strictly ABOVE that window or an
            # idle long-poll races into a spurious ConnectionError
            if isinstance(payload, dict) and "timeout" in payload:
                try:
                    srv = float(payload["timeout"])
                except (TypeError, ValueError):
                    srv = 0.0
                if srv > 0:     # non-blocking calls keep the short
                    timeout = max(timeout, srv + 10.0)  # user deadline
        with self._lock:
            self._req += 1
            rid = self._req
        self._conn.send(protocol.ActorCallRequest(rid, method, payload))
        with self._have:
            ok = self._have.wait_for(
                lambda: rid in self._replies or -1 in self._replies,
                timeout=timeout)
            if -1 in self._replies and rid not in self._replies:
                raise ConnectionError("session control channel closed")
            if not ok:
                # typed: a lost/unanswered control message is a timeout,
                # not a dead channel — callers can retry on it
                from ray_tpu.exceptions import GetTimeoutError
                raise GetTimeoutError(
                    f"control({method!r}) got no reply within {timeout}s")
            reply = self._replies.pop(rid)
        if reply.error:
            raise RuntimeError(reply.error)
        return reply.result

    def close(self):
        try:
            self._conn.close()
        except OSError:
            pass
