"""Per-task/actor runtime environments: working_dir, pip venvs, env_vars.

Counterpart of the reference's `python/ray/_private/runtime_env/`
(`working_dir.py`, `pip.py`, `uri_cache.py`) + the runtime-env agent
(`dashboard/modules/runtime_env/runtime_env_agent.py:161`): the node that
spawns a worker materializes the environment FIRST — a content-addressed
cache entry per distinct environment — then launches the worker inside it
(venv python, working_dir cwd, merged env vars).

Supported runtime_env keys (same schema shape as the reference):

- ``env_vars``:   {name: value} merged into the worker's environment
- ``working_dir``: a local directory (copied into the cache; the worker
                   starts with cwd there and the dir on sys.path)
- ``pip``:        list of requirement strings / local wheel paths, or
                   {"packages": [...]}. Installed into a cached venv
                   created with --system-site-packages so the image's
                   jax/numpy remain importable. No-network installs work
                   when requirements are local wheels; anything needing
                   egress fails with RuntimeEnvSetupError.
- ``py_modules``:  list of local module dirs/files appended to sys.path.
- ``conda``:       an environment spec dict (environment.yml content) or
                   a path to one — materialized once into a cached env
                   via the `conda` binary (reference:
                   `_private/runtime_env/conda.py`); the worker execs
                   that env's python. Requires conda on PATH (override:
                   RAY_TPU_CONDA_BINARY).
- ``container``:   {"image": ..., "run_options": [...]} — the worker
                   command is wrapped in `<runtime> run` (docker or
                   podman, RAY_TPU_CONTAINER_RUNTIME) with /dev/shm and
                   the checkout mounted so the containerized worker
                   reaches the node socket and shm arena (reference:
                   `_private/runtime_env/container.py` worker command
                   wrapping).

The cache is doubly bounded: entry count AND total bytes
(RUNTIME_ENV_CACHE_BYTES), LRU-evicted (reference: uri_cache.py).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import subprocess
import sys
import threading
import time

from ray_tpu._private import constants
from ray_tpu.exceptions import RuntimeEnvSetupError

from ray_tpu._private.constants import (
    RUNTIME_ENV_CACHE as _CACHE_ROOT,
    RUNTIME_ENV_CACHE_ENTRIES as _MAX_CACHE_ENTRIES,
)

_SETUP_KEYS = ("working_dir", "pip", "py_modules", "env_vars", "conda",
               "container")


def is_trivial(runtime_env: dict | None) -> bool:
    """True when the task can reuse a pool worker: no materialization AND
    no env_vars (pool workers were spawned without them; the reference
    likewise keys worker reuse on the runtime-env hash)."""
    if not runtime_env:
        return True
    return not any(runtime_env.get(k) for k in _SETUP_KEYS)


def _normalize_pip(spec) -> list[str]:
    if isinstance(spec, dict):
        spec = spec.get("packages", [])
    return [str(p) for p in spec]


_SIZE_SIDECAR = ".rtpu_size"


def _entry_bytes(path: str) -> int:
    """Cached entry size: the sidecar written at commit time, or one
    walk (then memoized to the sidecar) for pre-sidecar entries."""
    sidecar = os.path.join(path, _SIZE_SIDECAR)
    try:
        with open(sidecar) as f:
            return int(f.read())
    except (OSError, ValueError):
        pass
    n = _tree_bytes(path)
    try:
        with open(sidecar, "w") as f:
            f.write(str(n))
    except OSError:
        pass
    return n


def _tree_bytes(path: str) -> int:
    if os.path.isfile(path):
        try:
            return os.path.getsize(path)
        except OSError:
            return 0
    total = 0
    for root, _dirs, files in os.walk(path):
        for name in files:
            try:
                total += os.path.getsize(os.path.join(root, name))
            except OSError:
                pass
    return total


def _dir_fingerprint(path: str) -> str:
    """Content hash of a directory tree (URI of the packaged working_dir;
    reference: packaging.py hashes the zip the same way)."""
    h = hashlib.sha1()
    for root, dirs, files in sorted(os.walk(path)):
        dirs.sort()
        for name in sorted(files):
            fp = os.path.join(root, name)
            rel = os.path.relpath(fp, path)
            h.update(rel.encode())
            try:
                st = os.stat(fp)
                h.update(f"{st.st_size}:{int(st.st_mtime)}".encode())
            except OSError:
                continue
    return h.hexdigest()[:16]


class RuntimeEnvManager:
    """Materializes runtime environments into a content-addressed cache.

    One instance per worker-spawning process (head NodeServer and each
    HostDaemon). Entries are shared across sessions (the point of the
    cache: venv creation is seconds); an LRU cap bounds disk usage
    (reference: uri_cache.py)."""

    def __init__(self, cache_root: str = _CACHE_ROOT):
        self.cache_root = cache_root
        self._lock = threading.Lock()
        self._entry_locks: dict[str, threading.Lock] = {}

    # -- public -----------------------------------------------------------

    def setup(self, runtime_env: dict | None):
        """Materialize `runtime_env`. Returns (env_overrides, cwd,
        python_exe, cmd_prefix) — python_exe is None unless a pip venv /
        conda env applies; cmd_prefix is a command-line wrapper (the
        container runtime invocation) or None.
        Raises RuntimeEnvSetupError on any failure."""
        env: dict[str, str] = {}
        cwd = None
        python_exe = None
        cmd_prefix = None
        if not runtime_env:
            return env, cwd, python_exe, cmd_prefix
        # validate the SHAPE before materializing anything — a rejected
        # combination must not first burn minutes building a venv
        if runtime_env.get("conda") and runtime_env.get("pip"):
            raise RuntimeEnvSetupError(
                "runtime_env cannot combine 'pip' and 'conda' "
                "(pin pip packages inside the conda spec instead)")
        if runtime_env.get("container"):
            clash = [k for k in ("pip", "conda", "working_dir",
                                 "py_modules") if runtime_env.get(k)]
            if clash:
                # host-side cache paths (venvs, conda envs, staged
                # working dirs) don't exist inside the image; forwarding
                # them would fail at import time with no hint why
                raise RuntimeEnvSetupError(
                    f"runtime_env cannot combine 'container' with "
                    f"{clash} — bake packages and code into the image "
                    "(env_vars still apply)")
        for k, v in (runtime_env.get("env_vars") or {}).items():
            env[str(k)] = str(v)
        pypath: list[str] = []
        wd = runtime_env.get("working_dir")
        if wd:
            cwd = self._setup_working_dir(wd)
            pypath.append(cwd)
        for mod in runtime_env.get("py_modules") or []:
            pypath.append(self._setup_py_module(mod))
        pip = _normalize_pip(runtime_env.get("pip") or [])
        if pip:
            python_exe, site_dir = self._setup_pip(pip)
            if site_dir:
                # the venv's site-packages must SHADOW the parent's
                # propagated sys.path or version pins are silently ignored
                pypath.append(site_dir)
        conda = runtime_env.get("conda")
        if conda:
            python_exe = self._setup_conda(conda)
        container = runtime_env.get("container")
        if container:
            cmd_prefix = self._container_prefix(
                container, runtime_env.get("env_vars") or {})
        if pypath:
            # spawn.propagate_pythonpath places these first (after the
            # worker sitecustomize) so the env wins over inherited paths
            env["RAY_TPU_RUNTIME_ENV_PATHS"] = os.pathsep.join(pypath)
        return env, cwd, python_exe, cmd_prefix

    # -- working_dir ------------------------------------------------------

    def _setup_working_dir(self, src: str) -> str:
        src = os.path.abspath(os.path.expanduser(src))
        if not os.path.isdir(src):
            raise RuntimeEnvSetupError(
                f"runtime_env working_dir {src!r} is not a directory")
        key = "wd_" + _dir_fingerprint(src)
        dest = os.path.join(self.cache_root, key)
        with self._entry_lock(key):
            if not os.path.isdir(dest):
                tmp = dest + ".tmp.%d" % os.getpid()
                shutil.copytree(src, tmp)
                self._commit(tmp, dest)
            self._touch(dest)
        self._prune()
        return dest

    def _setup_py_module(self, mod: str) -> str:
        mod = os.path.abspath(os.path.expanduser(mod))
        if os.path.isdir(mod):
            # containing dir goes on sys.path so `import <basename>` works
            staged = self._setup_working_dir(mod)
            parent = os.path.join(
                os.path.dirname(staged), "pkg_" + os.path.basename(staged))
            os.makedirs(parent, exist_ok=True)
            link = os.path.join(parent, os.path.basename(mod))
            if not os.path.exists(link):
                try:
                    os.symlink(staged, link)
                except OSError:
                    shutil.copytree(staged, link, dirs_exist_ok=True)
            return parent
        raise RuntimeEnvSetupError(
            f"runtime_env py_modules entry {mod!r} is not a directory")

    # -- pip --------------------------------------------------------------

    def _setup_pip(self, packages: list[str]):
        """Returns (python_exe, site_packages_dir)."""
        # local wheels/sdists contribute content identity (size+mtime) to
        # the key: a rebuilt wheel at the same path must NOT reuse the
        # stale venv
        key_parts = []
        for p in sorted(packages):
            if os.path.exists(p):
                st = os.stat(p)
                # nanosecond mtime: a rebuild within the same second with
                # identical size must still invalidate the cached venv
                key_parts.append(f"{p}:{st.st_size}:{st.st_mtime_ns}")
            else:
                key_parts.append(p)
        key = "pip_" + hashlib.sha1(
            json.dumps(key_parts).encode()).hexdigest()[:16]
        venv_dir = os.path.join(self.cache_root, key)
        python_exe = os.path.join(venv_dir, "bin", "python")
        with self._entry_lock(key):
            if not os.path.exists(python_exe):
                tmp = venv_dir + ".tmp.%d" % os.getpid()
                shutil.rmtree(tmp, ignore_errors=True)
                try:
                    # --system-site-packages: the baked-in jax/numpy stack
                    # stays importable; the venv only ADDs packages
                    subprocess.run(
                        [sys.executable, "-m", "venv",
                         "--system-site-packages", tmp],
                        check=True, capture_output=True,
                        timeout=constants.RUNTIME_ENV_VENV_CREATE_TIMEOUT_S)
                    subprocess.run(
                        [os.path.join(tmp, "bin", "python"), "-m", "pip",
                         "install", "--quiet", "--no-input", *packages],
                        check=True, capture_output=True,
                        timeout=constants.RUNTIME_ENV_PIP_INSTALL_TIMEOUT_S)
                except subprocess.CalledProcessError as e:
                    shutil.rmtree(tmp, ignore_errors=True)
                    raise RuntimeEnvSetupError(
                        "pip runtime_env setup failed: "
                        f"{(e.stderr or b'').decode()[-2000:]}") from None
                except subprocess.TimeoutExpired:
                    shutil.rmtree(tmp, ignore_errors=True)
                    raise RuntimeEnvSetupError(
                        "pip runtime_env setup timed out") from None
                self._commit(tmp, venv_dir)
            self._touch(venv_dir)
        self._prune()
        import glob as _glob
        sites = _glob.glob(os.path.join(
            venv_dir, "lib", "python*", "site-packages"))
        return python_exe, (sites[0] if sites else None)

    # -- conda ------------------------------------------------------------

    def _setup_conda(self, spec) -> str:
        """Materialize a conda env into the cache; returns its python.
        `spec` is an environment.yml dict or a path to one (reference:
        `_private/runtime_env/conda.py` get_or_create_conda_env)."""
        from ray_tpu._private import config as _config
        conda_bin = shutil.which(_config.get("CONDA_BINARY"))
        if conda_bin is None:
            raise RuntimeEnvSetupError(
                "runtime_env 'conda' requires the conda binary on PATH "
                "(or RAY_TPU_CONDA_BINARY); it is not installed here")
        if isinstance(spec, str):
            spec = os.path.abspath(os.path.expanduser(spec))
            if not os.path.isfile(spec):
                raise RuntimeEnvSetupError(
                    f"conda spec file {spec!r} does not exist")
            with open(spec) as f:
                content = f.read()
        else:
            content = json.dumps(spec, sort_keys=True)
        key = "conda_" + hashlib.sha1(content.encode()).hexdigest()[:16]
        dest = os.path.join(self.cache_root, key)
        python_exe = os.path.join(dest, "bin", "python")
        with self._entry_lock(key):
            if not os.path.exists(python_exe):
                os.makedirs(self.cache_root, exist_ok=True)
                import tempfile
                tmp = dest + ".tmp.%d" % os.getpid()
                shutil.rmtree(tmp, ignore_errors=True)
                # spec lives OUTSIDE the cache (a sidecar in cache_root
                # would count as its own LRU entry and skew eviction)
                with tempfile.NamedTemporaryFile(
                        "w", suffix=".yml", delete=False) as f:
                    f.write(content)
                    spec_path = f.name
                try:
                    subprocess.run(
                        [conda_bin, "env", "create", "--yes",
                         "-p", tmp, "-f", spec_path],
                        check=True, capture_output=True,
                        timeout=constants.RUNTIME_ENV_CONDA_TIMEOUT_S)
                except subprocess.CalledProcessError as e:
                    shutil.rmtree(tmp, ignore_errors=True)
                    raise RuntimeEnvSetupError(
                        "conda runtime_env setup failed: "
                        f"{(e.stderr or b'').decode()[-2000:]}") from None
                except subprocess.TimeoutExpired:
                    shutil.rmtree(tmp, ignore_errors=True)
                    raise RuntimeEnvSetupError(
                        "conda runtime_env setup timed out") from None
                finally:
                    try:
                        os.unlink(spec_path)
                    except OSError:
                        pass
                self._commit(tmp, dest)
                if not os.path.exists(python_exe):
                    raise RuntimeEnvSetupError(
                        f"conda env at {dest} has no bin/python")
            self._touch(dest)
        self._prune()
        return python_exe

    # -- container --------------------------------------------------------

    @staticmethod
    def _container_prefix(spec, env_vars: dict | None = None) -> list[str]:
        """Command prefix wrapping the worker in a container (reference:
        `_private/runtime_env/container.py` worker command wrapping).
        /dev/shm (session dirs, arena, node sockets) and the checkout
        ride host mounts so the containerized worker still reaches its
        daemon and shares the zero-copy store. Bare `--env NAME` entries
        forward values from the spawner's Popen env, which carries the
        worker-env decisions (CPU gating, chip visibility, node id) and
        the runtime_env env_vars."""
        from ray_tpu._private import config as _config
        if isinstance(spec, str):
            spec = {"image": spec}
        image = spec.get("image")
        if not image:
            raise RuntimeEnvSetupError(
                "runtime_env 'container' needs an 'image'")
        runtime = _config.get("CONTAINER_RUNTIME")
        if not runtime:
            runtime = ("docker" if shutil.which("docker")
                       else "podman" if shutil.which("podman") else None)
        if runtime is None or shutil.which(runtime) is None:
            raise RuntimeEnvSetupError(
                "runtime_env 'container' requires docker or podman on "
                "PATH (or RAY_TPU_CONTAINER_RUNTIME)")
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        prefix = [runtime, "run", "--rm", "--network=host",
                  "-v", "/dev/shm:/dev/shm",
                  "-v", f"{pkg_root}:{pkg_root}:ro"]
        forward = ["RAY_TPU_AUTHKEY", "PYTHONPATH", "RAY_TPU_WORKER",
                   "RAY_TPU_WORKER_FORCE_CPU", "JAX_PLATFORMS",
                   "RAY_TPU_NODE_ID", "RAY_TPU_RUNTIME_ENV_PATHS",
                   constants.TPU_VISIBLE_CHIPS_ENV, "TPU_PROCESS_BOUNDS"]
        forward += [str(k) for k in (env_vars or {})]
        for name in forward:
            prefix += ["--env", name]
        prefix += [str(o) for o in spec.get("run_options") or []]
        prefix.append(image)
        return prefix

    # -- cache plumbing ---------------------------------------------------

    @staticmethod
    def _commit(tmp: str, dest: str) -> None:
        """Publish a finished cache entry. The entry locks are
        per-process; another daemon on this host may have won the same
        key — losing the rename race just means the entry already exists
        (content-addressed, so identical). The entry's tree size is
        recorded once here so _prune never re-walks big trees (a conda
        env is easily 100k files)."""
        try:
            with open(os.path.join(tmp, _SIZE_SIDECAR), "w") as f:
                f.write(str(_tree_bytes(tmp)))
        except OSError:
            pass
        try:
            os.rename(tmp, dest)
        except OSError:
            if os.path.isdir(dest):
                shutil.rmtree(tmp, ignore_errors=True)
            else:
                raise

    def _entry_lock(self, key: str) -> threading.Lock:
        with self._lock:
            return self._entry_locks.setdefault(key, threading.Lock())

    @staticmethod
    def _touch(path: str) -> None:
        try:
            os.utime(path, None)
        except OSError:
            pass

    def _prune(self) -> None:
        """Drop least-recently-used cache entries above the caps: entry
        COUNT and total BYTES (reference: uri_cache.py evicts on a byte
        budget)."""
        from ray_tpu._private import config as _config
        try:
            entries = [
                os.path.join(self.cache_root, e)
                for e in os.listdir(self.cache_root)
                if ".tmp." not in e]       # in-flight builds carry pids
        except FileNotFoundError:
            return
        max_bytes = _config.get("RUNTIME_ENV_CACHE_BYTES")
        sizes = {p: _entry_bytes(p) for p in entries}
        total = sum(sizes.values())
        if len(entries) <= _MAX_CACHE_ENTRIES and total <= max_bytes:
            return
        entries.sort(key=lambda p: os.path.getmtime(p))
        # never evict the newest entry for the BYTE budget: a single
        # entry larger than the budget was just handed to a spawner —
        # deleting it would strand the worker on a vanished interpreter
        # (and rebuild/evict forever)
        while entries and (len(entries) > _MAX_CACHE_ENTRIES
                           or (total > max_bytes and len(entries) > 1)):
            path = entries.pop(0)
            total -= sizes.get(path, 0)
            shutil.rmtree(path, ignore_errors=True)
            if os.path.isfile(path):           # spec sidecars (.yml)
                try:
                    os.unlink(path)
                except OSError:
                    pass


_manager: RuntimeEnvManager | None = None
_manager_lock = threading.Lock()


def get_manager() -> RuntimeEnvManager:
    global _manager
    with _manager_lock:
        if _manager is None:
            _manager = RuntimeEnvManager()
        return _manager
