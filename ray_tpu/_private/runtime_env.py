"""Per-task/actor runtime environments: working_dir, pip venvs, env_vars.

Counterpart of the reference's `python/ray/_private/runtime_env/`
(`working_dir.py`, `pip.py`, `uri_cache.py`) + the runtime-env agent
(`dashboard/modules/runtime_env/runtime_env_agent.py:161`): the node that
spawns a worker materializes the environment FIRST — a content-addressed
cache entry per distinct environment — then launches the worker inside it
(venv python, working_dir cwd, merged env vars).

Supported runtime_env keys (same schema shape as the reference):

- ``env_vars``:   {name: value} merged into the worker's environment
- ``working_dir``: a local directory (copied into the cache; the worker
                   starts with cwd there and the dir on sys.path)
- ``pip``:        list of requirement strings / local wheel paths, or
                   {"packages": [...]}. Installed into a cached venv
                   created with --system-site-packages so the image's
                   jax/numpy remain importable. No-network installs work
                   when requirements are local wheels; anything needing
                   egress fails with RuntimeEnvSetupError.
- ``py_modules``:  list of local module dirs/files appended to sys.path.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import subprocess
import sys
import threading
import time

from ray_tpu._private import constants
from ray_tpu.exceptions import RuntimeEnvSetupError

from ray_tpu._private.constants import (
    RUNTIME_ENV_CACHE as _CACHE_ROOT,
    RUNTIME_ENV_CACHE_ENTRIES as _MAX_CACHE_ENTRIES,
)

_SETUP_KEYS = ("working_dir", "pip", "py_modules", "env_vars")


def is_trivial(runtime_env: dict | None) -> bool:
    """True when the task can reuse a pool worker: no materialization AND
    no env_vars (pool workers were spawned without them; the reference
    likewise keys worker reuse on the runtime-env hash)."""
    if not runtime_env:
        return True
    return not any(runtime_env.get(k) for k in _SETUP_KEYS)


def _normalize_pip(spec) -> list[str]:
    if isinstance(spec, dict):
        spec = spec.get("packages", [])
    return [str(p) for p in spec]


def _dir_fingerprint(path: str) -> str:
    """Content hash of a directory tree (URI of the packaged working_dir;
    reference: packaging.py hashes the zip the same way)."""
    h = hashlib.sha1()
    for root, dirs, files in sorted(os.walk(path)):
        dirs.sort()
        for name in sorted(files):
            fp = os.path.join(root, name)
            rel = os.path.relpath(fp, path)
            h.update(rel.encode())
            try:
                st = os.stat(fp)
                h.update(f"{st.st_size}:{int(st.st_mtime)}".encode())
            except OSError:
                continue
    return h.hexdigest()[:16]


class RuntimeEnvManager:
    """Materializes runtime environments into a content-addressed cache.

    One instance per worker-spawning process (head NodeServer and each
    HostDaemon). Entries are shared across sessions (the point of the
    cache: venv creation is seconds); an LRU cap bounds disk usage
    (reference: uri_cache.py)."""

    def __init__(self, cache_root: str = _CACHE_ROOT):
        self.cache_root = cache_root
        self._lock = threading.Lock()
        self._entry_locks: dict[str, threading.Lock] = {}

    # -- public -----------------------------------------------------------

    def setup(self, runtime_env: dict | None):
        """Materialize `runtime_env`. Returns (env_overrides, cwd,
        python_exe) — python_exe is None unless a pip venv applies.
        Raises RuntimeEnvSetupError on any failure."""
        env: dict[str, str] = {}
        cwd = None
        python_exe = None
        if not runtime_env:
            return env, cwd, python_exe
        for k, v in (runtime_env.get("env_vars") or {}).items():
            env[str(k)] = str(v)
        pypath: list[str] = []
        wd = runtime_env.get("working_dir")
        if wd:
            cwd = self._setup_working_dir(wd)
            pypath.append(cwd)
        for mod in runtime_env.get("py_modules") or []:
            pypath.append(self._setup_py_module(mod))
        pip = _normalize_pip(runtime_env.get("pip") or [])
        if pip:
            python_exe, site_dir = self._setup_pip(pip)
            if site_dir:
                # the venv's site-packages must SHADOW the parent's
                # propagated sys.path or version pins are silently ignored
                pypath.append(site_dir)
        if pypath:
            # spawn.propagate_pythonpath places these first (after the
            # worker sitecustomize) so the env wins over inherited paths
            env["RAY_TPU_RUNTIME_ENV_PATHS"] = os.pathsep.join(pypath)
        return env, cwd, python_exe

    # -- working_dir ------------------------------------------------------

    def _setup_working_dir(self, src: str) -> str:
        src = os.path.abspath(os.path.expanduser(src))
        if not os.path.isdir(src):
            raise RuntimeEnvSetupError(
                f"runtime_env working_dir {src!r} is not a directory")
        key = "wd_" + _dir_fingerprint(src)
        dest = os.path.join(self.cache_root, key)
        with self._entry_lock(key):
            if not os.path.isdir(dest):
                tmp = dest + ".tmp.%d" % os.getpid()
                shutil.copytree(src, tmp)
                self._commit(tmp, dest)
            self._touch(dest)
        self._prune()
        return dest

    def _setup_py_module(self, mod: str) -> str:
        mod = os.path.abspath(os.path.expanduser(mod))
        if os.path.isdir(mod):
            # containing dir goes on sys.path so `import <basename>` works
            staged = self._setup_working_dir(mod)
            parent = os.path.join(
                os.path.dirname(staged), "pkg_" + os.path.basename(staged))
            os.makedirs(parent, exist_ok=True)
            link = os.path.join(parent, os.path.basename(mod))
            if not os.path.exists(link):
                try:
                    os.symlink(staged, link)
                except OSError:
                    shutil.copytree(staged, link, dirs_exist_ok=True)
            return parent
        raise RuntimeEnvSetupError(
            f"runtime_env py_modules entry {mod!r} is not a directory")

    # -- pip --------------------------------------------------------------

    def _setup_pip(self, packages: list[str]):
        """Returns (python_exe, site_packages_dir)."""
        # local wheels/sdists contribute content identity (size+mtime) to
        # the key: a rebuilt wheel at the same path must NOT reuse the
        # stale venv
        key_parts = []
        for p in sorted(packages):
            if os.path.exists(p):
                st = os.stat(p)
                # nanosecond mtime: a rebuild within the same second with
                # identical size must still invalidate the cached venv
                key_parts.append(f"{p}:{st.st_size}:{st.st_mtime_ns}")
            else:
                key_parts.append(p)
        key = "pip_" + hashlib.sha1(
            json.dumps(key_parts).encode()).hexdigest()[:16]
        venv_dir = os.path.join(self.cache_root, key)
        python_exe = os.path.join(venv_dir, "bin", "python")
        with self._entry_lock(key):
            if not os.path.exists(python_exe):
                tmp = venv_dir + ".tmp.%d" % os.getpid()
                shutil.rmtree(tmp, ignore_errors=True)
                try:
                    # --system-site-packages: the baked-in jax/numpy stack
                    # stays importable; the venv only ADDs packages
                    subprocess.run(
                        [sys.executable, "-m", "venv",
                         "--system-site-packages", tmp],
                        check=True, capture_output=True,
                        timeout=constants.RUNTIME_ENV_VENV_CREATE_TIMEOUT_S)
                    subprocess.run(
                        [os.path.join(tmp, "bin", "python"), "-m", "pip",
                         "install", "--quiet", "--no-input", *packages],
                        check=True, capture_output=True,
                        timeout=constants.RUNTIME_ENV_PIP_INSTALL_TIMEOUT_S)
                except subprocess.CalledProcessError as e:
                    shutil.rmtree(tmp, ignore_errors=True)
                    raise RuntimeEnvSetupError(
                        "pip runtime_env setup failed: "
                        f"{(e.stderr or b'').decode()[-2000:]}") from None
                except subprocess.TimeoutExpired:
                    shutil.rmtree(tmp, ignore_errors=True)
                    raise RuntimeEnvSetupError(
                        "pip runtime_env setup timed out") from None
                self._commit(tmp, venv_dir)
            self._touch(venv_dir)
        self._prune()
        import glob as _glob
        sites = _glob.glob(os.path.join(
            venv_dir, "lib", "python*", "site-packages"))
        return python_exe, (sites[0] if sites else None)

    # -- cache plumbing ---------------------------------------------------

    @staticmethod
    def _commit(tmp: str, dest: str) -> None:
        """Publish a finished cache entry. The entry locks are
        per-process; another daemon on this host may have won the same
        key — losing the rename race just means the entry already exists
        (content-addressed, so identical)."""
        try:
            os.rename(tmp, dest)
        except OSError:
            if os.path.isdir(dest):
                shutil.rmtree(tmp, ignore_errors=True)
            else:
                raise

    def _entry_lock(self, key: str) -> threading.Lock:
        with self._lock:
            return self._entry_locks.setdefault(key, threading.Lock())

    @staticmethod
    def _touch(path: str) -> None:
        try:
            os.utime(path, None)
        except OSError:
            pass

    def _prune(self) -> None:
        """Drop least-recently-used cache entries above the cap."""
        try:
            entries = [
                os.path.join(self.cache_root, e)
                for e in os.listdir(self.cache_root)
                if ".tmp." not in e]       # in-flight builds carry pids
        except FileNotFoundError:
            return
        if len(entries) <= _MAX_CACHE_ENTRIES:
            return
        entries.sort(key=lambda p: os.path.getmtime(p))
        for path in entries[:len(entries) - _MAX_CACHE_ENTRIES]:
            shutil.rmtree(path, ignore_errors=True)


_manager: RuntimeEnvManager | None = None
_manager_lock = threading.Lock()


def get_manager() -> RuntimeEnvManager:
    global _manager
    with _manager_lock:
        if _manager is None:
            _manager = RuntimeEnvManager()
        return _manager
