"""Usage-stats collection (reference: `_private/usage/usage_lib.py:92`).

The reference gathers cluster metadata + "library usages" (which Ray
libraries a session touched) and reports them to a telemetry endpoint
unless the user opts out. Here the polarity is inverted and the sink is
local-first: a `usage_stats.json` snapshot is always written into the
session directory (free, useful for support bundles), and anything
leaving the machine requires BOTH an explicit opt-in
(`RAY_TPU_USAGE_STATS_ENABLED=1`) and a configured report URL
(`RAY_TPU_USAGE_STATS_URL`) — the right default for TPU pods, which
commonly run with zero egress.
"""

from __future__ import annotations

import json
import os
import platform
import sys
import threading
import time

_lock = threading.Lock()
_library_usages: set[str] = set()
_extra_tags: dict[str, str] = {}
_start_ts = time.time()


def usage_stats_enabled() -> bool:
    """Whether REPORTING (not local collection) is on. Opt-in, unlike
    the reference's opt-out — this build targets zero-egress pods."""
    return os.environ.get(
        "RAY_TPU_USAGE_STATS_ENABLED", "0").strip().lower() in (
            "1", "true", "yes", "on")


def record_library_usage(library: str) -> None:
    """Called from library entry points (train/tune/data/serve/rllib),
    mirroring `usage_lib.record_library_usage`."""
    with _lock:
        _library_usages.add(library)


def record_extra_usage_tag(key: str, value: str) -> None:
    with _lock:
        _extra_tags[str(key)] = str(value)


def library_usages() -> list[str]:
    with _lock:
        return sorted(_library_usages)


def collect(node=None) -> dict:
    """Build the usage payload (reference: `UsageStatsToReport`)."""
    import ray_tpu
    data = {
        "schema_version": "0.1",
        "source": "ray_tpu",
        "ray_tpu_version": ray_tpu.__version__,
        "python_version": sys.version.split()[0],
        "os": platform.system().lower(),
        "arch": platform.machine(),
        "session_uptime_s": round(time.time() - _start_ts, 1),
        "libraries": library_usages(),
        "collected_at": time.time(),
    }
    with _lock:
        if _extra_tags:
            data["extra_tags"] = dict(_extra_tags)
    if node is not None:
        try:
            with node.lock:
                peers = [n for n in node.nodes.values() if n.alive]
                res = dict(node.total_resources)
                for n in peers:
                    for k, v in (n.total or {}).items():
                        res[k] = res.get(k, 0) + v
            data["total_num_nodes"] = 1 + len(peers)
            data["total_num_cpus"] = res.get("CPU", 0)
            data["total_num_tpus"] = res.get("TPU", 0)
            data["session_id"] = os.path.basename(
                getattr(node, "session_dir", "") or "")
        except Exception:
            pass
    return data


def write_local(node) -> str | None:
    """Dump the payload beside the session's other artifacts."""
    sd = getattr(node, "session_dir", None)
    if not sd or not os.path.isdir(sd):
        return None
    path = os.path.join(sd, "usage_stats.json")
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(collect(node), f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path
    except OSError:
        return None


def maybe_report(node) -> bool:
    """POST the payload iff opted in AND a URL is configured. Returns
    whether a report was sent (used by the test with a local server)."""
    if not usage_stats_enabled():
        return False
    url = os.environ.get("RAY_TPU_USAGE_STATS_URL", "").strip()
    if not url:
        return False
    import urllib.request
    body = json.dumps(collect(node)).encode()
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=5) as r:
            return 200 <= r.status < 300
    except OSError:
        return False


class UsageReporter:
    """Periodic local dump + (opted-in) report; one per head node."""

    def __init__(self, node, interval_s: float = 300.0):
        self._node = node
        self._interval = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="usage-stats")

    def start(self):
        # synchronous first dump: even a session that exits immediately
        # leaves a usage_stats.json snapshot behind
        try:
            write_local(self._node)
        except Exception:
            pass
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        # final dump so the snapshot reflects end-of-session state
        try:
            write_local(self._node)
        except Exception:
            pass

    def _loop(self):
        delay = self._interval
        while not self._stop.wait(delay):
            try:
                write_local(self._node)
                maybe_report(self._node)
            except Exception:
                pass
