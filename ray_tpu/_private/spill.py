"""Shared spill pass used by the head NodeServer and HostDaemons.

One implementation of the LocalObjectManager state machine
(local_object_manager.h:110): above the arena high-water mark, copy sealed
arena objects to the disk spill dir, swap the authoritative descriptor,
then release the arena block (drop this process's pins + tell the origin
worker to drop its owner pin). The swap-or-unlink race check and the
pin-release ordering live here exactly once; callers supply the candidate
list and the descriptor-swap callback.

Readers racing a spill (they hold the OLD arena descriptor) recover by
re-fetching the location from their node server — see the retry in
worker_main.get_objects / _resolve_args.
"""

from __future__ import annotations

import logging
import os

from ray_tpu._private import constants
from ray_tpu.exceptions import ObjectLostError

logger = logging.getLogger("ray_tpu")


def run_spill_pass(store, list_candidates, try_swap) -> int:
    """One high-water check + spill-until-low-water pass.

    - `store`: the owning process's ObjectStore.
    - `list_candidates()` -> [(oid, arena_desc), ...] (called once).
    - `try_swap(oid, old_desc, new_desc)` -> worker_conn | None | False:
      atomically (under the caller's lock) replace the authoritative
      descriptor IF it still equals old_desc; return False if it changed
      (the pass unlinks the orphaned spill file), else the origin worker
      connection holding the owner pin (or None if this process owns it).

    Returns the number of objects spilled.
    """
    from ray_tpu._private import protocol

    st = store.arena_stats()
    if st is None or st["capacity"] == 0:
        return 0
    if st["used"] < constants.SPILL_HIGH_WATER * st["capacity"]:
        return 0
    target = constants.SPILL_LOW_WATER * st["capacity"]
    spilled = 0
    for oid, desc in list_candidates():
        st = store.arena_stats()
        if st["used"] <= target:
            break
        try:
            payload = store.raw_bytes(desc)
        except (ObjectLostError, OSError):
            continue
        new_desc = store.spill_payload(oid, payload)
        origin_worker = try_swap(oid, desc, new_desc)
        if origin_worker is False:
            try:
                os.unlink(new_desc.path)
            except OSError:
                pass
            continue
        store.delete(desc)              # drop THIS process's pins
        if origin_worker is not None and origin_worker.alive:
            origin_worker.send(protocol.FreeObject(oid, desc))
        spilled += 1
    if spilled:
        logger.info("spilled %d arena objects to %s", spilled,
                    store._spill_dir)
    return spilled
