"""Shared-memory object store ("plasma-lite").

Counterpart of the reference's plasma store (`src/ray/object_manager/plasma/`,
`store.h:55`): one node-local store holding immutable serialized objects that
any process on the host can map zero-copy. Design differences, on purpose:

- The hot path is a native C++ arena (`_private/native/store.cc`: boundary-
  tag allocator + object index in one shm mapping, the counterpart of
  plasma's dlmalloc arena + `object_lifecycle_manager.h`), reached via the
  ctypes client in `_private/native/arena.py`. Objects put by the runtime
  are pinned (plasma Get/Release analog) so LRU eviction only reclaims
  explicitly released space; lifetime is owner-driven via `delete`.
- When the native library is unavailable (RAY_TPU_DISABLE_NATIVE=1, no
  toolchain) or the arena is full, objects fall back to one tmpfs-backed
  file per object under /dev/shm/<session>/ — same create/seal/get/delete
  verbs, so callers never see the difference.
- Small objects never touch the store; they ride inline in control messages
  (the reference similarly returns small task outputs inline in the gRPC
  reply and keeps them in the in-process memory store,
  store_provider/memory_store/).

Any process may create an object (workers write results directly — same as
plasma, where workers hold a store client); the *directory* of which objects
exist lives with the driver node (ownership, reference count) — the
counterpart of the ownership-based object directory
(ownership_based_object_directory.h).
"""

import mmap
import os
import shutil
import threading
from dataclasses import dataclass

from ray_tpu._private import constants, serialization
from ray_tpu._private.constants import INLINE_OBJECT_MAX_BYTES
from ray_tpu.exceptions import ObjectLostError


@dataclass(frozen=True)
class Descriptor:
    """Location of a sealed object's bytes: inline, arena, or file-backed.

    `node` names the cluster node whose store holds the bytes (None = the
    head node). A process on a different node must pull the bytes into its
    own store before reading — the counterpart of the reference's
    object-location entry in the ownership-based directory
    (ownership_based_object_directory.h)."""
    object_id: str
    size: int
    inline: bytes | None = None  # set iff the object is small
    path: str | None = None      # set iff the object lives in the store dir
    arena: bool = False          # set iff the object lives in the shm arena
    node: str | None = None      # owning node id; None = head node


def inline_descriptor(object_id: str, value) -> Descriptor:
    """Serialize `value` fully inline regardless of size — the put path
    for cross-machine client drivers that share no memory with the head
    (the head re-materializes oversized inline puts into its own store)."""
    size, meta, buffers = serialization.serialized_size(value)
    out = bytearray(size)
    n = serialization.write_envelope(memoryview(out), meta, buffers)
    return Descriptor(object_id, n, inline=bytes(out[:n]))


class ObjectStore:
    """Per-process handle to the session's shared object directory on tmpfs."""

    def __init__(self, session_dir: str):
        self._dir = os.path.join(session_dir, "objects")
        os.makedirs(self._dir, exist_ok=True)
        # Arena-overflow and spilled objects go to real disk, not tmpfs, so
        # shm usage stays bounded by the arena capacity (reference:
        # external_storage.py:246 FileSystemStorage). Paths are absolute in
        # descriptors, so any local process can read another's spill files.
        # OBJECT_SPILL_ROOT may be a URI (mem:// fake, registered gs://):
        # spill then rides the storage seam and descriptors carry the URI
        # (reference: smart_open S3 spill, external_storage.py:~350).
        from ray_tpu._private.config import get as _cfg
        spill_root = _cfg("OBJECT_SPILL_ROOT")
        base = os.path.basename(session_dir.rstrip("/"))
        if "://" in spill_root:
            from ray_tpu.util import storage as _storage
            self._spill_uri = _storage.uri_join(spill_root, base)
            self._spill_dir = os.path.join("/tmp/ray_tpu_spill_stage", base)
        else:
            self._spill_uri = None
            self._spill_dir = os.path.join(spill_root, base)
        # Keep mmaps alive while deserialized views may reference them.
        # obj_id -> (mmap, file size) for file-backed objects only.
        self._maps: dict[str, mmap.mmap] = {}
        self._lock = threading.Lock()
        from ray_tpu._private.native.arena import Arena
        self._arena = Arena.open(session_dir)
        # object_id -> pinned arena view held until delete() or close()
        # (shared-map views: raw_bytes/forwarding, which copy immediately)
        self._views: dict[str, memoryview] = {}
        # object_id -> (per-object mmap, view) handed to zero-copy
        # deserialization; the mmap is the buffer exporter, so close()
        # raising BufferError detects live borrowers at free time
        self._mviews: dict[str, tuple] = {}
        # object_id -> mmaps still borrowed when the object was freed;
        # one arena pin is held per entry (block condemned) until a
        # later sweep finds the borrowers gone. A list because an
        # object id can be reused and condemned again before the first
        # incarnation's borrowers die.
        self._condemned: dict[str, list] = {}
        # ids this process put (and therefore owner-pinned)
        self._owned: set[str] = set()

    # -- write path ---------------------------------------------------------

    def put(self, object_id: str, value) -> Descriptor:
        """Serialize `value`; small -> inline descriptor, large -> shm arena
        (native) with per-object file fallback."""
        size, meta, buffers = serialization.serialized_size(value)
        if size <= INLINE_OBJECT_MAX_BYTES:
            out = bytearray(size)
            n = serialization.write_envelope(memoryview(out), meta, buffers)
            return Descriptor(object_id, n, inline=bytes(out[:n]))
        if self._arena is not None:
            buf = self._arena.create(object_id, size)
            if buf is not None:
                try:
                    n = serialization.write_envelope(buf, meta, buffers)
                except BaseException:
                    # reclaim the reservation or it leaks for the session
                    self._arena.delete(object_id)
                    raise
                # pin BEFORE sealing: a sealed unpinned object is a valid
                # LRU-eviction victim for a concurrent out-of-space create
                self._arena.pin(object_id, 1)
                self._arena.seal(object_id)
                with self._lock:
                    self._owned.add(object_id)
                return Descriptor(object_id, n, arena=True)
        if self._spill_uri is not None:
            out = bytearray(size)
            n = serialization.write_envelope(memoryview(out), meta, buffers)
            return self.spill_payload(object_id, bytes(out[:n]))
        path = self._spill_path(object_id)
        tmp = path + ".tmp.%d" % os.getpid()
        with open(tmp, "wb+") as f:
            f.truncate(size)
            with mmap.mmap(f.fileno(), size) as m:
                n = serialization.write_envelope(memoryview(m), meta, buffers)
        if n != size:
            with open(tmp, "rb+") as f:
                f.truncate(n)
        os.rename(tmp, path)  # atomic seal: object visible only when complete
        return Descriptor(object_id, n, path=path)

    def create_serialized(self, object_id: str, nbytes: int):
        """Preallocate arena space for an incoming serialized envelope
        (chunked pulls land bytes straight in shared memory — no staging
        buffer, no put copy). Returns (writable memoryview, seal_fn) or
        (None, None) when the envelope should stage elsewhere (inline-
        small, no arena, arena full). seal_fn() -> Descriptor."""
        if nbytes <= INLINE_OBJECT_MAX_BYTES or self._arena is None:
            return None, None
        buf = self._arena.create(object_id, nbytes)
        if buf is None:
            return None, None

        def seal() -> Descriptor:
            self._arena.pin(object_id, 1)   # before seal; see put()
            self._arena.seal(object_id)
            with self._lock:
                self._owned.add(object_id)
            return Descriptor(object_id, nbytes, arena=True)

        return buf, seal

    def abort_create(self, object_id: str) -> None:
        """Drop an unsealed create_serialized allocation (pull failed)."""
        if self._arena is not None:
            try:
                self._arena.seal(object_id)
                self._arena.delete(object_id)
            except Exception:
                pass

    def put_serialized(self, object_id: str, payload) -> Descriptor:
        """Store an already-serialized envelope (bytes-like, e.g. the
        preallocated buffer a chunked pull landed in)."""
        if len(payload) <= INLINE_OBJECT_MAX_BYTES:
            return Descriptor(object_id, len(payload),
                              inline=bytes(payload))
        if self._arena is not None:
            buf = self._arena.create(object_id, len(payload))
            if buf is not None:
                buf[:] = payload
                self._arena.pin(object_id, 1)   # before seal; see put()
                self._arena.seal(object_id)
                with self._lock:
                    self._owned.add(object_id)
                return Descriptor(object_id, len(payload), arena=True)
        return self.spill_payload(object_id, payload)

    def _spill_path(self, object_id: str) -> str:
        os.makedirs(self._spill_dir, exist_ok=True)
        return os.path.join(self._spill_dir, object_id)

    def spill_payload(self, object_id: str, payload) -> Descriptor:
        """Write a serialized envelope to the spill target and return its
        file-backed descriptor (reference: LocalObjectManager::SpillObjects,
        local_object_manager.h:110; URI targets ride the storage seam)."""
        if self._spill_uri is not None:
            from ray_tpu.util import storage as _storage
            uri = _storage.uri_join(self._spill_uri, object_id)
            _storage.write_bytes(uri, bytes(payload))
            return Descriptor(object_id, len(payload), path=uri)
        path = self._spill_path(object_id)
        tmp = path + ".tmp.%d" % os.getpid()
        with open(tmp, "wb") as f:
            f.write(payload)
        os.rename(tmp, path)
        return Descriptor(object_id, len(payload), path=path)

    def purge_spill(self) -> None:
        """Remove this store's spill target (store OWNER only — head on
        shutdown, daemon on exit; readers must never call this)."""
        shutil.rmtree(self._spill_dir, ignore_errors=True)
        if self._spill_uri is not None:
            from ray_tpu.util import storage as _storage
            try:
                _storage.delete(self._spill_uri)
            except Exception:
                pass

    # -- read path ----------------------------------------------------------

    def get(self, desc: Descriptor):
        """Deserialize the object a descriptor points at (zero-copy mmap)."""
        if desc.inline is not None:
            return serialization.loads(desc.inline)
        if desc.arena:
            view = self._arena_read_view(desc)
            self._sweep_condemned()
            return serialization.loads(view)
        if desc.path is not None and "://" in desc.path:
            from ray_tpu.util import storage as _storage
            try:
                return serialization.loads(_storage.read_bytes(desc.path))
            except FileNotFoundError:
                raise ObjectLostError(
                    f"object {desc.object_id} missing from spill storage "
                    f"({desc.path})") from None
        with self._lock:
            m = self._maps.get(desc.object_id)
            if m is None:
                try:
                    with open(desc.path, "rb") as f:
                        m = mmap.mmap(f.fileno(), desc.size,
                                      access=mmap.ACCESS_READ)
                except FileNotFoundError:
                    raise ObjectLostError(
                        f"object {desc.object_id} missing from store "
                        f"({desc.path})") from None
                self._maps[desc.object_id] = m
        return serialization.loads(m)

    def _arena_view(self, desc: Descriptor) -> memoryview:
        """Pinned read view over the SHARED arena map — for callers that
        copy immediately (raw_bytes/forwarding). Zero-copy
        deserialization goes through _arena_read_view instead."""
        if self._arena is None:
            raise ObjectLostError(
                f"object {desc.object_id} is arena-backed but this process "
                "has no native arena (RAY_TPU_DISABLE_NATIVE mismatch?)")
        with self._lock:
            view = self._views.get(desc.object_id)
            if view is None:
                view = self._arena.acquire(desc.object_id)
                if view is None:
                    raise ObjectLostError(
                        f"object {desc.object_id} missing from arena "
                        "(evicted or deleted)")
                self._views[desc.object_id] = view
        return view[:desc.size]

    def _arena_read_view(self, desc: Descriptor) -> memoryview:
        """Pinned read view over a PER-OBJECT mmap, handed to zero-copy
        deserialization. Buffer exports from the deserialized arrays
        land on this object's own mmap, so the free path can probe
        "still borrowed?" precisely (mmap.close() raises BufferError) —
        the analog of plasma clients holding the buffer until Release,
        but with reclamation the moment the last borrower dies."""
        if self._arena is None:
            raise ObjectLostError(
                f"object {desc.object_id} is arena-backed but this process "
                "has no native arena (RAY_TPU_DISABLE_NATIVE mismatch?)")
        with self._lock:
            cached = self._mviews.get(desc.object_id)
            if cached is None:
                m, view = self._arena.acquire_mapped(desc.object_id)
                if view is None:
                    raise ObjectLostError(
                        f"object {desc.object_id} missing from arena "
                        "(evicted or deleted)")
                cached = (m, view)
                self._mviews[desc.object_id] = cached
        return cached[1][:desc.size]

    def _sweep_condemned(self) -> None:
        """Free condemned blocks whose borrowers have since died."""
        if not self._condemned:
            return
        with self._lock:
            items = [(oid, m) for oid, ms in self._condemned.items()
                     for m in list(ms)]
        for oid, m in items:
            try:
                m.close()
            except BufferError:
                continue        # still borrowed
            with self._lock:
                ms = self._condemned.get(oid)
                if ms and m in ms:
                    ms.remove(m)
                    if not ms:
                        del self._condemned[oid]
                    self._arena.pin(oid, -1)

    def raw_bytes(self, desc: Descriptor) -> bytes:
        """The serialized envelope (for forwarding across nodes)."""
        if desc.inline is not None:
            return desc.inline
        if desc.arena:
            return bytes(self._arena_view(desc))
        if "://" in desc.path:
            from ray_tpu.util import storage as _storage
            return _storage.read_bytes(desc.path)
        with open(desc.path, "rb") as f:
            return f.read()

    def raw_view(self, desc: Descriptor):
        """Zero-copy view of the serialized envelope where possible
        (arena: pinned view; file: cached mmap) — the serve side of the
        pull plane chunks from this without materializing the whole
        payload (reference: object chunks read straight out of plasma,
        object_buffer_pool.h)."""
        if desc.inline is not None:
            return desc.inline
        if desc.arena:
            # per-object mmap view: slices handed to the pull plane
            # export from that mmap, so delete()'s borrow probe covers
            # an in-flight chunked send (the shared view can't — slice
            # exports are invisible to memoryview.release())
            return self._arena_read_view(desc)
        if "://" in desc.path:
            from ray_tpu.util import storage as _storage
            return _storage.read_bytes(desc.path)
        with self._lock:
            m = self._maps.get(desc.object_id)
            if m is None:
                try:
                    with open(desc.path, "rb") as f:
                        m = mmap.mmap(f.fileno(), desc.size,
                                      access=mmap.ACCESS_READ)
                except FileNotFoundError:
                    raise ObjectLostError(
                        f"object {desc.object_id} missing from store "
                        f"({desc.path})") from None
                self._maps[desc.object_id] = m
        return memoryview(m)[:desc.size]

    # -- lifecycle ----------------------------------------------------------

    def arena_stats(self) -> dict | None:
        """{capacity, used, num_objects, num_evictions} or None without a
        native arena (drives the spill high-water check)."""
        return self._arena.stats() if self._arena is not None else None

    def adopt(self, object_id: str) -> bool:
        """Take over the owner pin of an arena object whose origin process
        died: pin it under THIS process (before the dead process's pins are
        force-released) and treat it as owned, so the free path releases
        the adopted pin like any put-time pin."""
        if self._arena is None:
            return False
        with self._lock:
            if object_id in self._owned:
                return True
            if self._arena.pin(object_id, 1) < 0:
                return False
            self._owned.add(object_id)
            return True

    def release_all_pins(self, pid: int) -> int:
        """Reclaim every arena pin a dead process held (owner pins from
        put, reader pins from get) plus its unsealed creations."""
        if self._arena is None:
            return 0
        return self._arena.release_all(pid)

    def delete(self, desc: Descriptor) -> None:
        if desc.arena:
            if self._arena is not None:
                oid = desc.object_id
                with self._lock:
                    view = self._views.pop(oid, None)
                    mview = self._mviews.pop(oid, None)
                    owned = oid in self._owned
                    self._owned.discard(oid)
                # drop THIS process's pins only (owner pin from put, reader
                # pins from get) — never another process's reader pin —
                # then delete: frees now if unpinned, else condemns until
                # the last remaining reader releases
                if view is not None:
                    # shared-map view: consumers copied, safe to release
                    view.release()
                    self._arena.pin(oid, -1)
                if mview is not None:
                    m, v = mview
                    try:
                        v.release()
                    except BufferError:
                        pass
                    try:
                        # the per-object mmap is the exporter for every
                        # zero-copy array deserialized from this object:
                        # close() raises while any borrower is alive
                        m.close()
                    except BufferError:
                        with self._lock:
                            self._condemned.setdefault(oid, []).append(m)
                    else:
                        self._arena.pin(oid, -1)
                if owned:
                    self._arena.pin(oid, -1)
                self._arena.delete(oid)
                self._sweep_condemned()
            return
        with self._lock:
            m = self._maps.pop(desc.object_id, None)
        if m is not None:
            try:
                m.close()
            except BufferError:
                pass  # live views reference it; the mmap dies with the process
        if desc.path is not None:
            if "://" in desc.path:
                from ray_tpu.util import storage as _storage
                _storage.delete(desc.path)
            else:
                try:
                    os.unlink(desc.path)
                except FileNotFoundError:
                    pass

    def close(self) -> None:
        with self._lock:
            maps, self._maps = self._maps, {}
        for m in maps.values():
            try:
                m.close()
            except BufferError:
                pass
        if self._arena is not None:
            with self._lock:
                views, self._views = self._views, {}
                mviews, self._mviews = self._mviews, {}
                condemned, self._condemned = self._condemned, {}
            for v in views.values():
                try:
                    v.release()
                except BufferError:
                    pass
            for m, v in mviews.values():
                for h in (v, m):
                    try:
                        h.release() if isinstance(h, memoryview) \
                            else h.close()
                    except BufferError:
                        pass  # borrower outlives the session; mmap dies
                              # with the process
            for ms in condemned.values():
                for m in ms:
                    try:
                        m.close()
                    except BufferError:
                        pass
            self._arena.close()
            self._arena = None
