"""Shared-memory object store ("plasma-lite").

Counterpart of the reference's plasma store (`src/ray/object_manager/plasma/`,
`store.h:55`): one node-local store holding immutable serialized objects that
any process on the host can map zero-copy. Design differences, on purpose:

- One tmpfs-backed file per object under /dev/shm/<session>/ instead of one
  dlmalloc arena: ownership and cleanup become trivial (driver unlinks the
  session dir), at the cost of a file create per large object. The interface
  (`create/seal/get/delete/contains`) matches plasma's client verbs
  (plasma/client.h) so a C++ slab allocator can replace the backend without
  touching callers.
- Small objects never touch the store; they ride inline in control messages
  (the reference similarly returns small task outputs inline in the gRPC
  reply and keeps them in the in-process memory store,
  store_provider/memory_store/).

Any process may create an object (workers write results directly — same as
plasma, where workers hold a store client); the *directory* of which objects
exist lives with the driver node (ownership, reference count) — the
counterpart of the ownership-based object directory
(ownership_based_object_directory.h).
"""

import mmap
import os
import threading
from dataclasses import dataclass

from ray_tpu._private import serialization
from ray_tpu._private.constants import INLINE_OBJECT_MAX_BYTES
from ray_tpu.exceptions import ObjectLostError


@dataclass(frozen=True)
class Descriptor:
    """Location of a sealed object's bytes. Either inline or file-backed."""
    object_id: str
    size: int
    inline: bytes | None = None  # set iff the object is small
    path: str | None = None      # set iff the object lives in the store dir


class ObjectStore:
    """Per-process handle to the session's shared object directory on tmpfs."""

    def __init__(self, session_dir: str):
        self._dir = os.path.join(session_dir, "objects")
        os.makedirs(self._dir, exist_ok=True)
        # Keep mmaps alive while deserialized views may reference them.
        # obj_id -> (mmap, file size). Never evicted within a session in v1;
        # the eviction/spilling policy slot is here (reference: eviction_policy.h).
        self._maps: dict[str, mmap.mmap] = {}
        self._lock = threading.Lock()

    # -- write path ---------------------------------------------------------

    def put(self, object_id: str, value) -> Descriptor:
        """Serialize `value`; small -> inline descriptor, large -> shm file."""
        size, meta, buffers = serialization.serialized_size(value)
        if size <= INLINE_OBJECT_MAX_BYTES:
            out = bytearray(size)
            n = serialization.write_envelope(memoryview(out), meta, buffers)
            return Descriptor(object_id, n, inline=bytes(out[:n]))
        path = os.path.join(self._dir, object_id)
        tmp = path + ".tmp.%d" % os.getpid()
        with open(tmp, "wb+") as f:
            f.truncate(size)
            with mmap.mmap(f.fileno(), size) as m:
                n = serialization.write_envelope(memoryview(m), meta, buffers)
        if n != size:
            with open(tmp, "rb+") as f:
                f.truncate(n)
        os.rename(tmp, path)  # atomic seal: object visible only when complete
        return Descriptor(object_id, n, path=path)

    def put_serialized(self, object_id: str, payload: bytes) -> Descriptor:
        """Store an already-serialized envelope (e.g. received over DCN)."""
        if len(payload) <= INLINE_OBJECT_MAX_BYTES:
            return Descriptor(object_id, len(payload), inline=payload)
        path = os.path.join(self._dir, object_id)
        tmp = path + ".tmp.%d" % os.getpid()
        with open(tmp, "wb") as f:
            f.write(payload)
        os.rename(tmp, path)
        return Descriptor(object_id, len(payload), path=path)

    # -- read path ----------------------------------------------------------

    def get(self, desc: Descriptor):
        """Deserialize the object a descriptor points at (zero-copy mmap)."""
        if desc.inline is not None:
            return serialization.loads(desc.inline)
        with self._lock:
            m = self._maps.get(desc.object_id)
            if m is None:
                try:
                    with open(desc.path, "rb") as f:
                        m = mmap.mmap(f.fileno(), desc.size,
                                      access=mmap.ACCESS_READ)
                except FileNotFoundError:
                    raise ObjectLostError(
                        f"object {desc.object_id} missing from store "
                        f"({desc.path})") from None
                self._maps[desc.object_id] = m
        return serialization.loads(m)

    def raw_bytes(self, desc: Descriptor) -> bytes:
        """The serialized envelope (for forwarding across nodes)."""
        if desc.inline is not None:
            return desc.inline
        with open(desc.path, "rb") as f:
            return f.read()

    # -- lifecycle ----------------------------------------------------------

    def delete(self, desc: Descriptor) -> None:
        with self._lock:
            m = self._maps.pop(desc.object_id, None)
        if m is not None:
            try:
                m.close()
            except BufferError:
                pass  # live views reference it; the mmap dies with the process
        if desc.path is not None:
            try:
                os.unlink(desc.path)
            except FileNotFoundError:
                pass

    def close(self) -> None:
        with self._lock:
            maps, self._maps = self._maps, {}
        for m in maps.values():
            try:
                m.close()
            except BufferError:
                pass
