"""Standalone head process: `python -m ray_tpu._private.head_main`.

Counterpart of the reference's GCS server binary (gcs_server.h:78, spawned
by `ray start --head`, scripts.py:537): the cluster control store runs in
its OWN process, so driver exit doesn't kill the cluster, and a SIGKILLed
head can restart into the same session dir — daemons reconnect-and-
reregister (daemon.py _reconnect_head), detached named actors re-attach,
and persisted jobs are re-adopted (job_submission.py JobManager._recover).

Operators normally reach this through `ray_tpu start --head`; drivers then
join with `ray_tpu.init(address=...)`.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time


def main(argv=None):
    ap = argparse.ArgumentParser(prog="ray_tpu-head")
    ap.add_argument("--session-dir", default=None,
                    help="session directory; restarting into an existing "
                    "one restores cluster metadata (head_state.pkl)")
    ap.add_argument("--port", type=int, default=None,
                    help="TCP listen port (enables the TCP tier; required "
                    "for daemons on other machines)")
    ap.add_argument("--bind-host", default=None)
    ap.add_argument("--num-cpus", type=int, default=None)
    ap.add_argument("--num-tpus", type=int, default=None)
    ap.add_argument("--resources", default="{}",
                    help="extra resources as JSON, e.g. '{\"red\": 2}'")
    args = ap.parse_args(argv)

    # Config is env-driven; translate flags before importing the node.
    if args.port is not None:
        os.environ["RAY_TPU_TRANSPORT"] = "tcp"
        os.environ["RAY_TPU_HEAD_PORT"] = str(args.port)
    if args.bind_host is not None:
        os.environ["RAY_TPU_HEAD_BIND_HOST"] = args.bind_host

    import ray_tpu
    from ray_tpu._private import constants, ids
    from ray_tpu._private.node import NodeServer

    num_cpus = args.num_cpus if args.num_cpus is not None \
        else (os.cpu_count() or 1)
    num_tpus = args.num_tpus if args.num_tpus is not None \
        else ray_tpu._detect_tpu_chips()
    total = {"CPU": float(num_cpus)}
    if num_tpus:
        total["TPU"] = float(num_tpus)
    for k, v in json.loads(args.resources).items():
        total[str(k)] = float(v)

    session_dir = args.session_dir or os.path.join(
        constants.SHM_ROOT, constants.SESSION_PREFIX + ids.new_node_id())
    os.makedirs(session_dir, exist_ok=True)

    node = NodeServer(total, session_dir, num_tpu_chips=int(num_tpus or 0),
                      standalone=True)

    def _term(signum, frame):
        node.shutdown()
        os._exit(0)

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)

    print(f"ray_tpu head up: session={session_dir}", flush=True)
    if node.tcp_address:
        print(f"address: {node.tcp_address}", flush=True)
        print(f"join:    ray_tpu start --address {node.tcp_address}",
              flush=True)
    print(f"drive:   ray_tpu.init(address={session_dir!r})", flush=True)

    if os.environ.get("RAY_TPU_HEAD_DETACHED") == "1":
        # The spawning CLI exits after the banner, closing our pipe; all
        # later output must go to a real file or it's lost to EPIPE
        # (reference: per-process log files under the session dir).
        log_dir = os.path.join(session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        fd = os.open(os.path.join(log_dir, "head.log"),
                     os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        sys.stdout.flush()
        sys.stderr.flush()
        os.dup2(fd, 1)
        os.dup2(fd, 2)
        os.close(fd)

    while not node._shutdown:
        time.sleep(0.5)


if __name__ == "__main__":
    sys.exit(main())
