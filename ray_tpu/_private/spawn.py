"""Worker-process spawning shared by the head NodeServer and HostDaemons.

Counterpart of the reference's worker-command assembly in
`python/ray/_private/services.py` (start_raylet builds the worker command
string the raylet's WorkerPool execs, worker_pool.h:80): environment
scoping (TPU chip visibility, JAX platform forcing) and sys.path
propagation so cloudpickled functions resolve in the child.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time as _time

from ray_tpu._private import constants


def worker_env(chips=None, runtime_env=None) -> dict:
    env = dict(os.environ)
    env["RAY_TPU_WORKER"] = "1"
    # Per-task/actor env overrides first (reference: runtime_env env_vars,
    # _private/runtime_env/) so an explicit JAX_PLATFORMS override is
    # visible to the FORCE_CPU decision below.
    overrides = {
        str(k): str(v)
        for k, v in ((runtime_env or {}).get("env_vars") or {}).items()
    }
    env.update(overrides)
    if chips:
        env[constants.TPU_VISIBLE_CHIPS_ENV] = ",".join(map(str, chips))
        env["TPU_PROCESS_BOUNDS"] = ""
    else:
        # Workers must not grab the host's TPU runtime by default: only
        # tasks that requested TPU resources see chips (the reference hides
        # GPUs the same way via CUDA_VISIBLE_DEVICES="").
        # RAY_TPU_WORKER_FORCE_CPU drives worker_site/sitecustomize.py,
        # which blocks accelerator plugin registration pre-jax-import.
        if "JAX_PLATFORMS" not in overrides:
            env["JAX_PLATFORMS"] = env.get(
                "RAY_TPU_WORKER_JAX_PLATFORMS", "cpu")
        if env["JAX_PLATFORMS"] == "cpu":
            env["RAY_TPU_WORKER_FORCE_CPU"] = "1"
    return env


def propagate_pythonpath(env: dict) -> dict:
    """Make the child resolve the same modules as this process: cloudpickle
    serializes module-level functions by reference, so the full sys.path
    (including the uninstalled checkout and the user's script dir) is
    propagated (reference: workers inherit the driver's load path /
    working_dir runtime env, services.py).

    Runtime-env paths (RAY_TPU_RUNTIME_ENV_PATHS: working_dir, py_modules,
    pip-venv site-packages) go FIRST, right after the worker sitecustomize
    — a runtime env must be able to shadow the parent's installed
    packages, or pip:["pkg==2.0"] silently resolves to the base image's
    pkg 1.0."""
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    worker_site = os.path.join(pkg_root, "ray_tpu", "_private", "worker_site")
    rt_paths = [p for p in env.get(
        "RAY_TPU_RUNTIME_ENV_PATHS", "").split(os.pathsep) if p]
    entries = [worker_site] + rt_paths + [pkg_root]
    entries += [p for p in sys.path if p]
    pypath = env.get("PYTHONPATH", "")
    entries += [p for p in pypath.split(os.pathsep) if p]
    seen, uniq = set(), []
    for p in entries:
        if p not in seen:
            seen.add(p)
            uniq.append(p)
    env["PYTHONPATH"] = os.pathsep.join(uniq)
    return env


def worker_log_file(log_dir: str | None, name: str):
    """Open `<log_dir>/<name>.log` for append if per-process log capture
    is on (reference: worker-*.out files under the session dir); None =
    inherit the parent's stdio."""
    from ray_tpu._private import config
    if log_dir is None or not config.get("WORKER_LOG_REDIRECT"):
        return None
    os.makedirs(log_dir, exist_ok=True)
    return open(os.path.join(log_dir, name + ".log"), "ab")


class ForkedProc:
    """Popen-compatible handle for a worker forked by the forkserver.
    The factory reaps the child on SIGCHLD, so the bare pid is
    recyclable the moment the child dies — every probe and signal is
    therefore guarded by the start-ticks identity recorded at fork
    (signal-0 alone would report a recycled pid as alive forever and
    kill() could SIGKILL an unrelated process)."""

    def __init__(self, pid: int, start_ticks=None):
        self.pid = pid
        self._start = start_ticks
        self._dead = start_ticks is None

    def _same_proc(self) -> bool:
        from ray_tpu._private.forkserver import _proc_start
        return _proc_start(self.pid) == self._start

    def poll(self):
        if self._dead:
            return 0
        if not self._same_proc():
            self._dead = True
            return 0
        return None

    def wait(self, timeout=None):
        import time as _time
        deadline = None if timeout is None else _time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and _time.monotonic() > deadline:
                raise subprocess.TimeoutExpired("forked-worker", timeout)
            _time.sleep(0.02)
        return 0

    def _signal(self, sig):
        if self._dead or not self._same_proc():
            self._dead = True
            return
        try:
            os.kill(self.pid, sig)
        except (ProcessLookupError, PermissionError):
            self._dead = True

    def terminate(self):
        import signal as _signal
        self._signal(_signal.SIGTERM)

    def kill(self):
        import signal as _signal
        self._signal(_signal.SIGKILL)


class _ForkServerClient:
    """Lazy per-process handle on a forkserver child (forkserver.py).
    Thread-safe: requests are serialized over one connection."""

    def __init__(self):
        import threading
        self._lock = threading.Lock()
        self._proc = None
        self._conn = None

    def _ensure(self, authkey: bytes):
        from multiprocessing import connection as mpc
        if self._conn is not None and self._proc.poll() is None:
            return True
        if self._proc is not None:
            # a previous factory whose connection dropped is still ours to
            # reap — left alone it would keep the old socket path open and
            # linger as an orphan beside the replacement
            try:
                self._proc.kill()
                self._proc.wait(timeout=5)
            except (OSError, subprocess.TimeoutExpired):
                pass
            self._proc = None
        sock = os.path.join(constants.SHM_ROOT,
                            f"ray_tpu_fs_{os.getpid()}.sock")
        env = propagate_pythonpath(dict(os.environ))
        env["RAY_TPU_AUTHKEY"] = authkey.hex()
        # the factory itself is a CPU process; the worker site hook keeps
        # platform plugins (and their 2s jax import) out of it
        env["RAY_TPU_WORKER_FORCE_CPU"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        try:
            # stdio INHERITED (not piped): forked children without a log
            # file keep the spawner's real stdout/stderr — a pipe nobody
            # drains would block a chatty worker at ~64KB
            self._proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu._private.forkserver",
                 sock],
                env=env, stdin=subprocess.DEVNULL)
            deadline = _time.monotonic() + 30.0
            while True:
                try:
                    self._conn = mpc.Client(sock, family="AF_UNIX",
                                            authkey=authkey)
                    break
                except (FileNotFoundError, ConnectionRefusedError,
                        OSError):
                    if (_time.monotonic() > deadline
                            or self._proc.poll() is not None):
                        raise OSError("forkserver failed to start")
                    _time.sleep(0.05)
            return True
        except Exception:
            if self._proc is not None:
                try:
                    self._proc.kill()
                except OSError:
                    pass
            self._proc = None
            self._conn = None
            return False

    def spawn(self, address, authkey, worker_id, env, log_path):
        with self._lock:
            if not self._ensure(authkey):
                return None
            try:
                self._conn.send({"address": address,
                                 "worker_id": worker_id,
                                 "env": env, "log_path": log_path})
                reply = self._conn.recv()
            except (OSError, EOFError, ValueError, TypeError):
                self._conn = None
                return None
            pid = reply.get("pid")
            if not pid:
                return None
            return ForkedProc(pid, reply.get("start"))


_forkserver = _ForkServerClient()


def _fork_eligible(env: dict, python_exe, cwd,
                   cmd_prefix=None) -> bool:
    """Fork only the common case: CPU worker, default interpreter, no
    runtime-env path/cwd overrides, no container wrapper. TPU workers
    must gate plugin registration before ANY import (env decides at
    exec time), and venv/conda/container workers need their own
    interpreter/command line."""
    return (python_exe is None and cwd is None and cmd_prefix is None
            and not env.get("RAY_TPU_RUNTIME_ENV_PATHS")
            and constants.TPU_VISIBLE_CHIPS_ENV not in env
            and env.get("JAX_PLATFORMS") == "cpu"
            and env.get("RAY_TPU_DISABLE_FORKSERVER") != "1")


def spawn_worker_proc(address: str, authkey: bytes, worker_id: str,
                      env: dict, python_exe: str | None = None,
                      cwd: str | None = None,
                      log_dir: str | None = None,
                      cmd_prefix: list | None = None):
    """Start a worker process that will register at `address`. The
    common (CPU, default-env) case forks from a warm factory —
    milliseconds instead of a cold interpreter exec; everything else
    execs a fresh python so the child env is exact and no TPU runtime
    handles/locks are inherited. `python_exe`/`cwd` come from a
    materialized runtime environment (pip venv / working_dir)."""
    env = propagate_pythonpath(dict(env))
    env["RAY_TPU_AUTHKEY"] = authkey.hex()
    from ray_tpu._private import config
    if _fork_eligible(env, python_exe, cwd, cmd_prefix):
        log_path = None
        if log_dir is not None and config.get("WORKER_LOG_REDIRECT"):
            os.makedirs(log_dir, exist_ok=True)
            log_path = os.path.join(log_dir, worker_id + ".log")
        proc = _forkserver.spawn(address, authkey, worker_id, env,
                                 log_path)
        if proc is not None:
            return proc
        # factory unavailable: fall through to exec
    # inside a container the HOST interpreter path means nothing; the
    # image's python3 + the mounted checkout (PYTHONPATH forwarded by
    # the runtime's --env passthrough) resolve the worker
    exe = python_exe or ("python3" if cmd_prefix else sys.executable)
    cmd = list(cmd_prefix or []) + [
        exe, "-m", "ray_tpu._private.worker_main", address, worker_id]
    logf = worker_log_file(log_dir, worker_id)   # ids carry their prefix
    try:
        return subprocess.Popen(
            cmd, env=env, stdin=subprocess.DEVNULL, cwd=cwd,
            stdout=logf or None, stderr=subprocess.STDOUT if logf else None)
    finally:
        if logf is not None:
            logf.close()     # the child holds its own fd now


def setup_runtime_env(runtime_env: dict | None, env: dict):
    """Materialize a runtime environment (runtime_env.py) and merge its
    env overrides into `env`. Returns (env, python_exe, cwd,
    cmd_prefix); raises RuntimeEnvSetupError on failure."""
    from ray_tpu._private.runtime_env import get_manager, is_trivial
    from ray_tpu.exceptions import RuntimeEnvSetupError
    if is_trivial(runtime_env):
        return env, None, None, None
    try:
        overrides, cwd, python_exe, cmd_prefix = \
            get_manager().setup(runtime_env)
    except RuntimeEnvSetupError:
        raise
    except Exception as e:
        # cache races / fs errors must surface as setup failures, not
        # escape the spawn thread and strand the task
        raise RuntimeEnvSetupError(
            f"runtime env setup failed: {e!r}") from e
    env.update(overrides)
    return env, python_exe, cwd, cmd_prefix
