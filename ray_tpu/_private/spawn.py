"""Worker-process spawning shared by the head NodeServer and HostDaemons.

Counterpart of the reference's worker-command assembly in
`python/ray/_private/services.py` (start_raylet builds the worker command
string the raylet's WorkerPool execs, worker_pool.h:80): environment
scoping (TPU chip visibility, JAX platform forcing) and sys.path
propagation so cloudpickled functions resolve in the child.
"""

from __future__ import annotations

import os
import subprocess
import sys

from ray_tpu._private import constants


def worker_env(chips=None, runtime_env=None) -> dict:
    env = dict(os.environ)
    env["RAY_TPU_WORKER"] = "1"
    # Per-task/actor env overrides first (reference: runtime_env env_vars,
    # _private/runtime_env/) so an explicit JAX_PLATFORMS override is
    # visible to the FORCE_CPU decision below.
    overrides = {
        str(k): str(v)
        for k, v in ((runtime_env or {}).get("env_vars") or {}).items()
    }
    env.update(overrides)
    if chips:
        env[constants.TPU_VISIBLE_CHIPS_ENV] = ",".join(map(str, chips))
        env["TPU_PROCESS_BOUNDS"] = ""
    else:
        # Workers must not grab the host's TPU runtime by default: only
        # tasks that requested TPU resources see chips (the reference hides
        # GPUs the same way via CUDA_VISIBLE_DEVICES="").
        # RAY_TPU_WORKER_FORCE_CPU drives worker_site/sitecustomize.py,
        # which blocks accelerator plugin registration pre-jax-import.
        if "JAX_PLATFORMS" not in overrides:
            env["JAX_PLATFORMS"] = env.get(
                "RAY_TPU_WORKER_JAX_PLATFORMS", "cpu")
        if env["JAX_PLATFORMS"] == "cpu":
            env["RAY_TPU_WORKER_FORCE_CPU"] = "1"
    return env


def propagate_pythonpath(env: dict) -> dict:
    """Make the child resolve the same modules as this process: cloudpickle
    serializes module-level functions by reference, so the full sys.path
    (including the uninstalled checkout and the user's script dir) is
    propagated (reference: workers inherit the driver's load path /
    working_dir runtime env, services.py).

    Runtime-env paths (RAY_TPU_RUNTIME_ENV_PATHS: working_dir, py_modules,
    pip-venv site-packages) go FIRST, right after the worker sitecustomize
    — a runtime env must be able to shadow the parent's installed
    packages, or pip:["pkg==2.0"] silently resolves to the base image's
    pkg 1.0."""
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    worker_site = os.path.join(pkg_root, "ray_tpu", "_private", "worker_site")
    rt_paths = [p for p in env.get(
        "RAY_TPU_RUNTIME_ENV_PATHS", "").split(os.pathsep) if p]
    entries = [worker_site] + rt_paths + [pkg_root]
    entries += [p for p in sys.path if p]
    pypath = env.get("PYTHONPATH", "")
    entries += [p for p in pypath.split(os.pathsep) if p]
    seen, uniq = set(), []
    for p in entries:
        if p not in seen:
            seen.add(p)
            uniq.append(p)
    env["PYTHONPATH"] = os.pathsep.join(uniq)
    return env


def worker_log_file(log_dir: str | None, name: str):
    """Open `<log_dir>/<name>.log` for append if per-process log capture
    is on (reference: worker-*.out files under the session dir); None =
    inherit the parent's stdio."""
    from ray_tpu._private import config
    if log_dir is None or not config.get("WORKER_LOG_REDIRECT"):
        return None
    os.makedirs(log_dir, exist_ok=True)
    return open(os.path.join(log_dir, name + ".log"), "ab")


def spawn_worker_proc(address: str, authkey: bytes, worker_id: str,
                      env: dict, python_exe: str | None = None,
                      cwd: str | None = None,
                      log_dir: str | None = None) -> subprocess.Popen:
    """Exec a worker process that will register at `address`. subprocess
    (not mp.Process) so we control the child env exactly and never inherit
    the parent's TPU runtime handles/locks. `python_exe`/`cwd` come from a
    materialized runtime environment (pip venv / working_dir)."""
    cmd = [python_exe or sys.executable,
           "-m", "ray_tpu._private.worker_main", address, worker_id]
    env = propagate_pythonpath(dict(env))
    env["RAY_TPU_AUTHKEY"] = authkey.hex()
    logf = worker_log_file(log_dir, worker_id)   # ids carry their prefix
    try:
        return subprocess.Popen(
            cmd, env=env, stdin=subprocess.DEVNULL, cwd=cwd,
            stdout=logf or None, stderr=subprocess.STDOUT if logf else None)
    finally:
        if logf is not None:
            logf.close()     # the child holds its own fd now


def setup_runtime_env(runtime_env: dict | None, env: dict):
    """Materialize a runtime environment (runtime_env.py) and merge its
    env overrides into `env`. Returns (env, python_exe, cwd); raises
    RuntimeEnvSetupError on failure."""
    from ray_tpu._private.runtime_env import get_manager, is_trivial
    from ray_tpu.exceptions import RuntimeEnvSetupError
    if is_trivial(runtime_env):
        return env, None, None
    try:
        overrides, cwd, python_exe = get_manager().setup(runtime_env)
    except RuntimeEnvSetupError:
        raise
    except Exception as e:
        # cache races / fs errors must surface as setup failures, not
        # escape the spawn thread and strand the task
        raise RuntimeEnvSetupError(
            f"runtime env setup failed: {e!r}") from e
    env.update(overrides)
    return env, python_exe, cwd
