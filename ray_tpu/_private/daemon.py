"""Per-host daemon: local worker pool, object store, and pull server.

Counterpart of the reference's raylet (`src/ray/raylet/node_manager.h:117`
NodeManager + worker_pool.h:80 WorkerPool) plus the node-to-node object
manager (`src/ray/object_manager/object_manager.h:117`), with scheduling
deliberately left at the head: the head's cluster scheduler assigns a task
to a node and sends a `LeaseTask`; this daemon only localizes dependencies
(pulling from peer nodes or the head), runs the task on a local worker, and
reports the sealed results back. That matches the reference's
GCS-scheduling mode (gcs_actor_scheduler.h:349 ScheduleByGcs) rather than
its raylet-autonomy mode — the right trade for TPU pods, where gang
placement decisions need the global view anyway.

Data plane: objects live in this node's own shm arena (store.cc); remote
reads are chunked pulls over UNIX sockets (object_manager.h:130,139
HandlePush/HandlePull). Workers on this host connect to this daemon's
listener and share its arena zero-copy, exactly like workers on the head.
"""

from __future__ import annotations

import collections
import itertools
import json
import logging
import os
import sys
import threading
import time
from dataclasses import dataclass, field, replace
from multiprocessing import connection

from ray_tpu._private import constants, ids, netaddr, protocol, spawn
from ray_tpu._private.object_store import Descriptor, ObjectStore
from ray_tpu._private.pull_plane import PullClient, serve_pull
from ray_tpu.exceptions import ObjectLostError, RuntimeEnvSetupError


def _env_trivial(spec) -> bool:
    from ray_tpu._private.runtime_env import is_trivial
    return is_trivial(spec.runtime_env)


def _local_link_groups() -> list:
    """Interconnect link-group ids this host hangs off (ICI ring / DCN
    pod), advertised in RegisterNode for contention-aware gang
    placement. Read per registration: set by the provisioner's env."""
    from ray_tpu._private import config
    return [s for s in config.get("LINK_GROUPS").split(",") if s]

logger = logging.getLogger("ray_tpu.daemon")


@dataclass
class _DWorker:
    worker_id: str
    conn: connection.Connection | None = None
    proc: object = None
    kind: str = "generic"            # generic | tpu | actor
    idle: bool = False
    alive: bool = False
    actor_id: str | None = None
    known_functions: set = field(default_factory=set)
    inflight: dict = field(default_factory=dict)   # task_id -> TaskSpec
    send_lock: threading.Lock = field(default_factory=threading.Lock)
    # Pipelined-submission receive state (touched only by this worker's
    # reader thread): next expected seq + outstanding-nack flag. The
    # daemon dedupes the worker's stream here, then relays each
    # submission ONCE on the reliable NodeSeq channel to the head.
    sub_next: int = 0
    sub_nacked: bool = False

    def send(self, msg) -> bool:
        return protocol.safe_send(self.conn, self.send_lock, msg)


class HostDaemon:
    def __init__(self, head_address: str, node_id: str, resources: dict,
                 num_tpu_chips: int):
        self.node_id = node_id
        self.head_address = head_address
        self.resources = dict(resources)
        self.num_tpu_chips = num_tpu_chips
        self.authkey = bytes.fromhex(os.environ["RAY_TPU_AUTHKEY"])
        tcp = netaddr.is_tcp(head_address)
        if not tcp:
            # same-machine session: node dir lives under the head's
            # session dir so shutdown/GC can sweep it
            session_dir = os.path.dirname(head_address)
            self.node_dir = os.path.join(session_dir, "nodes", node_id)
        else:
            # cross-machine join: no shared filesystem with the head —
            # this host owns its node dir (spawner may pin it via env for
            # same-host TCP test tiers)
            self.node_dir = os.environ.get("RAY_TPU_NODE_DIR") or \
                os.path.join(constants.SHM_ROOT, "ray_tpu_node_" + node_id)
        os.makedirs(self.node_dir, exist_ok=True)
        self.store = ObjectStore(self.node_dir)
        # workers always connect over UDS to their local daemon (reference
        # keeps worker<->raylet on UDS too); only peer/head edges go TCP
        self.address = os.path.join(self.node_dir, "node.sock")

        self.lock = threading.RLock()
        self.cv = threading.Condition(self.lock)
        self.workers: dict[str, _DWorker] = {}
        self.actors: dict[str, _DWorker] = {}
        self._objs: dict[str, Descriptor] = {}     # sealed in OUR store
        self._origin: dict[str, str] = {}          # oid -> worker_id
        self._copies: dict[str, Descriptor] = {}   # pulled remote objects
        self._pulling: set = set()                 # oids with pull in flight
        self.peer_addrs: dict[str, str] = {}
        self._peers: dict[str, tuple] = {}         # node -> (conn, lock)
        self._req = itertools.count(1)
        self._pull_client = PullClient()
        # head_req_id -> (kind, worker, worker_req_id, task_id)
        self._proxy: dict[int, tuple] = {}
        self._ctl: dict[int, dict] = {}     # daemon's own head RPCs
        self._ctl_cv = threading.Condition()
        self._shutdown = False

        if os.path.exists(self.address):
            # leftover socket of a dead daemon that reused this node dir
            os.unlink(self.address)
        self._listener = netaddr.listener(self.address, self.authkey)
        self._head = netaddr.client(head_address, self.authkey)
        self._head_lock = threading.Lock()
        # Reliable-delivery state for head-bound messages: a blip can
        # swallow sends WITHOUT an exception (the first write into a
        # half-closed TCP socket succeeds silently), so reliable messages
        # are seq-wrapped (protocol.NodeSeq), retained in a bounded ring,
        # and the whole ring is replayed after reconnect — the head
        # dedupes on seq, so completions that land inside the blip window
        # arrive exactly once.
        self._send_seq = itertools.count(1)
        self._sent_ring: collections.deque = collections.deque(
            maxlen=constants.HEAD_BACKLOG_CAP)
        # lease task id -> None while running, else the seq of its
        # terminal message (NodeTaskDone/Failed/NodeActorDied). Reported
        # in re-registration so the head can requeue leases the blip
        # swallowed; entries whose terminal seq fell off the replay ring
        # were delivered long ago and are pruned at reconnect.
        self._live_leases: dict[str, int | None] = {}
        if tcp:
            # peer pulls dial us over TCP; bind an ephemeral port on the
            # interface that routes to the head and advertise host:port
            host = netaddr.local_endpoint_host(self._head) or \
                netaddr.advertise_host()
            self._peer_listener = netaddr.listener((host, 0), self.authkey)
            self.advertised_address = netaddr.bound_address(
                self._peer_listener)
        else:
            self._peer_listener = None
            self.advertised_address = self.address
        # raw (un-seq'd) send: RegisterNode must be the literal first
        # message on the channel for the head to classify it. A send
        # failure here must NOT kill the daemon — head_loop's first recv
        # fails the same way and drives reconnect-and-reregister.
        try:
            self._head.send(protocol.RegisterNode(
                node_id=node_id, pid=os.getpid(), resources=resources,
                num_tpu_chips=num_tpu_chips,
                address=self.advertised_address,
                link_groups=_local_link_groups()))
        except (OSError, ValueError, BrokenPipeError):
            logger.warning("initial register send failed; deferring to "
                           "the reconnect path")

        threading.Thread(target=self._accept_loop, daemon=True,
                         name="daemon-accept").start()
        # ship this host's per-process log lines to the head (reference:
        # the per-node log monitor publishing via GCS pubsub)
        from ray_tpu._private.log_monitor import LogTailer
        self._log_tailer = LogTailer(
            os.path.join(self.node_dir, "logs"),
            lambda src, lines: self._head_send(
                protocol.LogBatch(src, self.node_id, lines),
                reliable=False)).start()
        if self._peer_listener is not None:
            threading.Thread(
                target=self._accept_loop, args=(self._peer_listener,),
                daemon=True, name="daemon-peer-accept").start()
        if self.store.arena_stats() is not None:
            threading.Thread(target=self._spill_loop, daemon=True,
                             name="daemon-spill").start()

    # ------------------------------------------------------------------
    # channels
    # ------------------------------------------------------------------

    def _head_send(self, msg, reliable: bool = True) -> int | None:
        """Send to the head; returns the seq for reliable messages.
        `reliable` messages (completions, object registrations, lifecycle
        events) are seq-wrapped and retained for replay across channel
        blips; lossy streams (LogBatch, PullChunk) pass `reliable=False`
        and ride unwrapped. Outbound pull REQUESTS stay reliable on
        purpose: a blip-swallowed request would hang the puller, while
        the chunk REPLIES it triggers are the lossy part."""
        with self._head_lock:
            if reliable:
                msg = protocol.NodeSeq(next(self._send_seq), msg)
                self._sent_ring.append(msg)
            try:
                self._head.send(msg)
            except (OSError, ValueError, BrokenPipeError):
                # reliable: already in the ring, replayed on reconnect;
                # lossy: dropped by design
                pass
            return msg.seq if reliable else None

    def _lease_terminal(self, task_id: str, seq: int | None) -> None:
        """Record that `task_id`'s terminal message was sent with `seq`
        (its outcome now rides the replay ring, not this table)."""
        with self.lock:
            if seq is None:
                self._live_leases.pop(task_id, None)
            elif task_id in self._live_leases:
                self._live_leases[task_id] = seq
            if len(self._live_leases) > 2 * constants.HEAD_BACKLOG_CAP:
                # amortized bound: entries whose terminal fell off the
                # replay ring were delivered long ago (self.lock ->
                # _head_lock nesting is the one order used everywhere)
                with self._head_lock:
                    oldest = (self._sent_ring[0].seq
                              if self._sent_ring else None)
                for tid, s in list(self._live_leases.items()):
                    if s is not None and (oldest is None or s < oldest):
                        del self._live_leases[tid]

    def _send_terminal(self, task_id: str, msg) -> None:
        """Send a lease's terminal outcome and move its delivery guarantee
        from the live-lease table to the replay ring."""
        self._lease_terminal(task_id, self._head_send(msg))

    def head_loop(self):
        """Main thread: serve the head channel until it closes. A closed
        channel means the head died or restarted: ride it out by
        reconnect-and-reregister within the grace window (reference:
        raylets survive GCS restarts, node_manager.proto:358
        NotifyGCSRestart), else die."""
        while not self._shutdown:
            try:
                msg = self._head.recv()
            except (EOFError, OSError, TypeError):
                if self._reconnect_head():
                    continue
                break
            try:
                self._handle_head(msg)
            except Exception:
                logger.exception("error handling %r from head", type(msg))
        self._die()

    def _reconnect_head(self) -> bool:
        from ray_tpu._private import config
        grace = config.get("DAEMON_RECONNECT_GRACE_S")
        if grace <= 0:
            return False
        logger.warning("head channel closed; trying to reconnect for %ss",
                       grace)
        deadline = time.monotonic() + grace
        while time.monotonic() < deadline and not self._shutdown:
            time.sleep(1.0)
            try:
                conn = netaddr.client(self.head_address, self.authkey)
            except Exception:
                continue
            # fail every request proxied before the crash: the restarted
            # head has no record of those req ids, so waiting is forever
            with self._head_lock:
                oldest_seq = (self._sent_ring[0].seq
                              if self._sent_ring else None)
            with self.lock:
                proxied, self._proxy = self._proxy, {}
                live_actors = {aid: {} for aid, w in self.actors.items()
                               if w.alive}
                objects = {oid: self._tag(d)
                           for oid, d in self._objs.items()}
                # prune leases whose terminal message fell off the replay
                # ring — the head saw those long ago; what remains is
                # every lease still running or whose outcome replays below
                for tid, s in list(self._live_leases.items()):
                    if s is not None and (oldest_seq is None
                                          or s < oldest_seq):
                        del self._live_leases[tid]
                leases = list(self._live_leases)
            with self._ctl_cv:
                for box in self._ctl.values():
                    box["error"] = "head restarted"
                    box["done"] = True
                self._ctl.clear()
                self._ctl_cv.notify_all()
            for kind, w, wreq, task_id in proxied.values():
                if kind == "get":
                    w.send(protocol.GetReply(
                        wreq, {}, error="ObjectLostError: head restarted "
                        "while this get() was in flight"))
                else:
                    w.send(protocol.ErrorReply(wreq, "head restarted"))
            register = protocol.RegisterNode(
                node_id=self.node_id, pid=os.getpid(),
                resources=self.resources, num_tpu_chips=self.num_tpu_chips,
                address=self.advertised_address, actors=live_actors,
                objects=objects, leases=leases,
                link_groups=_local_link_groups())
            # RegisterNode must be the FIRST message on the new channel
            # (the head classifies connections by it); then the retained
            # seq ring replays in order — the head drops already-seen
            # seqs, so messages swallowed by the blip (TCP reports no
            # error on the first write into a half-closed socket) arrive
            # exactly once. All under _head_lock so no concurrent
            # _head_send can jump the replay.
            with self._head_lock:
                try:
                    conn.send(register)
                    for wrapped in self._sent_ring:
                        conn.send(wrapped)
                except (OSError, ValueError, BrokenPipeError):
                    try:
                        conn.close()   # don't leak the fd while the
                    except OSError:    # head keeps flapping
                        pass
                    continue     # new conn died mid-handshake: retry
                self._head = conn
            logger.warning("re-registered with head "
                           "(%d actors, %d objects, %d replayed)",
                           len(live_actors), len(objects),
                           len(self._sent_ring))
            return True
        return False

    def _handle_head(self, msg):
        if isinstance(msg, protocol.LeaseTask):
            with self.lock:
                self._live_leases[msg.spec.task_id] = None
            threading.Thread(target=self._run_lease, args=(msg,),
                             daemon=True).start()
        elif isinstance(msg, protocol.PullRequest):
            # chunks are a lossy raw-framed stream on the head channel:
            # the puller re-requests on stall, and retaining MB-sized
            # chunks in the replay ring would balloon it
            with self._head_lock:
                raw = (self._head, self._head_lock)
            threading.Thread(
                target=self._serve_pull, args=(raw, msg),
                daemon=True).start()
        elif isinstance(msg, protocol.PullChunk):
            if msg.data is None:
                # raw body frame follows NOW on this channel; land it
                # before the next recv
                self._pull_client.on_chunk_raw(msg, self._head)
            else:
                self._pull_client.on_chunk(msg)
        elif isinstance(msg, (protocol.GetReply, protocol.WaitReply,
                              protocol.SubmitReply, protocol.ActorCallReply,
                              protocol.ErrorReply)):
            self._route_reply(msg)
        elif isinstance(msg, protocol.FreeObjectNode):
            self._free_local(msg.object_id)
        elif isinstance(msg, protocol.DumpStack):
            # fan out to this host's workers; replies ride back up
            with self.lock:
                targets = [w for w in self.workers.values()
                           if w.alive and (msg.worker_id is None
                                           or w.worker_id == msg.worker_id)]
            for w in targets:
                w.send(msg)
        elif isinstance(msg, protocol.SetTracing):
            if msg.enabled:
                from ray_tpu.util import tracing as _tracing
                _tracing._enable_local()   # future spawns inherit the env
            with self.lock:
                targets = [w for w in self.workers.values() if w.alive]
            for w in targets:
                w.send(msg)
        elif isinstance(msg, protocol.KillActorOnNode):
            with self.lock:
                w = self.actors.get(msg.actor_id)
            if w is not None and w.proc is not None:
                try:
                    w.proc.terminate()
                except OSError:
                    pass
        elif isinstance(msg, (protocol.KillNode, protocol.KillWorker)):
            self._die()
        else:
            logger.warning("unknown head message %r", type(msg))

    def _accept_loop(self, listener=None):
        listener = listener or self._listener
        while not self._shutdown:
            try:
                conn = listener.accept()
            except Exception:
                if self._shutdown:
                    return
                time.sleep(0.05)
                continue
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn):
        try:
            reg = conn.recv()
        except (EOFError, OSError, TypeError):
            return
        if isinstance(reg, protocol.RegisterWorker):
            with self.lock:
                w = self.workers.get(reg.worker_id)
                if w is None:
                    w = _DWorker(reg.worker_id, conn)
                    self.workers[reg.worker_id] = w
                else:
                    w.conn = conn
                w.alive = True
                w.pid = reg.pid
                self.cv.notify_all()
            self._worker_loop(w)
        elif isinstance(reg, protocol.RegisterPeer):
            psend = protocol.SafeConn(conn)
            raw = (conn, psend._lock)
            while True:
                try:
                    msg = conn.recv()
                except (EOFError, OSError, TypeError):
                    return
                if isinstance(msg, protocol.PullRequest):
                    threading.Thread(target=self._serve_pull,
                                     args=(raw, msg), daemon=True).start()
        else:
            conn.close()

    # ------------------------------------------------------------------
    # worker-facing protocol (same surface the head offers its workers)
    # ------------------------------------------------------------------

    def _worker_loop(self, w: _DWorker):
        while True:
            try:
                msg = w.conn.recv()
            except (EOFError, OSError, TypeError):
                self._on_worker_death(w)
                return
            try:
                self._handle_worker(w, msg)
            except Exception:
                logger.exception("error handling %r from %s", type(msg),
                                 w.worker_id)

    def _handle_worker(self, w: _DWorker, msg):
        if isinstance(msg, protocol.TaskDone):
            self._on_task_done(w, msg)
        elif isinstance(msg, protocol.StackDumpReply):
            self._head_send(msg)     # forward up to the collector
        elif isinstance(msg, protocol.PutRequest):
            with self.lock:
                if msg.desc.inline is None:
                    self._objs[msg.object_id] = msg.desc
                    self._origin[msg.object_id] = w.worker_id
            self._head_send(protocol.PutRequest(
                msg.object_id, self._tag(msg.desc), origin=w.worker_id))
        elif isinstance(msg, protocol.GetRequest):
            hreq = next(self._req)
            with self.lock:
                # resource release is only attributable when exactly one
                # task is in flight on this worker (a concurrent actor's
                # GetRequest doesn't say which method blocked)
                task_id = (next(iter(w.inflight))
                           if len(w.inflight) == 1 else None)
                self._proxy[hreq] = ("get", w, msg.req_id, task_id)
            if task_id is not None:
                self._head_send(protocol.NodeWorkerBlocked(task_id, True))
            self._head_send(protocol.GetRequest(
                hreq, msg.object_ids, msg.timeout))
        elif (isinstance(msg, protocol.SubmitRequest)
                and msg.seq is not None):
            self._on_pipelined_submit(w, msg)
        elif isinstance(msg, (protocol.WaitRequest, protocol.SubmitRequest,
                              protocol.ActorCallRequest)):
            hreq = next(self._req)
            with self.lock:
                self._proxy[hreq] = ("fwd", w, msg.req_id, None)
            if isinstance(msg, protocol.SubmitRequest):
                # identify the real submitter so the head keys the implicit
                # holds on its fresh return refs by the right worker id
                fwd = replace(msg, req_id=hreq, submitter=w.worker_id)
            else:
                fwd = replace(msg, req_id=hreq)
            self._head_send(fwd)
        else:
            logger.warning("unknown worker message %r", type(msg))

    _SUBMIT_CREDIT_EVERY = max(1, constants.SUBMIT_WINDOW // 4)

    def _on_pipelined_submit(self, w: _DWorker, msg) -> None:
        """Worker->daemon leg of the pipelined submit stream: the same
        seq state machine the head runs for local workers (in-order:
        apply; duplicate: drop + re-credit; gap: nack once). "Apply"
        here means relay ONCE on the reliable seq-wrapped head channel
        — NodeSeq replay gives daemon->head exactly-once, so the
        worker-side ring never needs to survive a daemon hop."""
        seq = msg.seq
        if seq == w.sub_next:
            w.sub_next = seq + 1
            w.sub_nacked = False
            self._head_send(replace(msg, req_id=-1, seq=None,
                                    submitter=w.worker_id))
            if w.sub_next % self._SUBMIT_CREDIT_EVERY == 0:
                w.send(protocol.SubmitCredit(w.sub_next - 1))
        elif seq < w.sub_next:
            w.send(protocol.SubmitCredit(w.sub_next - 1))
        elif not w.sub_nacked:
            w.sub_nacked = True
            w.send(protocol.SubmitNack(w.sub_next))

    def _head_control(self, method, payload=None,
                      timeout: float | None = None):
        """The daemon's OWN control RPC to the head (distinct from the
        worker-request proxying): e.g. resolving a peer address it was
        never told about."""
        if timeout is None:
            timeout = constants.HEAD_CONTROL_TIMEOUT_S
        hreq = next(self._req)
        box = {"done": False, "result": None, "error": None}
        with self._ctl_cv:
            self._ctl[hreq] = box
        self._head_send(protocol.ActorCallRequest(hreq, method, payload))
        deadline = time.monotonic() + timeout
        with self._ctl_cv:
            while not box["done"]:
                rem = deadline - time.monotonic()
                if rem <= 0 or self._shutdown:
                    self._ctl.pop(hreq, None)
                    raise ObjectLostError(
                        f"head control {method} timed out")
                self._ctl_cv.wait(min(rem, 0.5))
        if box["error"] is not None:
            raise ObjectLostError(
                f"head control {method} failed: {box['error']}")
        return box["result"]

    def _route_reply(self, msg):
        if isinstance(msg, protocol.ActorCallReply):
            with self._ctl_cv:
                box = self._ctl.pop(msg.req_id, None)
                if box is not None:
                    box["result"] = msg.result
                    box["error"] = msg.error
                    box["done"] = True
                    self._ctl_cv.notify_all()
                    return
        with self.lock:
            entry = self._proxy.pop(msg.req_id, None)
        if entry is None:
            return
        kind, w, wreq, task_id = entry
        if isinstance(msg, protocol.ErrorReply):
            if kind == "get":
                w.send(protocol.GetReply(wreq, {}, error=msg.error))
            else:
                w.send(protocol.ErrorReply(wreq, msg.error))
            return
        if kind == "get":
            def _finish():
                if msg.timed_out or msg.error is not None:
                    reply = protocol.GetReply(wreq, {}, msg.timed_out,
                                              msg.error)
                else:
                    try:
                        locs = {oid: self._ensure_local(d)
                                for oid, d in msg.locations.items()}
                        reply = protocol.GetReply(wreq, locs)
                    except (ObjectLostError, OSError) as e:
                        # OSError: a peer daemon died mid-pull (connect or
                        # stream failure) — must still answer the worker
                        reply = protocol.GetReply(
                            wreq, {}, error=f"ObjectLostError: {e}")
                if task_id is not None:
                    self._head_send(
                        protocol.NodeWorkerBlocked(task_id, False))
                w.send(reply)
            threading.Thread(target=_finish, daemon=True).start()
        else:
            w.send(replace(msg, req_id=wreq))

    # ------------------------------------------------------------------
    # task execution
    # ------------------------------------------------------------------

    def _tag(self, desc: Descriptor) -> Descriptor:
        if desc.inline is not None:
            return desc
        return replace(desc, node=self.node_id)

    def _run_lease(self, lease: protocol.LeaseTask):
        spec = lease.spec
        with self.lock:
            self.peer_addrs.update(lease.peer_addrs)
        try:
            arg_locs = {oid: self._ensure_local(d)
                        for oid, d in lease.arg_locations.items()}
        except (ObjectLostError, OSError) as e:
            self._send_terminal(spec.task_id, protocol.NodeTaskFailed(
                spec.task_id, f"dependency pull failed: {e}"))
            return
        if spec.actor_id is not None and not spec.actor_creation:
            with self.cv:
                deadline = time.monotonic() + constants.ACTOR_LEASE_WAIT_S
                w = self.actors.get(spec.actor_id)
                while w is None or not w.alive:
                    rem = deadline - time.monotonic()
                    if rem <= 0 or self._shutdown:
                        self._send_terminal(
                            spec.task_id, protocol.NodeTaskFailed(
                                spec.task_id,
                                "actor worker not on this node"))
                        return
                    self.cv.wait(min(rem, 0.2))
                    w = self.actors.get(spec.actor_id)
        elif spec.actor_creation:
            try:
                w = self._spawn_worker("actor", lease.tpu_chips,
                                       spec.runtime_env)
            except RuntimeEnvSetupError as e:
                # actor lifecycle runs through NodeActorDied (a plain
                # NodeTaskFailed for a creation task would strand the
                # actor in PENDING forever on the head)
                self._send_terminal(spec.task_id, protocol.NodeActorDied(
                    spec.actor_id, f"runtime env setup failed: {e}"))
                return
            if w is None:
                self._send_terminal(spec.task_id, protocol.NodeActorDied(
                    spec.actor_id, "actor worker failed to start"))
                return
            w.actor_id = spec.actor_id
            with self.cv:
                self.actors[spec.actor_id] = w
                self.cv.notify_all()
        elif spec.resources.get("TPU", 0) > 0 or not _env_trivial(spec):
            try:
                w = self._spawn_worker("dedicated", lease.tpu_chips,
                                       spec.runtime_env)
            except RuntimeEnvSetupError as e:
                self._send_terminal(spec.task_id, protocol.NodeTaskFailed(
                    spec.task_id, f"runtime env setup failed: {e}"))
                return
            if w is None:
                self._send_terminal(spec.task_id, protocol.NodeTaskFailed(
                    spec.task_id, "dedicated worker failed to start"))
                return
        else:
            with self.lock:
                w = next((x for x in self.workers.values()
                          if x.kind == "generic" and x.idle and x.alive),
                         None)
                if w is not None:
                    w.idle = False
            if w is None:
                try:
                    w = self._spawn_worker("generic", None, None)
                except RuntimeEnvSetupError:
                    w = None
                if w is None:
                    self._send_terminal(spec.task_id, protocol.NodeTaskFailed(
                        spec.task_id, "worker failed to start"))
                    return
        with self.lock:
            w.inflight[spec.task_id] = spec
            if spec.function_id in w.known_functions:
                spec = protocol.TaskSpec(
                    **{**spec.__dict__, "function_blob": None})
            else:
                w.known_functions.add(spec.function_id)
        w.send(protocol.PushTask(spec=spec, arg_locations=arg_locs))

    def _spawn_worker(self, kind, chips, runtime_env):
        """Raises RuntimeEnvSetupError if the env can't materialize;
        returns None on registration timeout/startup crash."""
        wid = ids.new_worker_id()
        w = _DWorker(wid, kind=kind)
        with self.lock:
            self.workers[wid] = w
        env = spawn.worker_env(chips=chips or None, runtime_env=runtime_env)
        env["RAY_TPU_NODE_ID"] = self.node_id
        try:
            env, python_exe, cwd, cmd_prefix = \
                spawn.setup_runtime_env(runtime_env, env)
        except RuntimeEnvSetupError:
            with self.lock:
                self.workers.pop(wid, None)
            raise
        w.proc = spawn.spawn_worker_proc(
            self.address, self.authkey, wid, env, python_exe, cwd,
            log_dir=os.path.join(self.node_dir, "logs"),
            cmd_prefix=cmd_prefix)
        deadline = time.monotonic() + constants.WORKER_REGISTER_TIMEOUT_S
        with self.cv:
            while not w.alive:
                rem = deadline - time.monotonic()
                if rem <= 0 or self._shutdown:
                    self.workers.pop(wid, None)
                    return None
                if w.proc.poll() is not None:
                    self.workers.pop(wid, None)
                    return None
                self.cv.wait(min(rem, 0.2))
        return w

    def _on_task_done(self, w: _DWorker, msg: protocol.TaskDone):
        retire = None
        with self.lock:
            spec = w.inflight.pop(msg.task_id, None)
            if spec is None:
                logger.warning("TaskDone for unknown task %s", msg.task_id)
                return
            tagged = []
            for oid, desc in zip(spec.return_ids, msg.return_descs):
                if desc.inline is None:
                    self._objs[oid] = desc
                    self._origin[oid] = w.worker_id
                tagged.append(self._tag(desc))
            if w.kind == "dedicated":
                retire = w
            elif w.kind == "generic":
                w.idle = True
        self._send_terminal(msg.task_id, protocol.NodeTaskDone(
            task_id=msg.task_id, return_descs=tagged, error=msg.error,
            actor_ready=msg.actor_ready,
            exec_start_ts=msg.exec_start_ts, exec_end_ts=msg.exec_end_ts,
            spans=msg.spans))
        if retire is not None:
            retire.send(protocol.KillWorker())
            with self.lock:
                self.workers.pop(retire.worker_id, None)

    def _on_worker_death(self, w: _DWorker):
        with self.lock:
            if not w.alive and not w.inflight:
                self.workers.pop(w.worker_id, None)
                return
            w.alive = False
            w.idle = False
            self.workers.pop(w.worker_id, None)
            inflight, w.inflight = w.inflight, {}
            actor_id = w.actor_id
            if actor_id is not None:
                self.actors.pop(actor_id, None)
            # Reclaim the dead process's arena pins; adopt the owner pin of
            # every live object it put first (same order as the head,
            # node.py _on_worker_death).
            pid = getattr(w.proc, "pid", None)
            if pid is not None:
                for oid, origin in list(self._origin.items()):
                    if origin != w.worker_id:
                        continue
                    desc = self._objs.get(oid)
                    if desc is not None and desc.arena:
                        self.store.adopt(oid)
                    self._origin[oid] = "daemon"
                self.store.release_all_pins(pid)
        self._head_send(protocol.NodeWorkerGone(w.worker_id))
        if actor_id is not None:
            seq = self._head_send(protocol.NodeActorDied(
                actor_id, "worker process died"))
            # the actor-death notice is terminal for every lease that was
            # running on the actor worker (the head requeues them through
            # its actor restart path)
            for tid in inflight:
                self._lease_terminal(tid, seq)
        else:
            for tid in inflight:
                self._send_terminal(tid, protocol.NodeTaskFailed(
                    tid, "worker died while running task"))

    # ------------------------------------------------------------------
    # object data plane
    # ------------------------------------------------------------------

    def _ensure_local(self, desc: Descriptor) -> Descriptor:
        if desc.inline is not None or desc.node == self.node_id:
            return desc
        oid = desc.object_id
        with self.cv:
            while True:
                c = self._copies.get(oid)
                if c is not None:
                    return c
                if oid not in self._pulling:
                    self._pulling.add(oid)
                    break
                self.cv.wait(0.2)
        seal_box = {}

        def alloc(total: int):
            buf, seal = self.store.create_serialized(oid, total)
            if buf is not None:
                seal_box["seal"] = seal
            return buf

        try:
            # on pull failure the PullClient owns releasing the arena
            # allocation (a late in-flight frame may still be landing in
            # it — freeing here would corrupt whatever recycles the
            # block); we only seal on success
            payload, in_arena = self._pull(
                desc.node, oid, alloc,
                cleanup=lambda: self.store.abort_create(oid))
            if in_arena:
                # bytes landed straight in the arena: seal, done — the
                # pull WAS the put (zero staging copies)
                local = seal_box["seal"]()
            else:
                local = self.store.put_serialized(oid, payload)
            # publish BEFORE dropping the _pulling claim, or a waiter can
            # wake to no-copy/no-claim and start a duplicate pull
            with self.lock:
                self._copies[oid] = local
        finally:
            with self.cv:
                self._pulling.discard(oid)
                self.cv.notify_all()
        self._head_send(protocol.ObjectCopyNote(
            oid, self.node_id, self._tag(local)))
        return local

    def _peer_send(self, node_id: str):
        with self.lock:
            entry = self._peers.get(node_id)
            addr = self.peer_addrs.get(node_id)
        if entry is not None:
            return entry[0]
        if addr is None:
            # never told about this node (it joined after our last lease):
            # ask the head's membership table
            addr = self._head_control("node_address", node_id)
            if addr is None:
                raise ObjectLostError(f"no address for node {node_id}")
            with self.lock:
                self.peer_addrs[node_id] = addr
        conn = netaddr.client(addr, self.authkey)
        send = protocol.SafeConn(conn)
        send(protocol.RegisterPeer(self.node_id))

        def reader(_c=conn):
            while True:
                try:
                    msg = _c.recv()
                except (EOFError, OSError, TypeError):
                    return
                if isinstance(msg, protocol.PullChunk):
                    if msg.data is None:
                        self._pull_client.on_chunk_raw(msg, _c)
                    else:
                        self._pull_client.on_chunk(msg)
        threading.Thread(target=reader, daemon=True,
                         name=f"peer-{node_id}").start()
        with self.lock:
            self._peers[node_id] = (send, conn)
        return send

    def _pull(self, source_node: str | None, oid: str, alloc=None,
              cleanup=None):
        """-> (payload, landed_in_alloc). Outbound pull REQUESTS stay
        reliable on purpose (a blip-swallowed request hangs the puller);
        the chunk replies are the lossy part."""
        if source_node is None:
            send = self._head_send
        else:
            send = self._peer_send(source_node)
        return self._pull_client.pull_into(send, oid, alloc=alloc,
                                           cleanup=cleanup)

    def _serve_pull(self, raw, msg: protocol.PullRequest):
        with self.lock:
            desc = self._objs.get(msg.object_id) or \
                self._copies.get(msg.object_id)
        if desc is None:
            serve_pull(raw, msg, None)
            return
        try:
            payload = self.store.raw_view(desc)
        except (ObjectLostError, OSError) as e:
            payload = e
        serve_pull(raw, msg, payload)

    def _spill_loop(self):
        """Above the arena high-water mark, move sealed local objects to
        the disk spill dir and re-register their descriptors with the head
        (LocalObjectManager equivalent on the daemon's own store)."""
        while not self._shutdown:
            time.sleep(constants.SPILL_PASS_INTERVAL_S)
            try:
                self._maybe_spill()
            except Exception:
                logger.exception("daemon spill pass failed")
            try:
                # reclaim condemned pull buffers even if this node never
                # pulls again (the sweep otherwise only runs on the next
                # pull / abort_all)
                self._pull_client.sweep()
            except Exception:
                logger.exception("tombstone sweep failed")

    def _maybe_spill(self):
        from ray_tpu._private.spill import run_spill_pass

        def candidates():
            with self.lock:
                return [(oid, d) for oid, d in self._objs.items()
                        if d.arena]

        def try_swap(oid, old, new):
            with self.lock:
                if self._objs.get(oid) != old:
                    return False
                self._objs[oid] = new
                origin = self._origin.get(oid)
                self._origin[oid] = "daemon"
                w = self.workers.get(origin) if origin else None
            # refresh the head's directory so future arg_locations carry
            # the file-backed descriptor
            self._head_send(protocol.PutRequest(oid, self._tag(new)))
            return w

        run_spill_pass(self.store, candidates, try_swap)

    def _free_local(self, oid: str):
        with self.lock:
            desc = self._objs.pop(oid, None)
            copy = self._copies.pop(oid, None)
            self._origin.pop(oid, None)
            workers = [w for w in self.workers.values() if w.alive]
        gone = desc or copy
        for d in (desc, copy):
            if d is not None:
                try:
                    self.store.delete(d)
                except Exception:
                    pass
        if gone is not None:
            # EVERY worker that read the object holds a pinned view of
            # the arena block (zero-copy reads) or a cached mmap; until
            # they all drop it the block is condemned, its offset can't
            # be reused, and the arena grows cold pages forever. Fan the
            # free out to the whole local pool (no-op for workers that
            # never read it) — the origin-only version leaked reader
            # pins.
            for w in workers:
                w.send(protocol.FreeObject(oid, gone))

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def _die(self):
        with self.lock:
            if self._shutdown:
                return
            self._shutdown = True
            workers = list(self.workers.values())
        for w in workers:
            w.send(protocol.KillWorker())
        for lst in (self._listener, self._peer_listener):
            if lst is None:
                continue
            try:
                lst.close()
            except OSError:
                pass
        deadline = time.monotonic() + 2.0
        for w in workers:
            if w.proc is None:
                continue
            try:
                while w.proc.poll() is None and time.monotonic() < deadline:
                    time.sleep(0.02)
                if w.proc.poll() is None:
                    w.proc.kill()
            except OSError:
                pass
        self.store.purge_spill()
        self.store.close()
        if os.environ.get("RAY_TPU_NODE_DIR") is None and \
                os.path.basename(os.path.dirname(self.node_dir)) != "nodes":
            # we created this node dir ourselves (cross-machine TCP join):
            # nobody else will sweep it
            import shutil
            shutil.rmtree(self.node_dir, ignore_errors=True)
        os._exit(0)


def main():
    head_address = sys.argv[1]
    node_id = sys.argv[2]
    resources = json.loads(sys.argv[3])
    num_tpus = int(sys.argv[4]) if len(sys.argv) > 4 else 0
    logging.basicConfig(level=logging.INFO)
    daemon = HostDaemon(head_address, node_id, resources, num_tpus)
    daemon.head_loop()


if __name__ == "__main__":
    main()
