"""TFRecord framing + tf.train.Example proto codec, dependency-free.

Shared by the Data tfrecord datasource (`data/datasource.py` — reference:
`data/datasource/tfrecords_datasource.py` reads Example records into
columns) and the Tune TensorBoard logger (event files use the same
record framing). The image vendors neither tensorflow nor crc32c, so the
framing ([len u64le][masked-crc32c(len)][payload][masked-crc32c(payload)])
and the three-field Example/Features/Feature protos are encoded by hand —
the schema is tiny and frozen.
"""

from __future__ import annotations

import struct

import numpy as np

# ---------------------------------------------------------------------------
# crc32c (software table) + tfrecord masking
# ---------------------------------------------------------------------------

_CRC_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ (0x82F63B78 if _c & 1 else 0)
    _CRC_TABLE.append(_c)


def _crc32c_py(data: bytes) -> int:
    crc = 0xFFFFFFFF
    table = _CRC_TABLE
    for b in data:
        crc = (crc >> 8) ^ table[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


try:
    # the C extension is ~1000x the pure-python loop — essential once the
    # codec sits on the Data read/write hot path, not just tiny tfevents
    import google_crc32c as _gcrc

    def crc32c(data: bytes) -> int:
        return _gcrc.value(bytes(data))
except ImportError:             # pragma: no cover - image always has it
    crc32c = _crc32c_py


def masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def write_record(f, payload: bytes) -> None:
    header = struct.pack("<Q", len(payload))
    f.write(header)
    f.write(struct.pack("<I", masked_crc(header)))
    f.write(payload)
    f.write(struct.pack("<I", masked_crc(payload)))


def read_records(path: str, verify: bool = True) -> list:
    """Payloads of a tfrecord file. `verify` checks both CRCs per record;
    truncation (writer crash, partial copy) raises ValueError, never a
    bare struct.error."""
    out = []
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if not header:
                return out
            if len(header) < 8:
                raise ValueError(f"{path}: truncated record header")
            (n,) = struct.unpack("<Q", header)
            hcrc_raw = f.read(4)
            payload = f.read(n)
            pcrc_raw = f.read(4)
            if len(hcrc_raw) < 4 or len(payload) < n or len(pcrc_raw) < 4:
                raise ValueError(f"{path}: truncated record")
            if verify:
                if struct.unpack("<I", hcrc_raw)[0] != masked_crc(header):
                    raise ValueError(f"{path}: corrupt record length crc")
                if struct.unpack("<I", pcrc_raw)[0] != masked_crc(payload):
                    raise ValueError(f"{path}: corrupt record payload crc")
            out.append(payload)


# ---------------------------------------------------------------------------
# protobuf wire helpers
# ---------------------------------------------------------------------------

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        bits = n & 0x7F
        n >>= 7
        if n:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _field(num: int, wire: int) -> bytes:
    return _varint((num << 3) | wire)


def _read_varint(buf: bytes, pos: int):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _iter_fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a proto message.
    Length-delimited values come back as bytes; varints as int; 32/64-bit
    as raw bytes."""
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        num, wire = tag >> 3, tag & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            val = buf[pos:pos + 4]
            pos += 4
        elif wire == 1:
            val = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield num, wire, val


# ---------------------------------------------------------------------------
# tf.train.Example codec
#
# Example{1: Features}; Features{1: map<string, Feature>} (map entry:
# 1 key, 2 value); Feature{oneof: 1 BytesList, 2 FloatList, 3 Int64List};
# BytesList{repeated 1 bytes}; FloatList{repeated packed 1 float};
# Int64List{repeated packed 1 int64}.
# ---------------------------------------------------------------------------

def _encode_feature(values) -> bytes:
    arr = np.asarray(values)
    if arr.dtype.kind in ("S", "U", "O"):
        payload = b""
        for v in np.atleast_1d(arr):
            b = v if isinstance(v, bytes) else str(v).encode()
            payload += _field(1, 2) + _varint(len(b)) + b
        return _field(1, 2) + _varint(len(payload)) + payload
    if arr.dtype.kind == "f":
        packed = np.atleast_1d(arr).astype("<f4").tobytes()
        body = _field(1, 2) + _varint(len(packed)) + packed
        return _field(2, 2) + _varint(len(body)) + body
    if arr.dtype.kind in ("i", "u", "b"):
        packed = b"".join(_varint(int(v) & 0xFFFFFFFFFFFFFFFF)
                          for v in np.atleast_1d(arr))
        body = _field(1, 2) + _varint(len(packed)) + packed
        return _field(3, 2) + _varint(len(body)) + body
    raise TypeError(f"cannot encode feature dtype {arr.dtype}")


def encode_example(features: dict) -> bytes:
    """{name: scalar|list|ndarray of bytes/str/float/int} -> Example."""
    fmap = b""
    for name, values in features.items():
        key = name.encode()
        feat = _encode_feature(values)
        entry = (_field(1, 2) + _varint(len(key)) + key
                 + _field(2, 2) + _varint(len(feat)) + feat)
        fmap += _field(1, 2) + _varint(len(entry)) + entry
    return _field(1, 2) + _varint(len(fmap)) + fmap


def _decode_feature(buf: bytes):
    for num, _wire, val in _iter_fields(buf):
        if num == 1:        # BytesList
            return [v for n2, _, v in _iter_fields(val) if n2 == 1]
        if num == 2:        # FloatList (packed or repeated f32)
            out = []
            for n2, w2, v in _iter_fields(val):
                if n2 != 1:
                    continue
                if w2 == 2:
                    out.extend(np.frombuffer(v, "<f4").tolist())
                else:
                    out.append(struct.unpack("<f", v)[0])
            return out
        if num == 3:        # Int64List (packed varints or repeated)
            out = []
            for n2, w2, v in _iter_fields(val):
                if n2 != 1:
                    continue
                if w2 == 2:
                    pos = 0
                    while pos < len(v):
                        iv, pos = _read_varint(v, pos)
                        if iv >= 1 << 63:
                            iv -= 1 << 64
                        out.append(iv)
                else:
                    if v >= 1 << 63:
                        v -= 1 << 64
                    out.append(v)
            return out
    return []


def decode_example(payload: bytes) -> dict:
    """Example bytes -> {name: list of python values}."""
    out = {}
    for num, _w, features_buf in _iter_fields(payload):
        if num != 1:
            continue
        for n2, _w2, entry in _iter_fields(features_buf):
            if n2 != 1:
                continue
            key = None
            feat = b""
            for n3, _w3, v in _iter_fields(entry):
                if n3 == 1:
                    key = v.decode()
                elif n3 == 2:
                    feat = v
            if key is not None:
                out[key] = _decode_feature(feat)
    return out
