"""Worker process entry point.

Counterpart of the reference's worker main + Cython `execute_task` callback
(`python/ray/_private/workers/default_worker.py` + `_raylet.pyx:1245`): a
process that registers with its node, receives pushed tasks, resolves
dependencies from the shared-memory store, runs user code, and seals results.

The same process hosts either a pool ("generic") worker or a dedicated
actor. Actor concurrency has two modes, mirroring the reference: classes
with any `async def` method run every call as a coroutine on a per-actor
event loop (max_concurrency = an asyncio.Semaphore; reference:
`_private/async_compat.py:19` + async execute_task in `_raylet.pyx`),
and plain classes with `max_concurrency > 1` use a thread pool (threaded
concurrency groups).
"""

from __future__ import annotations

import os
import queue
import sys
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from multiprocessing import connection

from ray_tpu._private import netaddr, protocol, serialization
from ray_tpu._private.object_store import ObjectStore
from ray_tpu.exceptions import RayTpuError, TaskError
from ray_tpu.util import tracing as _tracing

import contextvars

_ASYNC_TASK_ID: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_async_task_id", default=None)


class WorkerRuntime:
    """Per-worker state + the client channel back to the node server.

    `exit_on_disconnect` is True for real pool/actor workers (their whole
    purpose dies with the session) and False for client drivers embedded
    in a USER process (ray_tpu.init(address=...)) — killing the user's
    script on disconnect would be hostile."""

    def __init__(self, address: str, worker_id: str, authkey: bytes,
                 exit_on_disconnect: bool = True):
        self.worker_id = worker_id
        self.exit_on_disconnect = exit_on_disconnect
        self.conn = netaddr.client(address, authkey)
        if netaddr.is_tcp(address):
            # cross-machine client driver: no shared memory with the head —
            # object payloads ride inline both ways (the head inlines
            # GetReply locations for remote conns and re-materializes
            # oversized inline puts into its own store)
            self.store = None
        else:
            session_dir = os.path.dirname(address)
            self.store = ObjectStore(session_dir)
        self.functions: dict[str, object] = {}
        self.actor_instance = None
        self.actor_id: str | None = None
        self.task_queue: queue.Queue = queue.Queue()
        self._req_id = 0
        self._req_lock = threading.Lock()
        self._replies: dict[int, object] = {}
        self._reply_cv = threading.Condition()
        self._send_lock = threading.Lock()
        self._executor: ThreadPoolExecutor | None = None
        self._loop = None                # asyncio actors: per-actor loop
        self._async_sem = None
        self._io_executor: ThreadPoolExecutor | None = None
        self._current_task_ids = threading.local()
        self.shutdown = False
        # batched refcount events -> driver (hold/release/escape), flushed
        # by a timer so __del__ storms don't become a message storm. An
        # ORDERED (kind, oid) list: bucketing by kind would replay a
        # release-then-re-hold pair inside one flush window in the wrong
        # order and free an object with a live ref.
        self._ref_lock = threading.Lock()
        self._ref_pending: list[tuple[str, str]] = []
        # Pipelined submission state (credit window + replay ring).
        # Submissions stream without per-task acks; `_sub_ring` retains
        # every spec past the last credit so a SubmitNack (the head saw
        # a seq gap) or the resync timer can replay it. Guarded by
        # `_sub_cv`'s lock; `_sub_next` is the next seq to assign,
        # `_sub_acked` the highest credited seq.
        from ray_tpu._private import config as _config
        self._sub_pipelined = bool(_config.get("SUBMIT_PIPELINE"))
        self._sub_cv = threading.Condition()
        self._sub_ring: dict[int, object] = {}
        self._sub_next = 0
        self._sub_acked = -1
        self._sub_last_progress = time.monotonic()
        threading.Thread(target=self._ref_flush_loop,
                         name="ref-flush", daemon=True).start()

    # ---- channel ----------------------------------------------------------

    def send(self, msg):
        with self._send_lock:
            self.conn.send(msg)

    def _next_req_id(self) -> int:
        with self._req_lock:
            self._req_id += 1
            return self._req_id

    def request(self, make_msg):
        """Send a request carrying a fresh req_id; block for the reply."""
        req_id = self._next_req_id()
        self.send(make_msg(req_id))
        with self._reply_cv:
            while req_id not in self._replies:
                self._reply_cv.wait(1.0)
                if self.shutdown:
                    raise RuntimeError("worker shutting down")
            reply = self._replies.pop(req_id)
        if isinstance(reply, protocol.ErrorReply):
            raise RayTpuError(reply.error)
        return reply

    def reader_loop(self):
        while True:
            try:
                msg = self.conn.recv()
            except (EOFError, OSError, TypeError):
                if self.exit_on_disconnect:
                    os._exit(0)
                self.shutdown = True
                with self._reply_cv:
                    self._reply_cv.notify_all()
                return
            if isinstance(msg, protocol.PushTask):
                self.task_queue.put(msg)
            elif isinstance(msg, protocol.FreeObject):
                # all refs gone cluster-wide: drop this process's owner pin
                # so the arena block can actually be reclaimed
                try:
                    if self.store is not None:
                        self.store.delete(msg.desc)
                except Exception:
                    pass
            elif isinstance(msg, protocol.DumpStack):
                self.send(protocol.StackDumpReply(
                    msg.req_id, self.worker_id, os.getpid(),
                    _format_stacks()))
            elif isinstance(msg, protocol.LogBatch):
                # log_to_driver subscription: another process's output,
                # prefixed so interleaved sources stay attributable
                nid = msg.node_id or "head"
                for ln in msg.lines or ():
                    print(f"({msg.source}, node={nid}) {ln}",
                          file=sys.stderr)
            elif isinstance(msg, protocol.SetTracing):
                # driver enabled tracing after this worker spawned
                if msg.enabled:
                    _tracing._enable_local()
            elif isinstance(msg, protocol.KillWorker):
                self.shutdown = True
                self.task_queue.put(None)
                with self._reply_cv:
                    self._reply_cv.notify_all()
            elif isinstance(msg, protocol.SubmitCredit):
                self._on_submit_credit(msg.ack_seq)
            elif isinstance(msg, protocol.SubmitNack):
                with self._sub_cv:
                    self._replay_submits_locked(msg.expected_seq)
            elif isinstance(msg, (protocol.GetReply, protocol.WaitReply,
                                  protocol.SubmitReply,
                                  protocol.ActorCallReply,
                                  protocol.ErrorReply)):
                with self._reply_cv:
                    self._replies[msg.req_id] = msg
                    self._reply_cv.notify_all()

    # ---- object access (used by the ray_tpu client API in worker mode) ----

    def get_objects(self, object_ids, timeout=None):
        reply = self.request(lambda rid: protocol.GetRequest(
            rid, list(object_ids), timeout))
        if reply.timed_out:
            from ray_tpu.exceptions import GetTimeoutError
            raise GetTimeoutError(f"get() timed out: {object_ids[:3]}")
        if getattr(reply, "error", None):
            from ray_tpu.exceptions import ObjectFreedError, ObjectLostError
            cls_name, _, detail = reply.error.partition(": ")
            cls = (ObjectFreedError if cls_name == "ObjectFreedError"
                   else ObjectLostError)
            raise cls(detail or reply.error)
        out = []
        for oid in object_ids:
            out.append(self._read_with_refresh(oid, reply.locations[oid]))
        return out

    def _read_with_refresh(self, oid, desc, retries: int = 2):
        """Read a descriptor, re-fetching the location on a miss: a spill
        or copy-promotion may have moved the bytes after this descriptor
        was handed out (the spiller swaps the directory entry first, so a
        fresh location always resolves)."""
        from ray_tpu.exceptions import ObjectLostError
        if self.store is None:
            if desc.inline is None:
                raise ObjectLostError(
                    f"object {oid} arrived without inline payload on a "
                    "remote client connection")
            return serialization.loads(desc.inline)
        for attempt in range(retries + 1):
            try:
                return self.store.get(desc)
            except ObjectLostError:
                if attempt == retries:
                    raise
                reply = self.request(lambda rid: protocol.GetRequest(
                    rid, [oid], 30.0))
                if reply.timed_out or getattr(reply, "error", None) \
                        or oid not in reply.locations:
                    raise
                desc = reply.locations[oid]

    def put_object(self, value) -> str:
        from ray_tpu._private import ids
        oid = ids.new_object_id()
        if self.store is None:
            from ray_tpu._private.object_store import inline_descriptor
            desc = inline_descriptor(oid, value)
        else:
            desc = self.store.put(oid, value)
        self.send(protocol.PutRequest(oid, desc))
        return oid

    def wait_objects(self, object_ids, num_returns, timeout, fetch_local):
        reply = self.request(lambda rid: protocol.WaitRequest(
            rid, list(object_ids), num_returns, timeout, fetch_local))
        return reply.ready, reply.not_ready

    def submit_spec(self, spec):
        if not self._sub_pipelined:
            reply = self.request(
                lambda rid: protocol.SubmitRequest(rid, spec))
            if not reply.ok:
                raise RayTpuError(f"submit failed: {reply.error}")
            return
        # Pipelined: assign the next seq, retain the spec for replay,
        # block only when the credit window is exhausted. No reply is
        # awaited — submit failures surface as error objects stored
        # under the spec's return ids (matching how the reference's
        # async task submission reports scheduling errors).
        from ray_tpu._private.constants import (SUBMIT_RESYNC_S,
                                                SUBMIT_WINDOW)
        with self._sub_cv:
            while (self._sub_next - self._sub_acked > SUBMIT_WINDOW
                   and not self.shutdown):
                progressed = self._sub_cv.wait(SUBMIT_RESYNC_S)
                if not progressed:
                    self._replay_submits_locked(self._sub_acked + 1)
            if self.shutdown:
                raise RuntimeError("worker shutting down")
            seq = self._sub_next
            self._sub_next = seq + 1
            self._sub_ring[seq] = spec
        self.send(protocol.SubmitRequest(-1, spec, seq=seq))

    def _replay_submits_locked(self, from_seq: int) -> None:
        """Re-send every retained spec with seq >= from_seq in order
        (caller holds _sub_cv). Duplicates are dropped by the receiver's
        seq dedupe, which re-credits — so replay is idempotent and also
        recovers a lost credit."""
        for seq in sorted(self._sub_ring):
            if seq >= from_seq:
                self.send(protocol.SubmitRequest(
                    -1, self._sub_ring[seq], seq=seq))
        self._sub_last_progress = time.monotonic()

    def _on_submit_credit(self, ack_seq: int) -> None:
        with self._sub_cv:
            if ack_seq > self._sub_acked:
                self._sub_acked = ack_seq
                for seq in [s for s in self._sub_ring if s <= ack_seq]:
                    del self._sub_ring[seq]
                self._sub_last_progress = time.monotonic()
                self._sub_cv.notify_all()

    def _submit_resync(self) -> None:
        """Periodic (ref-flush cadence): with unacked submissions and no
        credit progress for SUBMIT_RESYNC_S, replay the ring — covers a
        lost tail message that no later gap would ever reveal."""
        from ray_tpu._private.constants import SUBMIT_RESYNC_S
        with self._sub_cv:
            if (self._sub_ring
                    and time.monotonic() - self._sub_last_progress
                    > SUBMIT_RESYNC_S):
                self._replay_submits_locked(self._sub_acked + 1)

    def control(self, method, payload=None):
        reply = self.request(lambda rid: protocol.ActorCallRequest(
            rid, method, payload))
        if reply.error is not None:
            raise RayTpuError(reply.error)
        return reply.result

    # ---- refcount event batching -----------------------------------------

    def enqueue_ref_event(self, kind: str, oid: str) -> None:
        with self._ref_lock:
            self._ref_pending.append((kind, oid))

    def _flush_ref_events(self) -> None:
        with self._ref_lock:
            if not self._ref_pending:
                return
            batch, self._ref_pending = self._ref_pending, []
        try:
            self.control("ref_update",
                         {"holder": self.worker_id, "events": batch})
        except Exception:
            pass  # driver gone; session over

    def _ref_flush_loop(self) -> None:
        from ray_tpu._private import worker as _worker_mod
        from ray_tpu._private.constants import REF_FLUSH_INTERVAL_S
        while not self.shutdown:
            time.sleep(REF_FLUSH_INTERVAL_S)
            _worker_mod._drain_decs()
            self._flush_ref_events()
            self._submit_resync()

    # ---- execution --------------------------------------------------------

    def current_task_id(self):
        # async actor methods record their id in a ContextVar (one per
        # asyncio task); sync paths use the thread-local
        tid = _ASYNC_TASK_ID.get()
        if tid is not None:
            return tid
        return getattr(self._current_task_ids, "task_id", None)

    def _resolve_fn(self, spec: protocol.TaskSpec):
        fn = self.functions.get(spec.function_id)
        if fn is None:
            if spec.function_blob is None:
                raise RayTpuError(
                    f"function {spec.function_desc} not cached and no blob")
            fn = serialization.loads_message(spec.function_blob)
            self.functions[spec.function_id] = fn
        return fn

    def _resolve_args(self, spec, arg_locations):
        def one(kind, v):
            if kind == "ref":
                loc = arg_locations.get(v)
                if loc is None:
                    # directory hole at push time (object lost mid-flight):
                    # fetch a fresh location — it resolves once the object
                    # is reconstructed or raises the terminal error
                    value = self.get_objects([v])[0]
                else:
                    value = self._read_with_refresh(v, loc)
            else:
                value = serialization.loads(v)
            return value
        args = [one(k, v) for k, v in spec.args]
        kwargs = {name: one(k, v) for name, (k, v) in spec.kwargs.items()}
        # Error propagation: a dependency that failed short-circuits this
        # task, surfacing the ORIGINAL error (reference: RayTaskError values
        # poison downstream tasks).
        for v in list(args) + list(kwargs.values()):
            if isinstance(v, (TaskError, RayTpuError)):
                raise _DepFailed(v)
        return args, kwargs

    def _start_task_span(self, spec: protocol.TaskSpec):
        """Attach the submitter's trace context and open `task.execute`.
        Gated on the stamped ctx, not on local enablement: a stamped spec
        proves the trace is live even if this worker predates the
        driver's enable_tracing() broadcast. Returns (span, token)."""
        if spec.trace_ctx is None:
            return None
        return _tracing.start_span(
            "task.execute",
            {"task_id": spec.task_id,
             "name": spec.name or spec.function_desc,
             "worker_id": self.worker_id},
            parent=spec.trace_ctx)

    def run_task(self, push: protocol.PushTask):
        spec = push.spec
        chips = os.environ.get("TPU_VISIBLE_CHIPS")
        self._current_task_ids.task_id = spec.task_id
        sp = self._start_task_span(spec)
        exec_start = time.time()
        try:
            is_actor_method = (spec.actor_id is not None
                               and not spec.actor_creation)
            fn = None if is_actor_method else self._resolve_fn(spec)
            args, kwargs = self._resolve_args(spec, push.arg_locations)
            if spec.actor_creation:
                cls = fn
                self.actor_instance = cls(*args, **kwargs)
                self.actor_id = spec.actor_id
                result = None
                values = [None] * spec.num_returns
            elif spec.actor_id is not None:
                method = getattr(self.actor_instance, spec.method_name)
                result = method(*args, **kwargs)
                values = self._split_returns(result, spec.num_returns)
            else:
                result = fn(*args, **kwargs)
                values = self._split_returns(result, spec.num_returns)
            error = False
        except _DepFailed as df:
            values = [df.cause] * spec.num_returns
            error = True
        except BaseException as e:
            tb = traceback.format_exc()
            te = TaskError(type(e).__name__, str(e), tb, cause=e)
            values = [te] * spec.num_returns
            error = True
        finally:
            self._current_task_ids.task_id = None
        exec_end = time.time()
        if sp is not None:
            _tracing.end_span(sp[0], sp[1],
                              error="task_error" if error else None)
        self._seal_and_send(spec, values, error, exec_start, exec_end)

    def _drain_spans_for_push(self):
        """This process's buffered tracing spans (plus any worker-resident
        FlightRecorder spans), to piggyback on the next TaskDone. Cheap
        when tracing never ran: one deque emptiness check."""
        spans = _tracing.drain_spans()
        if "ray_tpu.util.telemetry" in sys.modules:
            from ray_tpu.util import telemetry as _telemetry
            spans += _telemetry.drain_recorder_spans()
        return spans or None

    def _seal_and_send(self, spec, values, error,
                       exec_start=None, exec_end=None):
        descs = []
        for oid, value in zip(spec.return_ids, values):
            try:
                descs.append(self.store.put(oid, value))
            except BaseException as e:   # unpicklable return, etc.
                tb = traceback.format_exc()
                te = TaskError(type(e).__name__,
                               f"failed to serialize result: {e}", tb)
                descs.append(self.store.put(oid, te))
                error = True
        self.send(protocol.TaskDone(
            task_id=spec.task_id, return_descs=descs, error=error,
            actor_ready=spec.actor_creation and not error,
            exec_start_ts=exec_start, exec_end_ts=exec_end,
            spans=self._drain_spans_for_push()))

    @staticmethod
    def _split_returns(result, num_returns):
        if num_returns == 1:
            return [result]
        out = list(result)
        if len(out) != num_returns:
            raise ValueError(
                f"task declared num_returns={num_returns} but returned "
                f"{len(out)} values")
        return out

    # ---- asyncio actor runtime -------------------------------------------
    # Async actors (any `async def` method) run their methods as
    # coroutines on ONE per-actor event loop with max_concurrency as an
    # asyncio.Semaphore — thousands of concurrent slow requests overlap
    # on awaits instead of burning a thread each (reference:
    # `_private/async_compat.py:19` get_new_event_loop + async task
    # execution in `_raylet.pyx` execute_task; Serve's replica relies on
    # exactly this).

    def _start_actor_event_loop(self, max_concurrency: int):
        import asyncio
        self._loop = asyncio.new_event_loop()
        self._async_sem = None
        # blocking work (dependency resolution via store/network, result
        # sealing) leaves the loop for this pool so awaits keep flowing
        self._io_executor = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="actor-io")

        def run():
            asyncio.set_event_loop(self._loop)
            self._async_sem = asyncio.Semaphore(max_concurrency)
            self._loop.run_forever()
        t = threading.Thread(target=run, daemon=True,
                             name="actor-eventloop")
        t.start()
        while self._async_sem is None:   # loop thread publishing the sem
            time.sleep(0.001)

    async def _run_task_async(self, push: protocol.PushTask):
        import asyncio
        import contextlib
        import inspect as _inspect
        spec = push.spec
        loop = asyncio.get_running_loop()
        # Control-plane exemption (reference: Ray's concurrency groups —
        # actor classes route health/stats RPCs through a group that
        # data-plane calls cannot saturate). A class may declare
        # `_control_plane_methods`: those methods skip the
        # max_concurrency semaphore, so a scrape or health ping is never
        # queued behind a full window of long-blocking data calls.
        # (Observed: serve replicas with max_concurrency streams all
        # parked in next_chunks starved the controller's stats fan-out.)
        gate = self._async_sem
        if spec.method_name in getattr(type(self.actor_instance),
                                       "_control_plane_methods", ()):
            gate = contextlib.nullcontext()
        async with gate:
            # each asyncio task has its own context, so the current-task
            # id — and the attached trace context — survive interleaving
            # (a thread-local cannot)
            _ASYNC_TASK_ID.set(spec.task_id)
            sp = self._start_task_span(spec)
            exec_start = time.time()
            try:
                args, kwargs = await loop.run_in_executor(
                    self._io_executor, self._resolve_args, spec,
                    push.arg_locations)
                method = getattr(self.actor_instance, spec.method_name)
                result = method(*args, **kwargs)
                if _inspect.isawaitable(result):
                    result = await result
                values = self._split_returns(result, spec.num_returns)
                error = False
            except _DepFailed as df:
                values = [df.cause] * spec.num_returns
                error = True
            except BaseException as e:
                tb = traceback.format_exc()
                te = TaskError(type(e).__name__, str(e), tb, cause=e)
                values = [te] * spec.num_returns
                error = True
            exec_end = time.time()
            if sp is not None:
                _tracing.end_span(sp[0], sp[1],
                                  error="task_error" if error else None)
            await loop.run_in_executor(
                self._io_executor, self._seal_and_send, spec, values,
                error, exec_start, exec_end)

    def main_loop(self):
        import asyncio
        while not self.shutdown:
            push = self.task_queue.get()
            if push is None:
                break
            spec = push.spec
            if spec.actor_creation:
                max_concurrency = (spec.actor_options or {}).get(
                    "max_concurrency", 1)
                self.run_task(push)      # constructs the instance
                # async-ness is decided from the CLASS with the same
                # predicate the driver uses (actor.py _is_async_class):
                # instance-level getattr would execute property getters,
                # and dunder filtering would miss `async def __call__`
                from ray_tpu.actor import _is_async_class
                if self.actor_instance is not None and \
                        _is_async_class(type(self.actor_instance)):
                    self._start_actor_event_loop(max_concurrency)
                elif max_concurrency > 1:
                    self._executor = ThreadPoolExecutor(
                        max_workers=max_concurrency,
                        thread_name_prefix="actor-method")
            elif self._loop is not None:
                asyncio.run_coroutine_threadsafe(
                    self._run_task_async(push), self._loop)
            elif self._executor is not None:
                self._executor.submit(self.run_task, push)
            else:
                self.run_task(push)
        os._exit(0)


def _format_stacks() -> str:
    """Every thread's Python stack, named (the `ray stack` payload)."""
    import traceback
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(tid, tid)} ---")
        out.extend(ln.rstrip()
                   for ln in traceback.format_stack(frame))
    return "\n".join(out)


class _DepFailed(Exception):
    def __init__(self, cause):
        self.cause = cause


def run(address: str, worker_id: str):
    """Worker entry, callable both from exec (main) and from a
    forkserver child (forkserver.py) — the child passes args directly
    instead of re-parsing argv."""
    authkey = bytes.fromhex(os.environ["RAY_TPU_AUTHKEY"])
    rt = WorkerRuntime(address, worker_id, authkey)
    _tracing.set_process_label(f"worker:{worker_id}")
    rt.send(protocol.RegisterWorker(worker_id, os.getpid()))

    # Install this runtime as the process-global client so user code can call
    # ray_tpu.get/put/remote/... inside tasks (nested submission).
    from ray_tpu._private import worker as worker_mod
    worker_mod.connect_worker_mode(rt)

    # Span drain must not depend on the process ever registering a
    # metric (the proxy records spans but owns no counters).
    from ray_tpu.util import metrics as _metrics
    _metrics.ensure_flusher()

    threading.Thread(target=rt.reader_loop, daemon=True,
                     name="ray_tpu-worker-reader").start()
    rt.main_loop()


def main():
    run(sys.argv[1], sys.argv[2])


if __name__ == "__main__":
    main()
