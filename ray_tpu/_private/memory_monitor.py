"""Host memory monitor + worker-killing policy.

Counterpart of the reference's node memory monitor
(`src/ray/common/memory_monitor.h:52`) and worker-killing policies
(`worker_killing_policy_retriable_fifo.h`): when host memory usage crosses
the threshold, kill the newest worker running a retriable task (so the
work is retried) — or, failing that, the newest busy worker — instead of
letting the kernel OOM-killer take down the head or a daemon.

Disabled when RAY_TPU_MEMORY_MONITOR_THRESHOLD=0.
"""

from __future__ import annotations

import logging
import threading
import time

from ray_tpu._private import constants

logger = logging.getLogger("ray_tpu")


def _meminfo_fraction() -> float:
    total = avail = None
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1])
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1])
                if total is not None and avail is not None:
                    break
    except OSError:
        return 0.0
    if not total or avail is None:
        return 0.0
    return 1.0 - avail / total


def _read_int(path: str) -> int | None:
    try:
        with open(path) as f:
            txt = f.read().strip()
        return None if txt == "max" else int(txt)
    except (OSError, ValueError):
        return None


def _cgroup_fraction() -> float | None:
    """Usage fraction against the cgroup memory limit (v2 then v1); None
    when unlimited/unreadable. Inside a memory-limited container the
    cgroup limit is the real ceiling — /proc/meminfo is the HOST's (the
    reference's memory monitor consults cgroups the same way)."""
    for limit_p, used_p in (
            ("/sys/fs/cgroup/memory.max", "/sys/fs/cgroup/memory.current"),
            ("/sys/fs/cgroup/memory/memory.limit_in_bytes",
             "/sys/fs/cgroup/memory/memory.usage_in_bytes")):
        limit = _read_int(limit_p)
        used = _read_int(used_p)
        if limit and used is not None and limit < (1 << 60):
            return used / limit
    return None


def host_memory_fraction() -> float:
    """Fraction of available memory in use: the tighter of host meminfo
    and this process tree's cgroup limit."""
    frac = _meminfo_fraction()
    cg = _cgroup_fraction()
    return max(frac, cg) if cg is not None else frac


class MemoryMonitor:
    """Polls host memory; kills one worker per trip above the threshold.
    `usage_fn` is injectable for tests."""

    def __init__(self, node_server, threshold: float | None = None,
                 interval: float | None = None, usage_fn=None):
        self.node = node_server
        self.threshold = (constants.MEMORY_MONITOR_THRESHOLD
                          if threshold is None else threshold)
        self.interval = (constants.MEMORY_MONITOR_INTERVAL_S
                         if interval is None else interval)
        self.usage_fn = usage_fn or host_memory_fraction
        self.kills = 0
        self._thread: threading.Thread | None = None

    def start(self):
        if self.threshold <= 0:
            return
        self._thread = threading.Thread(
            target=self._loop, name="ray_tpu-memmon", daemon=True)
        self._thread.start()

    def _loop(self):
        while not self.node._shutdown:
            time.sleep(self.interval)
            try:
                self.tick()
            except Exception:
                logger.exception("memory monitor tick failed")

    def tick(self) -> bool:
        """One check; returns True if a worker was killed."""
        usage = self.usage_fn()
        if usage < self.threshold:
            return False
        victim = self.pick_victim()
        if victim is None:
            return False
        w, retriable = victim
        logger.warning(
            "memory pressure %.0f%% >= %.0f%%: killing worker %s "
            "(task %s, %s)", usage * 100, self.threshold * 100,
            w.worker_id,
            w.current.spec.task_id if w.current else "?",
            "will retry" if retriable else "NOT retriable")
        self.kills += 1
        try:
            w.proc.kill()
        except OSError:
            return False
        return True

    def pick_victim(self):
        """Newest busy worker, preferring ones whose task can retry
        (retriable-FIFO: kill the most recently started retriable work
        first — it loses the least progress and costs nothing to redo)."""
        with self.node.lock:
            busy = [w for w in self.node.workers.values()
                    if w.alive and w.current is not None
                    and w.proc is not None]
            if not busy:
                return None
            retriable = [w for w in busy if w.current.retries_left > 0]
            pool = retriable or busy
            return pool[-1], bool(retriable)
