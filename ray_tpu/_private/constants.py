"""Session-wide constants for the ray_tpu core runtime.

Counterpart of the reference's `python/ray/_private/ray_constants.py` plus the
native config table (`src/ray/common/ray_config_def.h`): every tunable is
env-overridable with the ``RAY_TPU_`` prefix, mirroring the reference's
``RAY_<name>`` convention (ray_config.h:74).
"""

import os


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get("RAY_TPU_" + name, default))


def _env_float(name: str, default: float) -> float:
    return float(os.environ.get("RAY_TPU_" + name, default))


def _env_str(name: str, default: str) -> str:
    return os.environ.get("RAY_TPU_" + name, default)


# Objects whose serialized envelope is at most this many bytes travel inline in
# control messages; larger ones go to the shared-memory store (the reference
# inlines <=100KB returns in the gRPC reply, core_worker.cc).
INLINE_OBJECT_MAX_BYTES = _env_int("INLINE_OBJECT_MAX_BYTES", 100 * 1024)

# Where shared-memory object files live (tmpfs). The reference mounts plasma
# over /dev/shm (plasma/store.h); we use one file per object under a session
# directory, which keeps ownership trivially correct (driver unlinks on exit).
SHM_ROOT = _env_str("SHM_ROOT", "/dev/shm" if os.path.isdir("/dev/shm") else "/tmp")

SESSION_PREFIX = "ray_tpu_session_"

# Worker pool sizing: hard cap on generic (non-actor) worker processes.
MAX_WORKERS_CAP = _env_int("MAX_WORKERS_CAP", 32)

# Seconds to wait for a spawned worker process to phone home before declaring
# startup failure (reference: worker_register_timeout_seconds).
WORKER_REGISTER_TIMEOUT_S = _env_float("WORKER_REGISTER_TIMEOUT_S", 60.0)

# Default resource requests (reference: task default num_cpus=1; actors hold 0
# lifetime CPUs unless explicitly requested — ray_option_utils.py).
DEFAULT_TASK_NUM_CPUS = 1.0
DEFAULT_ACTOR_LIFETIME_CPUS = 0.0

# Buffer alignment inside serialized envelopes so zero-copy numpy views are
# 64-byte aligned (plasma aligns to 64 as well).
BUFFER_ALIGNMENT = 64

# Polling granularity for blocking waits.
WAIT_POLL_S = 0.01

# How many times a lost task-produced object may be rebuilt from lineage
# before readers get ObjectLostError (reference: task max retries gate
# reconstruction, object_recovery_manager.h:41 + task_manager.h:173).
MAX_OBJECT_RECONSTRUCTIONS = _env_int("MAX_OBJECT_RECONSTRUCTIONS", 3)

# Lineage table caps: specs of recent task-produced objects are kept for
# reconstruction, bounded BOTH by entry count and by accumulated spec
# bytes (function blobs + inline args — the reference's
# RAY_max_lineage_bytes); oldest entries evict first and their objects
# simply stop being reconstructable.
MAX_LINEAGE_ENTRIES = _env_int("MAX_LINEAGE_ENTRIES", 100_000)
MAX_LINEAGE_BYTES = _env_int("MAX_LINEAGE_BYTES", 256 * 1024 * 1024)

# Object spilling (reference: LocalObjectManager + external_storage.py
# FileSystemStorage): arena-overflow objects and proactively spilled
# objects land under OBJECT_SPILL_ROOT on real disk — NOT tmpfs — so a
# session's shm usage is bounded by the arena capacity. The store owner
# spills sealed objects above SPILL_HIGH_WATER of arena capacity until
# usage drops below SPILL_LOW_WATER.
OBJECT_SPILL_ROOT = _env_str("OBJECT_SPILL_ROOT", "/tmp/ray_tpu_spill")
SPILL_HIGH_WATER = _env_float("SPILL_HIGH_WATER", 0.80)
SPILL_LOW_WATER = _env_float("SPILL_LOW_WATER", 0.50)

# Memory monitor (reference: memory_monitor.h:52 + worker-killing
# policies): when host memory usage exceeds the threshold fraction, the
# newest worker running a retriable task is killed (and retried) instead
# of letting the OS OOM-killer take down a daemon. 0 disables.
MEMORY_MONITOR_THRESHOLD = _env_float("MEMORY_MONITOR_THRESHOLD", 0.95)
MEMORY_MONITOR_INTERVAL_S = _env_float("MEMORY_MONITOR_INTERVAL_S", 1.0)

# How many task submissions a single client may have in flight before
# submit blocks (simple backpressure; reference has per-lease backlogs).
MAX_INFLIGHT_SUBMISSIONS = _env_int("MAX_INFLIGHT_SUBMISSIONS", 100000)

# Env var handed to workers that were allocated TPU chips, mirroring how the
# reference sets CUDA_VISIBLE_DEVICES from the resource assignment
# (_private/utils.py:342-355).
TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
