"""Session-wide constants for the ray_tpu core runtime.

Counterpart of the reference's `python/ray/_private/ray_constants.py` plus
the native config table (`src/ray/common/ray_config_def.h`): every tunable
is declared once in the typed option table (`_private/config.py` —
name, type, default, doc) and env-overridable with the ``RAY_TPU_``
prefix, mirroring the reference's ``RAY_<name>`` convention
(ray_config.h:74). `ray_tpu config list` (scripts/cli.py) prints the
table with effective values.
"""

import os

from ray_tpu._private.config import define

# Objects whose serialized envelope is at most this many bytes travel inline
# in control messages; larger ones go to the shared-memory store (the
# reference inlines <=100KB returns in the gRPC reply, core_worker.cc).
INLINE_OBJECT_MAX_BYTES = define(
    "INLINE_OBJECT_MAX_BYTES", int, 100 * 1024,
    "Objects at most this many serialized bytes ride inline in control "
    "messages instead of the shared-memory store.")

# Where shared-memory object files live (tmpfs). The reference mounts plasma
# over /dev/shm (plasma/store.h); we use one file per object under a session
# directory, which keeps ownership trivially correct (driver unlinks on exit).
SHM_ROOT = define(
    "SHM_ROOT", str, "/dev/shm" if os.path.isdir("/dev/shm") else "/tmp",
    "Root for session directories (object arena + sockets); tmpfs.")

SESSION_PREFIX = "ray_tpu_session_"

MAX_WORKERS_CAP = define(
    "MAX_WORKERS_CAP", int, 32,
    "Hard cap on generic (pool) worker processes per node.")

WORKER_REGISTER_TIMEOUT_S = define(
    "WORKER_REGISTER_TIMEOUT_S", float, 60.0,
    "Seconds to wait for a spawned worker/daemon to phone home before "
    "declaring startup failure (reference: "
    "worker_register_timeout_seconds).")

DEFAULT_TASK_NUM_CPUS = define(
    "DEFAULT_TASK_NUM_CPUS", float, 1.0,
    "CPUs a task holds when @remote doesn't say (reference: tasks "
    "default to num_cpus=1, ray_option_utils.py).")

DEFAULT_ACTOR_LIFETIME_CPUS = define(
    "DEFAULT_ACTOR_LIFETIME_CPUS", float, 0.0,
    "CPUs an actor holds for its lifetime when @remote doesn't say "
    "(reference: actors hold 0 lifetime CPUs by default).")

BUFFER_ALIGNMENT = define(
    "BUFFER_ALIGNMENT", int, 64,
    "Byte alignment of buffers inside serialized envelopes so zero-copy "
    "numpy views land 64-byte aligned (plasma aligns to 64 too).")

WAIT_POLL_S = define(
    "WAIT_POLL_S", float, 0.01,
    "Polling granularity for blocking waits in the client runtime.")

MAX_INFLIGHT_SUBMISSIONS = define(
    "MAX_INFLIGHT_SUBMISSIONS", int, 100_000,
    "How many task submissions a single client may have in flight before "
    "submit blocks (reference has per-lease backlogs).")

# Env var handed to workers that were allocated TPU chips, mirroring how the
# reference sets CUDA_VISIBLE_DEVICES from the resource assignment
# (_private/utils.py:342-355).
TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"

MAX_OBJECT_RECONSTRUCTIONS = define(
    "MAX_OBJECT_RECONSTRUCTIONS", int, 3,
    "How many times a lost task-produced object may be rebuilt from "
    "lineage before readers get ObjectLostError (reference: task max "
    "retries gate reconstruction, object_recovery_manager.h:41).")

MAX_LINEAGE_ENTRIES = define(
    "MAX_LINEAGE_ENTRIES", int, 100_000,
    "Lineage table entry cap; oldest specs evict first and their objects "
    "stop being reconstructable.")

MAX_LINEAGE_BYTES = define(
    "MAX_LINEAGE_BYTES", int, 256 * 1024 * 1024,
    "Lineage table byte cap over retained specs (function blobs + inline "
    "args) — the reference's RAY_max_lineage_bytes.")

OBJECT_SPILL_ROOT = define(
    "OBJECT_SPILL_ROOT", str, "/tmp/ray_tpu_spill",
    "Real-disk root for arena-overflow and spilled objects (reference: "
    "external_storage.py FileSystemStorage); bounds shm usage by the "
    "arena capacity.")

SPILL_HIGH_WATER = define(
    "SPILL_HIGH_WATER", float, 0.80,
    "Arena-usage fraction above which the store owner spills sealed "
    "objects to disk (local_object_manager.h:110).")

SPILL_LOW_WATER = define(
    "SPILL_LOW_WATER", float, 0.50,
    "Spill passes drain arena usage down to this fraction.")

MEMORY_MONITOR_THRESHOLD = define(
    "MEMORY_MONITOR_THRESHOLD", float, 0.95,
    "Host/cgroup memory-usage fraction above which the newest retriable "
    "worker is killed (memory_monitor.h:52); 0 disables.")

MEMORY_MONITOR_INTERVAL_S = define(
    "MEMORY_MONITOR_INTERVAL_S", float, 1.0,
    "Memory monitor poll interval in seconds.")

OBJECT_STORE_BYTES = define(
    "OBJECT_STORE_BYTES", int, 0,
    "Shared-memory arena capacity per node (plasma store size analog). "
    "0 = auto: 20% of system memory, min 512 MiB (the reference sizes "
    "plasma at 30% of RAM by default; the arena file is sparse, so "
    "unused capacity costs nothing).")

RUNTIME_ENV_CACHE = define(
    "RUNTIME_ENV_CACHE", str, "/tmp/ray_tpu_runtime_envs",
    "Content-addressed cache dir for materialized runtime environments "
    "(working_dir copies, pip venvs; reference: uri_cache.py).")

RUNTIME_ENV_CACHE_ENTRIES = define(
    "RUNTIME_ENV_CACHE_ENTRIES", int, 20,
    "LRU cap on cached runtime-env entries.")

PUBSUB_RING_MESSAGES = define(
    "PUBSUB_RING_MESSAGES", int, 1000,
    "Per-channel cap on retained pubsub messages (long-poll publisher "
    "ring, reference: publisher.h buffered channels).")

# --- transport (reference: gRPC-over-TCP for every cross-host edge,
# src/ray/rpc/grpc_server.h; UDS only worker<->local raylet) ---

TRANSPORT = define(
    "TRANSPORT", str, "uds",
    "Cluster transport for daemon/client<->head and peer pulls: 'uds' "
    "(single machine) or 'tcp' (cluster spans machines). Workers always "
    "ride UDS to their local daemon, like the reference. Read at init() "
    "time via config.get, so tests can flip it per-session.")

HEAD_PORT = define(
    "HEAD_PORT", int, 0,
    "TCP port for the head listener when TRANSPORT=tcp (0 = ephemeral). "
    "Reference: --port on `ray start --head` (scripts.py:537).")

HEAD_BIND_HOST = define(
    "HEAD_BIND_HOST", str, "0.0.0.0",
    "Bind host for the head's TCP listener.")

NODE_IP = define(
    "NODE_IP", str, "",
    "Advertised IP of this machine for cross-host dials; empty = "
    "autodetect via the outbound interface (reference: "
    "node_ip_address detection, services.py:1353).")

DAEMON_RECONNECT_GRACE_S = define(
    "DAEMON_RECONNECT_GRACE_S", float, 60.0,
    "How long a HostDaemon keeps retrying the head channel after it "
    "closes (head crash/restart) before giving up and dying "
    "(reference: raylets ride out GCS restarts, "
    "gcs_rpc_server_reconnect_timeout_s). 0 disables reconnect.")

HEAD_SNAPSHOT_INTERVAL_S = define(
    "HEAD_SNAPSHOT_INTERVAL_S", float, 1.0,
    "Standalone-head metadata snapshot period (named actors, KV, jobs, "
    "placement groups -> session_dir/head_state.pkl; reference: "
    "Redis-backed GCS persistence, redis_store_client.h:33).")

HEAD_SNAPSHOT_URI = define(
    "HEAD_SNAPSHOT_URI", str, "",
    "Optional URI (mem:// fake, registered gs://...) the standalone "
    "head mirrors its metadata snapshot to; a NEW head on ANY machine "
    "restores from it when its session dir has no local snapshot — "
    "head failover (reference: Redis-backed GCS persistence, "
    "redis_store_client.h:33).")

AUTOSCALER_UPDATE_INTERVAL_S = define(
    "AUTOSCALER_UPDATE_INTERVAL_S", float, 1.0,
    "Head monitor tick: refresh LoadMetrics from cluster state and run "
    "StandardAutoscaler.update (reference: monitor.py:371 loop, "
    "AUTOSCALER_UPDATE_INTERVAL_S=5).")

WORKER_LOG_REDIRECT = define(
    "WORKER_LOG_REDIRECT", bool, True,
    "Write each worker/daemon process's stdout+stderr to its own file "
    "under the session (node) logs dir instead of inheriting the "
    "driver's terminal (reference: per-process files under the session "
    "dir, log_monitor.py). Disable for raw interleaved output.")

LOG_TAIL_INTERVAL_S = define(
    "LOG_TAIL_INTERVAL_S", float, 0.5,
    "How often the head/daemon LogTailer polls its local log files for "
    "new lines (reference: LOG_NAME_UPDATE_INTERVAL_S).")

LOG_RING_LINES = define(
    "LOG_RING_LINES", int, 2000,
    "Per-source cap on log lines the head retains for the dashboard "
    "/api/logs endpoint and `ray_tpu logs`.")

PG_AUTOSCALE_WAIT_S = define(
    "PG_AUTOSCALE_WAIT_S", float, 60.0,
    "With an autoscaler attached, how long placement-group creation "
    "waits for capacity (the gang rides the demand queue) before "
    "raising PlacementGroupError (reference: PENDING placement groups "
    "feed autoscaler demand).")

# --- object data plane (object_manager.h chunking / pull admission) ---

PULL_CHUNK_BYTES = define(
    "PULL_CHUNK_BYTES", int, 8 << 20,
    "Chunk size for node-to-node object pulls (reference: "
    "object_manager_default_chunk_size; 8 MiB measured best for GiB-"
    "scale broadcasts on the pickle-framed channel, see SCALE.json).")

PULL_TIMEOUT_S = define(
    "PULL_TIMEOUT_S", float, 120.0,
    "Deadline for one chunked object pull before the reader declares "
    "the object unavailable from that source.")

PULL_RETRY_ATTEMPTS = define(
    "PULL_RETRY_ATTEMPTS", int, 4,
    "How many sources/attempts a head-side pull tries (promotion or "
    "reconstruction can re-home the object between attempts).")

OBJECT_REPLACEMENT_WAIT_S = define(
    "OBJECT_REPLACEMENT_WAIT_S", float, 60.0,
    "After an object's source died mid-pull, how long to wait for a "
    "promoted copy or lineage reconstruction to re-register it.")

SUBMIT_INLINE_BACKLOG = define(
    "SUBMIT_INLINE_BACKLOG", int, 32,
    "Pending-queue depth beyond which task submission skips its inline "
    "dispatch attempt and becomes a pure enqueue: with a deep backlog "
    "the attempt is futile (older tasks wait on the same capacity) and "
    "completions pull from the backlog directly. Keeps saturated "
    "submission O(1) while idle-cluster submit->execute latency stays "
    "on the fast path.")

SCHEDULER_DISPATCH_WINDOW = define(
    "SCHEDULER_DISPATCH_WINDOW", int, 64,
    "Max non-dispatchable tasks one schedule pass examines before "
    "leaving the rest queued (the pass rotates the examined prefix to "
    "the back, so successive passes cover the whole backlog). Bounds "
    "every scheduling event to O(window) instead of O(backlog) — the "
    "reference caps its dispatch loop the same way.")

FREED_REFS_CAP = define(
    "FREED_REFS_CAP", int, 100_000,
    "Bounded FIFO of freed object ids kept as tombstones so racing "
    "get/wait calls fail fast instead of hanging.")

ARGS_RELEASED_CAP = define(
    "ARGS_RELEASED_CAP", int, 200_000,
    "Bounded FIFO of task ids whose args were already released "
    "(exactly-once guard on the refcount decrement).")

COLLECTIVE_MAX_BYTES = define(
    "COLLECTIVE_MAX_BYTES", int, 64 << 20,
    "Per-payload cap on host-side util.collective verbs — the rendezvous "
    "actor is a control-plane funnel; device tensors belong in-graph "
    "(psum/all_gather over a Mesh axis).")

DATA_PUSH_SHUFFLE_MIN_BLOCKS = define(
    "DATA_PUSH_SHUFFLE_MIN_BLOCKS", int, 32,
    "Input-block count above which all-to-all data exchanges insert the "
    "push-based merge tier (push_based_shuffle.py analog): ~sqrt(M) "
    "merger fan-in instead of every reducer fetching from all M maps.")

RUNTIME_ENV_CACHE_BYTES = define(
    "RUNTIME_ENV_CACHE_BYTES", int, 10 << 30,
    "Total-bytes cap on the runtime-env cache; least-recently-used "
    "entries are evicted above it (uri_cache.py byte budget analog).")

RUNTIME_ENV_CONDA_TIMEOUT_S = define(
    "RUNTIME_ENV_CONDA_TIMEOUT_S", float, 1800.0,
    "Timeout for `conda env create` when materializing a conda "
    "runtime environment.")

CONDA_BINARY = define(
    "CONDA_BINARY", str, "conda",
    "Conda executable used for runtime_env={'conda': ...}.")

CONTAINER_RUNTIME = define(
    "CONTAINER_RUNTIME", str, "",
    "Container runtime for runtime_env={'container': ...}; empty = "
    "autodetect docker then podman.")

HEAD_BACKLOG_CAP = define(
    "HEAD_BACKLOG_CAP", int, 10_000,
    "Max daemon->head messages buffered during a head-channel blip for "
    "replay after reconnect (completions must survive the window).")

# --- control-plane timeouts / cadences ---

HEAD_CONTROL_TIMEOUT_S = define(
    "HEAD_CONTROL_TIMEOUT_S", float, 30.0,
    "Daemon-issued control RPCs to the head (peer address lookup etc.) "
    "fail after this many seconds.")

ACTOR_LEASE_WAIT_S = define(
    "ACTOR_LEASE_WAIT_S", float, 30.0,
    "How long a daemon waits for an actor's worker to (re)appear before "
    "failing a leased actor method call.")

ATTACH_CONTROL_TIMEOUT_S = define(
    "ATTACH_CONTROL_TIMEOUT_S", float, 30.0,
    "Default timeout for CLI/job attach-client control calls.")

SPILL_PASS_INTERVAL_S = define(
    "SPILL_PASS_INTERVAL_S", float, 1.0,
    "How often the head/daemon spill loop checks the arena high-water "
    "mark (local_object_manager spill polling analog).")

REF_FLUSH_INTERVAL_S = define(
    "REF_FLUSH_INTERVAL_S", float, 0.5,
    "Workers batch ObjectRef hold/release events and flush them to the "
    "head at this cadence (__del__ storms never become message storms).")

JOB_ADOPT_POLL_S = define(
    "JOB_ADOPT_POLL_S", float, 0.5,
    "Poll interval while a restarted head watches an adopted job's "
    "process for exit.")

METRICS_FLUSH_PERIOD_S = define(
    "METRICS_FLUSH_PERIOD_S", float, 5.0,
    "Workers push metric snapshots to the head at this cadence "
    "(reference: metrics_report_interval_ms).")

TASK_EVENT_QUERY_LIMIT = define(
    "TASK_EVENT_QUERY_LIMIT", int, 10_000,
    "Default cap on task records returned by the state API "
    "(reference: RAY_MAX_LIMIT_FROM_API_SERVER).")

GC_STALE_SESSIONS = define(
    "GC_STALE_SESSIONS", bool, True,
    "init() sweeps session dirs whose driver/head process is dead "
    "before creating a new one.")

DASHBOARD_BIND_HOST = define(
    "DASHBOARD_BIND_HOST", str, "127.0.0.1",
    "Bind host for the dashboard HTTP server.")

# --- ray_tpu.data streaming executor budgets (reference: Data streaming
# backpressure, streaming_executor_state.py) ---

DATA_MAX_TASKS_IN_FLIGHT = define(
    "DATA_MAX_TASKS_IN_FLIGHT", int, 8,
    "Per-operator cap on concurrently running Data tasks when the "
    "DataContext doesn't override it.")

DATA_BYTES_IN_FLIGHT = define(
    "DATA_BYTES_IN_FLIGHT", int, 128 * 1024 * 1024,
    "Per-operator byte budget of in-flight blocks (streaming "
    "backpressure, reference byte-budget model).")

DATA_BLOCK_SIZE_ESTIMATE = define(
    "DATA_BLOCK_SIZE_ESTIMATE", int, 8 * 1024 * 1024,
    "Default estimated output block size used for read planning before "
    "any block has materialized.")

# --- ray_tpu.serve control/data plane cadences ---

SERVE_RECONCILE_PERIOD_S = define(
    "SERVE_RECONCILE_PERIOD_S", float, 1.0,
    "Serve controller reconcile loop period (deployment_state.py "
    "analog).")

SERVE_HANDLE_REFRESH_S = define(
    "SERVE_HANDLE_REFRESH_S", float, 2.0,
    "How often a ServeHandle refreshes its replica set from the "
    "controller (long-poll refresh analog).")

SERVE_STREAM_BATCH = define(
    "SERVE_STREAM_BATCH", int, 16,
    "Streaming responses ship this many chunks per proxy round-trip.")

SERVE_STREAM_IDLE_TTL_S = define(
    "SERVE_STREAM_IDLE_TTL_S", float, 300.0,
    "Undrained response streams are reaped after this idle time.")

SERVE_DOWNSCALE_DELAY_S = define(
    "SERVE_DOWNSCALE_DELAY_S", float, 30.0,
    "Default delay before the Serve autoscaler honors a downscale "
    "decision (reference: downscale_delay_s).")

SERVE_STATS_TIMEOUT_S = define(
    "SERVE_STATS_TIMEOUT_S", float, 10.0,
    "Timeout for the controller's replica stats fan-out each "
    "autoscaling tick.")

SERVE_DRAIN_TIMEOUT_S = define(
    "SERVE_DRAIN_TIMEOUT_S", float, 30.0,
    "On scale-down, how long the controller waits for a victim "
    "replica's in-flight requests and response streams to drain "
    "before it is killed anyway.")

SERVE_DRAIN_POLL_S = define(
    "SERVE_DRAIN_POLL_S", float, 0.1,
    "Poll period for the scale-down drain loop's replica stats checks.")

# --- ray_tpu.serve fault tolerance (health plane, retries, breaker) ---

SERVE_HEALTH_FAILURE_THRESHOLD = define(
    "SERVE_HEALTH_FAILURE_THRESHOLD", int, 3,
    "Consecutive failed health pings before the controller declares a "
    "replica dead (an ActorDiedError is authoritative immediately). "
    "Reference: health_check_failure_threshold, deployment_state.py.")

SERVE_HEALTH_STARTUP_GRACE_S = define(
    "SERVE_HEALTH_STARTUP_GRACE_S", float, 60.0,
    "Startup probation: ping failures of a replica that has never yet "
    "passed a health check don't count as strikes for this long after "
    "creation (slow engine construction is not flapping). Real deaths "
    "still replace immediately.")

SERVE_BREAKER_THRESHOLD = define(
    "SERVE_BREAKER_THRESHOLD", int, 3,
    "Replica deaths within SERVE_BREAKER_WINDOW_S that trip a "
    "deployment's circuit breaker from closed to open.")

SERVE_BREAKER_WINDOW_S = define(
    "SERVE_BREAKER_WINDOW_S", float, 30.0,
    "Sliding window over replica deaths for the breaker trip decision.")

SERVE_BREAKER_COOLDOWN_S = define(
    "SERVE_BREAKER_COOLDOWN_S", float, 10.0,
    "How long an open breaker quarantines a deployment (no replica "
    "restarts) before moving to half-open and allowing one probe.")

SERVE_BREAKER_PROBE_S = define(
    "SERVE_BREAKER_PROBE_S", float, 5.0,
    "How long a half-open breaker's single probe replica must stay "
    "healthy before the breaker closes and normal restarts resume.")

SERVE_RETRY_MAX_ATTEMPTS = define(
    "SERVE_RETRY_MAX_ATTEMPTS", int, 3,
    "Default attempt budget for handle-level request retries through "
    "replica death (capped exponential backoff between attempts).")

SERVE_RETRY_BASE_S = define(
    "SERVE_RETRY_BASE_S", float, 0.05,
    "Base delay of the handle retry backoff; attempt n sleeps "
    "min(cap, base * 2**n) with jitter.")

SERVE_RETRY_CAP_S = define(
    "SERVE_RETRY_CAP_S", float, 2.0,
    "Cap on a single handle retry backoff sleep.")

SERVE_STREAM_FAILOVERS = define(
    "SERVE_STREAM_FAILOVERS", int, 2,
    "How many mid-stream failovers one streaming call may ride before "
    "the replica-death error propagates to the consumer.")

SERVE_HTTP_HOST = define(
    "SERVE_HTTP_HOST", str, "127.0.0.1",
    "Default bind host for the Serve HTTP proxy.")

SERVE_HTTP_PORT = define(
    "SERVE_HTTP_PORT", int, 8000,
    "Default port for the Serve HTTP proxy (reference: "
    "serve.start(http_options).")

# --- multi-tenant inference: priority classes + preemption ---

ENGINE_PRIORITY_CLASSES = define(
    "ENGINE_PRIORITY_CLASSES", int, 3,
    "Number of request priority classes the inference engine admits "
    "(0 = lowest .. N-1 = highest). submit(priority=) must be in "
    "range; the admission queue weights, sheds, and preempts by "
    "class.")

ENGINE_PRIORITY_AGING_S = define(
    "ENGINE_PRIORITY_AGING_S", float, 2.0,
    "Admission aging quantum: a pending request older than "
    "(priority_classes - its class) * this jumps the weighted-share "
    "order entirely (FIFO among the escalated), bounding how long a "
    "low class can wait behind sustained high-class load.")

ENGINE_PRIORITY_WEIGHT_BASE = define(
    "ENGINE_PRIORITY_WEIGHT_BASE", float, 4.0,
    "Weighted-share base for class admission: class c gets stride "
    "weight base**c, so each step up the class ladder gets base x the "
    "admission share of the class below while every backlogged class "
    "keeps a nonzero guaranteed share (no starvation even before "
    "aging kicks in).")

# --- runtime environments ---

RUNTIME_ENV_VENV_CREATE_TIMEOUT_S = define(
    "RUNTIME_ENV_VENV_CREATE_TIMEOUT_S", int, 120,
    "Timeout for creating a pip runtime-env virtualenv.")

RUNTIME_ENV_PIP_INSTALL_TIMEOUT_S = define(
    "RUNTIME_ENV_PIP_INSTALL_TIMEOUT_S", int, 600,
    "Timeout for installing a pip runtime-env's requirements "
    "(reference: pip runtime env install timeout).")
# --- control-plane throughput (channel framing + pipelined submission) ---

CHANNEL_BATCHING = define(
    "CHANNEL_BATCHING", bool, True,
    "Coalesce control-plane messages into one wire frame per channel "
    "flush (netaddr.BatchedConnection). Each logical message keeps its "
    "own identity for fault injection and FIFO order; turning this off "
    "restores one pickle per send (the parity smoke test runs both).")

CHANNEL_QUEUE_CAP = define(
    "CHANNEL_QUEUE_CAP", int, 65536,
    "Backpressure bound on a batched channel's outbound queue: past "
    "this many queued logical messages send() blocks until the flusher "
    "drains, matching the blocking a raw full pipe would impose.")

SUBMIT_PIPELINE = define(
    "SUBMIT_PIPELINE", bool, True,
    "Workers stream nested task submissions without a per-task ack, "
    "under a windowed credit scheme with sequence-numbered nack/replay "
    "(reference: Ray's pipelined task submission to the raylet). Off "
    "restores one blocking SubmitRequest/SubmitReply round trip each.")

SUBMIT_WINDOW = define(
    "SUBMIT_WINDOW", int, 1024,
    "Max unacknowledged pipelined submissions per worker channel before "
    "submit_spec blocks waiting for credit.")

SUBMIT_RESYNC_S = define(
    "SUBMIT_RESYNC_S", float, 1.0,
    "With unacked pipelined submissions and no credit progress for this "
    "long, the worker replays its unacked ring (the head dedupes by "
    "seq and re-credits, so a lost tail message cannot stall forever).")

SCHEDULER_FREED_BATCH = define(
    "SCHEDULER_FREED_BATCH", int, 16,
    "How many queued plain tasks the completion fast path may dispatch "
    "under ONE scheduler-lock acquisition when workers free up.")

LINK_GROUPS = define(
    "LINK_GROUPS", str, "",
    "Comma-separated interconnect link-group ids (ICI ring / DCN pod) "
    "this host hangs off, advertised in RegisterNode for the "
    "contention-aware gang placement model (2207.07817). Empty = no "
    "topology information; contention scoring is a no-op.")
