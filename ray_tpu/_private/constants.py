"""Session-wide constants for the ray_tpu core runtime.

Counterpart of the reference's `python/ray/_private/ray_constants.py` plus
the native config table (`src/ray/common/ray_config_def.h`): every tunable
is declared once in the typed option table (`_private/config.py` —
name, type, default, doc) and env-overridable with the ``RAY_TPU_``
prefix, mirroring the reference's ``RAY_<name>`` convention
(ray_config.h:74). `ray_tpu config list` (scripts/cli.py) prints the
table with effective values.
"""

import os

from ray_tpu._private.config import define

# Objects whose serialized envelope is at most this many bytes travel inline
# in control messages; larger ones go to the shared-memory store (the
# reference inlines <=100KB returns in the gRPC reply, core_worker.cc).
INLINE_OBJECT_MAX_BYTES = define(
    "INLINE_OBJECT_MAX_BYTES", int, 100 * 1024,
    "Objects at most this many serialized bytes ride inline in control "
    "messages instead of the shared-memory store.")

# Where shared-memory object files live (tmpfs). The reference mounts plasma
# over /dev/shm (plasma/store.h); we use one file per object under a session
# directory, which keeps ownership trivially correct (driver unlinks on exit).
SHM_ROOT = define(
    "SHM_ROOT", str, "/dev/shm" if os.path.isdir("/dev/shm") else "/tmp",
    "Root for session directories (object arena + sockets); tmpfs.")

SESSION_PREFIX = "ray_tpu_session_"

MAX_WORKERS_CAP = define(
    "MAX_WORKERS_CAP", int, 32,
    "Hard cap on generic (pool) worker processes per node.")

WORKER_REGISTER_TIMEOUT_S = define(
    "WORKER_REGISTER_TIMEOUT_S", float, 60.0,
    "Seconds to wait for a spawned worker/daemon to phone home before "
    "declaring startup failure (reference: "
    "worker_register_timeout_seconds).")

# Default resource requests (reference: task default num_cpus=1; actors hold
# 0 lifetime CPUs unless explicitly requested — ray_option_utils.py).
DEFAULT_TASK_NUM_CPUS = 1.0
DEFAULT_ACTOR_LIFETIME_CPUS = 0.0

# Buffer alignment inside serialized envelopes so zero-copy numpy views are
# 64-byte aligned (plasma aligns to 64 as well).
BUFFER_ALIGNMENT = 64

# Polling granularity for blocking waits.
WAIT_POLL_S = 0.01

MAX_INFLIGHT_SUBMISSIONS = define(
    "MAX_INFLIGHT_SUBMISSIONS", int, 100_000,
    "How many task submissions a single client may have in flight before "
    "submit blocks (reference has per-lease backlogs).")

# Env var handed to workers that were allocated TPU chips, mirroring how the
# reference sets CUDA_VISIBLE_DEVICES from the resource assignment
# (_private/utils.py:342-355).
TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"

MAX_OBJECT_RECONSTRUCTIONS = define(
    "MAX_OBJECT_RECONSTRUCTIONS", int, 3,
    "How many times a lost task-produced object may be rebuilt from "
    "lineage before readers get ObjectLostError (reference: task max "
    "retries gate reconstruction, object_recovery_manager.h:41).")

MAX_LINEAGE_ENTRIES = define(
    "MAX_LINEAGE_ENTRIES", int, 100_000,
    "Lineage table entry cap; oldest specs evict first and their objects "
    "stop being reconstructable.")

MAX_LINEAGE_BYTES = define(
    "MAX_LINEAGE_BYTES", int, 256 * 1024 * 1024,
    "Lineage table byte cap over retained specs (function blobs + inline "
    "args) — the reference's RAY_max_lineage_bytes.")

OBJECT_SPILL_ROOT = define(
    "OBJECT_SPILL_ROOT", str, "/tmp/ray_tpu_spill",
    "Real-disk root for arena-overflow and spilled objects (reference: "
    "external_storage.py FileSystemStorage); bounds shm usage by the "
    "arena capacity.")

SPILL_HIGH_WATER = define(
    "SPILL_HIGH_WATER", float, 0.80,
    "Arena-usage fraction above which the store owner spills sealed "
    "objects to disk (local_object_manager.h:110).")

SPILL_LOW_WATER = define(
    "SPILL_LOW_WATER", float, 0.50,
    "Spill passes drain arena usage down to this fraction.")

MEMORY_MONITOR_THRESHOLD = define(
    "MEMORY_MONITOR_THRESHOLD", float, 0.95,
    "Host/cgroup memory-usage fraction above which the newest retriable "
    "worker is killed (memory_monitor.h:52); 0 disables.")

MEMORY_MONITOR_INTERVAL_S = define(
    "MEMORY_MONITOR_INTERVAL_S", float, 1.0,
    "Memory monitor poll interval in seconds.")

OBJECT_STORE_BYTES = define(
    "OBJECT_STORE_BYTES", int, 512 * 1024 * 1024,
    "Shared-memory arena capacity per node (plasma store size analog).")

RUNTIME_ENV_CACHE = define(
    "RUNTIME_ENV_CACHE", str, "/tmp/ray_tpu_runtime_envs",
    "Content-addressed cache dir for materialized runtime environments "
    "(working_dir copies, pip venvs; reference: uri_cache.py).")

RUNTIME_ENV_CACHE_ENTRIES = define(
    "RUNTIME_ENV_CACHE_ENTRIES", int, 20,
    "LRU cap on cached runtime-env entries.")

# --- transport (reference: gRPC-over-TCP for every cross-host edge,
# src/ray/rpc/grpc_server.h; UDS only worker<->local raylet) ---

TRANSPORT = define(
    "TRANSPORT", str, "uds",
    "Cluster transport for daemon/client<->head and peer pulls: 'uds' "
    "(single machine) or 'tcp' (cluster spans machines). Workers always "
    "ride UDS to their local daemon, like the reference. Read at init() "
    "time via config.get, so tests can flip it per-session.")

HEAD_PORT = define(
    "HEAD_PORT", int, 0,
    "TCP port for the head listener when TRANSPORT=tcp (0 = ephemeral). "
    "Reference: --port on `ray start --head` (scripts.py:537).")

HEAD_BIND_HOST = define(
    "HEAD_BIND_HOST", str, "0.0.0.0",
    "Bind host for the head's TCP listener.")

NODE_IP = define(
    "NODE_IP", str, "",
    "Advertised IP of this machine for cross-host dials; empty = "
    "autodetect via the outbound interface (reference: "
    "node_ip_address detection, services.py:1353).")

DAEMON_RECONNECT_GRACE_S = define(
    "DAEMON_RECONNECT_GRACE_S", float, 60.0,
    "How long a HostDaemon keeps retrying the head channel after it "
    "closes (head crash/restart) before giving up and dying "
    "(reference: raylets ride out GCS restarts, "
    "gcs_rpc_server_reconnect_timeout_s). 0 disables reconnect.")

HEAD_SNAPSHOT_INTERVAL_S = define(
    "HEAD_SNAPSHOT_INTERVAL_S", float, 1.0,
    "Standalone-head metadata snapshot period (named actors, KV, jobs, "
    "placement groups -> session_dir/head_state.pkl; reference: "
    "Redis-backed GCS persistence, redis_store_client.h:33).")

AUTOSCALER_UPDATE_INTERVAL_S = define(
    "AUTOSCALER_UPDATE_INTERVAL_S", float, 1.0,
    "Head monitor tick: refresh LoadMetrics from cluster state and run "
    "StandardAutoscaler.update (reference: monitor.py:371 loop, "
    "AUTOSCALER_UPDATE_INTERVAL_S=5).")

PG_AUTOSCALE_WAIT_S = define(
    "PG_AUTOSCALE_WAIT_S", float, 60.0,
    "With an autoscaler attached, how long placement-group creation "
    "waits for capacity (the gang rides the demand queue) before "
    "raising PlacementGroupError (reference: PENDING placement groups "
    "feed autoscaler demand).")