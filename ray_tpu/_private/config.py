"""Typed, documented, env-overridable runtime option table.

Counterpart of the reference's RAY_CONFIG x-macro table
(`src/ray/common/ray_config_def.h`, 204 entries + `ray_config.h:74`
ReadEnv<T>("RAY_" + name)): every tunable is declared ONCE with its type,
default, and doc; the environment override is `RAY_TPU_<NAME>`. The
values in `constants.py` are all defined through this table, so the
whole system shares one registry and `ray_tpu config list` (scripts/cli)
can print it with current effective values.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


_PARSERS: dict[type, Callable[[str], Any]] = {
    int: int,
    float: float,
    str: str,
    bool: _parse_bool,
}


@dataclass(frozen=True)
class ConfigOption:
    name: str            # env override: RAY_TPU_<name>
    type: type
    default: Any
    doc: str

    @property
    def env_var(self) -> str:
        return "RAY_TPU_" + self.name

    def current(self) -> Any:
        raw = os.environ.get(self.env_var)
        if raw is None:
            return self.default
        try:
            return _PARSERS[self.type](raw)
        except (ValueError, KeyError):
            raise ValueError(
                f"invalid value {raw!r} for {self.env_var} "
                f"(expected {self.type.__name__})") from None


OPTIONS: dict[str, ConfigOption] = {}


def define(name: str, type_: type, default: Any, doc: str) -> Any:
    """Register an option and return its effective value (resolved once
    at import, like the reference's static RayConfig instance)."""
    if name in OPTIONS:
        raise ValueError(f"config option {name} defined twice")
    opt = ConfigOption(name, type_, default, doc)
    OPTIONS[name] = opt
    return opt.current()


def get(name: str) -> Any:
    """Re-resolve an option against the current environment (tests and
    subprocess-facing code paths that must see fresh overrides)."""
    return OPTIONS[name].current()


# Modules that memoize a derived value of a config option (e.g. the
# advertised host in netaddr) register an invalidation hook here;
# anything that changes an override mid-process (tests flipping
# RAY_TPU_NODE_IP, an operator re-pointing the node IP) calls
# reset_caches() to flush every derived value at once.
_reset_hooks: list[Callable[[], None]] = []


def on_reset(fn: Callable[[], None]) -> Callable[[], None]:
    """Register an invalidation hook run by reset_caches(); returns the
    hook so it can double as a decorator."""
    _reset_hooks.append(fn)
    return fn


def reset_caches() -> None:
    """Invalidate every registered config-derived cache."""
    for fn in _reset_hooks:
        fn()


def describe() -> list:
    """Rows for `ray_tpu config list`: (name, type, default, current,
    overridden, doc)."""
    rows = []
    for name in sorted(OPTIONS):
        opt = OPTIONS[name]
        cur = opt.current()
        rows.append({
            "name": name,
            "env": opt.env_var,
            "type": opt.type.__name__,
            "default": opt.default,
            "current": cur,
            "overridden": cur != opt.default,
            "doc": opt.doc,
        })
    return rows
