// Native shared-memory object store ("plasma-lite" arena).
//
// TPU-native counterpart of the reference's plasma store
// (src/ray/object_manager/plasma/store.h:55 PlasmaStore,
//  plasma/plasma_allocator.h + plasma/dlmalloc.cc for the allocator,
//  plasma/eviction_policy.h for LRU eviction). Instead of a store *server*
// process speaking a flatbuffer socket protocol (plasma/protocol.h), every
// client maps one arena file on tmpfs and mutates it directly under a
// process-shared robust mutex: on a single TPU host the store's clients are
// all local, so the socket hop the reference pays per create/get is pure
// overhead. The verbs (create/seal/get/delete/contains/evict) match
// plasma's client API (plasma/client.h) one-for-one.
//
// Layout of the arena file:
//   [ArenaHeader | index: NSLOTS * IndexSlot | data region]
// Data region is managed by a first-fit free list with boundary tags
// (header+footer per block) so frees coalesce in O(1) with both physical
// neighbours — the same discipline dlmalloc uses, minus the size bins.
//
// Concurrency: one pthread mutex (PTHREAD_PROCESS_SHARED + ROBUST) in the
// header guards index + allocator. Object *payload* writes happen outside
// the lock between create() and seal(): the slot is CREATED (invisible to
// lookup) until sealed, the same create→seal visibility contract as plasma.

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52545053544f5245ull;  // "RTPSTORE"
constexpr uint32_t kVersion = 2;
constexpr uint32_t kIdLen = 48;        // "obj_" + 32 hex + NUL fits
constexpr uint32_t kNumSlots = 1 << 16;
constexpr uint64_t kAlign = 64;        // block + payload alignment
constexpr uint32_t kMaxPinners = 8;    // per-object pin-attribution slots

// Block tags. size includes header+footer. Low bit = allocated.
// Block layout: [head tag (8B) | pad to kAlign | payload | foot tag (8B)];
// blocks start kAlign-aligned and payloads begin at block+kAlign, so
// zero-copy numpy views really are cacheline-aligned.
constexpr uint64_t kAllocBit = 1ull;
constexpr uint64_t kTagSize = 8;       // one u64 tag at each end
constexpr uint64_t kPayloadOff = kAlign;  // payload offset within a block

enum SlotState : uint32_t {
  kEmpty = 0,
  kCreated = 1,
  kSealed = 2,
  kTombstone = 3,
  // Deleted while readers still hold pins: invisible to lookup, block stays
  // allocated until the last rts_pin(-1) drops refcnt to zero (the plasma
  // "delete defers until Release" contract, plasma/object_lifecycle_manager.h).
  kCondemned = 4,
};

struct PinEntry {
  uint32_t pid;             // owning process
  uint32_t count;           // pins held by that process (0 = slot free)
};

struct IndexSlot {
  uint32_t state;
  uint32_t refcnt;          // total pin count; eviction skips pinned objects
  uint64_t offset;          // payload offset from arena base
  uint64_t size;            // payload size in bytes
  uint64_t tick;            // LRU clock value of last lookup/seal
  uint32_t creator_pid;     // reclaims unsealed blocks when creator dies
  uint32_t pad_;
  // Pins attributed per process so a dead client's pins can be force-
  // released (rts_release_all) — the counterpart of plasma dropping a
  // disconnected client's references. Overflow pins (more than kMaxPinners
  // concurrent pinning processes) stay unattributed in refcnt and are not
  // reclaimable, matching the old behavior.
  PinEntry pinners[kMaxPinners];
  char id[kIdLen];
};

struct ArenaHeader {
  uint64_t magic;
  uint32_t version;
  uint32_t num_slots;
  pthread_mutex_t mutex;
  uint64_t capacity;        // bytes in data region
  uint64_t data_off;        // arena-relative start of data region
  uint64_t index_off;
  uint64_t used;            // bytes allocated (incl. tags)
  uint64_t tick;            // LRU clock
  uint64_t num_objects;
  uint64_t num_evictions;
  uint64_t free_head;       // arena-relative offset of first free block, 0=none
  // Set when a client died holding the mutex mid-mutation: allocator
  // metadata can no longer be trusted, so allocation/free/evict are refused
  // for the rest of the session. Sealed payloads and the index remain
  // readable (index writes are single-slot and idempotent).
  uint32_t poisoned;
};

struct Handle {
  int fd;
  uint8_t* base;
  uint64_t map_len;
  ArenaHeader* hdr;
  uint32_t pid;             // pin attribution identity of this client
};

inline uint64_t align_up(uint64_t v, uint64_t a) { return (v + a - 1) & ~(a - 1); }

inline uint64_t* tag_at(Handle* h, uint64_t off) {
  return reinterpret_cast<uint64_t*>(h->base + off);
}
// free blocks keep a next-pointer right after the head tag
inline uint64_t* next_ptr(Handle* h, uint64_t off) {
  return reinterpret_cast<uint64_t*>(h->base + off + kTagSize);
}

inline uint64_t block_size(uint64_t tag) { return tag & ~kAllocBit; }
inline bool block_alloc(uint64_t tag) { return tag & kAllocBit; }

void set_tags(Handle* h, uint64_t off, uint64_t size, bool alloc) {
  uint64_t tag = size | (alloc ? kAllocBit : 0);
  *tag_at(h, off) = tag;
  *tag_at(h, off + size - kTagSize) = tag;
}

IndexSlot* slots(Handle* h) {
  return reinterpret_cast<IndexSlot*>(h->base + h->hdr->index_off);
}

uint64_t hash_id(const char* id) {
  // FNV-1a
  uint64_t x = 1469598103934665603ull;
  for (const char* p = id; *p; ++p) x = (x ^ (uint64_t)(uint8_t)*p) * 1099511628211ull;
  return x;
}

// Find slot for id. If `for_insert`, returns the first reusable slot when
// the id is absent. Returns nullptr if absent and table is full / not insert.
IndexSlot* find_slot(Handle* h, const char* id, bool for_insert) {
  ArenaHeader* hdr = h->hdr;
  IndexSlot* tab = slots(h);
  uint64_t mask = hdr->num_slots - 1;
  uint64_t i = hash_id(id) & mask;
  IndexSlot* insert = nullptr;
  for (uint32_t probe = 0; probe < hdr->num_slots; ++probe, i = (i + 1) & mask) {
    IndexSlot* s = &tab[i];
    if (s->state == kEmpty) {
      if (for_insert) return insert ? insert : s;
      return nullptr;
    }
    if (s->state == kTombstone) {
      if (!insert) insert = s;
      continue;
    }
    if (strncmp(s->id, id, kIdLen) == 0) return s;
  }
  return for_insert ? insert : nullptr;
}

// -- pin attribution ----------------------------------------------------------

void pin_add(IndexSlot* s, uint32_t pid, uint32_t n) {
  s->refcnt += n;
  PinEntry* empty = nullptr;
  for (uint32_t i = 0; i < kMaxPinners; ++i) {
    PinEntry* e = &s->pinners[i];
    if (e->count != 0 && e->pid == pid) { e->count += n; return; }
    if (e->count == 0 && !empty) empty = e;
  }
  if (empty) { empty->pid = pid; empty->count = n; }
}

void pin_sub(IndexSlot* s, uint32_t pid, uint32_t n) {
  for (uint32_t i = 0; i < kMaxPinners; ++i) {
    PinEntry* e = &s->pinners[i];
    if (e->count != 0 && e->pid == pid) {
      e->count -= (n < e->count) ? n : e->count;
      break;
    }
  }
  if (s->refcnt >= n) s->refcnt -= n; else s->refcnt = 0;
}

void lock(Handle* h) {
  int rc = pthread_mutex_lock(&h->hdr->mutex);
  if (rc == EOWNERDEAD) {
    // A client died holding the lock, possibly mid-way through a
    // free-list/tag mutation. Recover the mutex but poison the allocator:
    // existing sealed objects stay readable, new allocation moves to the
    // caller's fallback path (per-object files).
    h->hdr->poisoned = 1;
    pthread_mutex_consistent(&h->hdr->mutex);
  }
}
void unlock(Handle* h) { pthread_mutex_unlock(&h->hdr->mutex); }

// -- free-list allocator ------------------------------------------------------

void freelist_push(Handle* h, uint64_t off) {
  *next_ptr(h, off) = h->hdr->free_head;
  h->hdr->free_head = off;
}

void freelist_remove(Handle* h, uint64_t off) {
  uint64_t* cur = &h->hdr->free_head;
  while (*cur) {
    if (*cur == off) {
      *cur = *next_ptr(h, off);
      return;
    }
    cur = next_ptr(h, *cur);
  }
}

// Allocate a block whose payload is >= payload_size bytes. Returns payload
// offset (arena-relative) or 0 on failure.
uint64_t alloc_block(Handle* h, uint64_t payload_size) {
  ArenaHeader* hdr = h->hdr;
  uint64_t need = align_up(payload_size + kPayloadOff + kTagSize, kAlign);
  // min block must hold tags + next pointer when freed
  if (need < kAlign) need = kAlign;
  uint64_t* cur = &hdr->free_head;
  while (*cur) {
    uint64_t off = *cur;
    uint64_t bsz = block_size(*tag_at(h, off));
    if (bsz >= need) {
      *cur = *next_ptr(h, off);  // unlink
      uint64_t rem = bsz - need;
      if (rem >= kAlign) {  // split
        set_tags(h, off + need, rem, false);
        freelist_push(h, off + need);
        bsz = need;
      }
      set_tags(h, off, bsz, true);
      hdr->used += bsz;
      return off + kPayloadOff;
    }
    cur = next_ptr(h, off);
  }
  return 0;
}

void free_block(Handle* h, uint64_t payload_off) {
  ArenaHeader* hdr = h->hdr;
  uint64_t off = payload_off - kPayloadOff;
  uint64_t size = block_size(*tag_at(h, off));
  hdr->used -= size;
  uint64_t data_end = hdr->data_off + hdr->capacity;
  // coalesce forward
  uint64_t next = off + size;
  if (next < data_end && !block_alloc(*tag_at(h, next))) {
    freelist_remove(h, next);
    size += block_size(*tag_at(h, next));
  }
  // coalesce backward
  if (off > hdr->data_off) {
    uint64_t prev_tag = *tag_at(h, off - kTagSize);
    if (!block_alloc(prev_tag)) {
      uint64_t prev = off - block_size(prev_tag);
      freelist_remove(h, prev);
      size += off - prev;
      off = prev;
    }
  }
  set_tags(h, off, size, false);
  freelist_push(h, off);
}

// Free a condemned slot once its last pin is gone. Caller holds the lock.
void maybe_reap_locked(Handle* h, IndexSlot* s) {
  if (s->state == kCondemned && s->refcnt == 0 && !h->hdr->poisoned) {
    free_block(h, s->offset);
    s->state = kTombstone;
    h->hdr->num_objects--;
  }
}

// Evict sealed, unpinned objects in LRU order until at least `goal` bytes
// are freed. Single pass over the index: collect candidates, sort by LRU
// tick, free in order (counterpart of plasma's eviction_policy.h LRU list).
// Caller holds the lock. Returns bytes freed.
uint64_t evict_locked(Handle* h, uint64_t goal) {
  ArenaHeader* hdr = h->hdr;
  if (hdr->poisoned) return 0;
  IndexSlot* tab = slots(h);
  struct Cand { uint64_t tick; uint32_t idx; };
  Cand* cands = new Cand[hdr->num_objects ? hdr->num_objects : 1];
  uint32_t n = 0;
  for (uint32_t i = 0; i < hdr->num_slots; ++i) {
    IndexSlot* s = &tab[i];
    if (s->state == kSealed && s->refcnt == 0) cands[n++] = {s->tick, i};
  }
  // insertion sort by tick ascending (candidate counts are modest; avoids
  // pulling <algorithm> into the shared header ABI surface)
  for (uint32_t i = 1; i < n; ++i) {
    Cand key = cands[i];
    uint32_t j = i;
    for (; j > 0 && cands[j - 1].tick > key.tick; --j) cands[j] = cands[j - 1];
    cands[j] = key;
  }
  uint64_t freed = 0;
  for (uint32_t i = 0; i < n && freed < goal; ++i) {
    IndexSlot* s = &tab[cands[i].idx];
    uint64_t before = hdr->used;
    free_block(h, s->offset);
    freed += before - hdr->used;
    s->state = kTombstone;
    hdr->num_objects--;
    hdr->num_evictions++;
  }
  delete[] cands;
  return freed;
}

}  // namespace

extern "C" {

// Open (or create+initialize) the arena at `path` with `capacity` data bytes.
// Creation must be externally serialized (the Python side holds a file lock).
void* rts_open(const char* path, uint64_t capacity, int create) {
  uint64_t index_bytes = (uint64_t)kNumSlots * sizeof(IndexSlot);
  uint64_t data_off = align_up(sizeof(ArenaHeader) + index_bytes, 4096);
  int fd = open(path, create ? (O_RDWR | O_CREAT) : O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return nullptr; }
  bool init = (st.st_size == 0);
  uint64_t map_len = init ? data_off + capacity : (uint64_t)st.st_size;
  if (init && ftruncate(fd, (off_t)map_len) != 0) { close(fd); return nullptr; }
  void* base = mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) { close(fd); return nullptr; }
  Handle* h = new Handle{fd, static_cast<uint8_t*>(base), map_len,
                         reinterpret_cast<ArenaHeader*>(base),
                         (uint32_t)getpid()};
  if (init) {
    ArenaHeader* hdr = h->hdr;
    memset(hdr, 0, sizeof(*hdr));
    hdr->version = kVersion;
    hdr->num_slots = kNumSlots;
    hdr->capacity = map_len - data_off;
    hdr->data_off = data_off;
    hdr->index_off = sizeof(ArenaHeader);
    memset(h->base + hdr->index_off, 0, index_bytes);
    pthread_mutexattr_t attr;
    pthread_mutexattr_init(&attr);
    pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&hdr->mutex, &attr);
    pthread_mutexattr_destroy(&attr);
    set_tags(h, hdr->data_off, hdr->capacity, false);
    freelist_push(h, hdr->data_off);
    __sync_synchronize();
    hdr->magic = kMagic;  // published last: openers check magic
  } else if (h->hdr->magic != kMagic || h->hdr->version != kVersion) {
    munmap(base, map_len);
    close(fd);
    delete h;
    return nullptr;
  }
  return h;
}

void rts_close(void* vh) {
  Handle* h = static_cast<Handle*>(vh);
  if (!h) return;
  munmap(h->base, h->map_len);
  close(h->fd);
  delete h;
}

// Reserve space for an object. Returns payload offset, or 0 if out of space
// (after attempting eviction) / duplicate id / index full.
uint64_t rts_create(void* vh, const char* id, uint64_t size) {
  Handle* h = static_cast<Handle*>(vh);
  lock(h);
  if (h->hdr->poisoned) { unlock(h); return 0; }
  IndexSlot* s = find_slot(h, id, true);
  if (!s || (s->state != kEmpty && s->state != kTombstone)) {
    unlock(h);
    return 0;
  }
  uint64_t off = alloc_block(h, size);
  if (!off) {
    // mirror alloc_block's block-size formula or eviction frees too little
    uint64_t need = align_up(size + kPayloadOff + kTagSize, kAlign);
    if (evict_locked(h, need) >= need) off = alloc_block(h, size);
    if (!off) { unlock(h); return 0; }
  }
  s->state = kCreated;
  s->refcnt = 0;
  s->offset = off;
  s->size = size;
  s->tick = ++h->hdr->tick;
  s->creator_pid = h->pid;
  memset(s->pinners, 0, sizeof(s->pinners));
  strncpy(s->id, id, kIdLen - 1);
  s->id[kIdLen - 1] = '\0';
  h->hdr->num_objects++;
  unlock(h);
  return off;
}

int rts_seal(void* vh, const char* id) {
  Handle* h = static_cast<Handle*>(vh);
  lock(h);
  IndexSlot* s = find_slot(h, id, false);
  int rc = -1;
  if (s && s->state == kCreated) {
    s->state = kSealed;
    s->tick = ++h->hdr->tick;
    rc = 0;
  }
  unlock(h);
  return rc;
}

// Look up a sealed object. Returns payload offset (0 if absent) and fills
// *size. Touches the LRU clock.
uint64_t rts_lookup(void* vh, const char* id, uint64_t* size) {
  Handle* h = static_cast<Handle*>(vh);
  lock(h);
  IndexSlot* s = find_slot(h, id, false);
  uint64_t off = 0;
  if (s && s->state == kSealed) {
    off = s->offset;
    *size = s->size;
    s->tick = ++h->hdr->tick;
  }
  unlock(h);
  return off;
}

int rts_contains(void* vh, const char* id) {
  Handle* h = static_cast<Handle*>(vh);
  lock(h);
  IndexSlot* s = find_slot(h, id, false);
  int rc = (s && s->state == kSealed) ? 1 : 0;
  unlock(h);
  return rc;
}

// Delete an object. Pins are untouched: with no pins the block is freed
// immediately; with outstanding pins the slot is condemned — invisible to
// lookup, reclaimed when the last rts_pin(-1) lands (plasma's
// deferred-delete contract). Callers holding their own pin (the runtime's
// put-time owner pin) must release it before or after calling delete.
int rts_delete(void* vh, const char* id) {
  Handle* h = static_cast<Handle*>(vh);
  lock(h);
  IndexSlot* s = find_slot(h, id, false);
  int rc = -1;
  if (s && (s->state == kSealed || s->state == kCreated)) {
    if (s->refcnt == 0) {
      if (!h->hdr->poisoned) {
        free_block(h, s->offset);
        s->state = kTombstone;
      } else {
        s->state = kCondemned;  // space unreclaimable; keep it invisible
      }
      h->hdr->num_objects--;
    } else {
      // num_objects stays: decremented when the last pin frees the block
      s->state = kCondemned;
    }
    rc = 0;
  }
  unlock(h);
  return rc;
}

// Pin/unpin an object against eviction (plasma client Get/Release analog).
// Unpinning a condemned object to zero frees its block.
int rts_pin(void* vh, const char* id, int delta) {
  Handle* h = static_cast<Handle*>(vh);
  lock(h);
  IndexSlot* s = find_slot(h, id, false);
  int rc = -1;
  if (s && (s->state == kSealed || s->state == kCreated ||
            s->state == kCondemned)) {
    if (delta > 0) pin_add(s, h->pid, (uint32_t)delta);
    else pin_sub(s, h->pid, (uint32_t)(-delta));
    maybe_reap_locked(h, s);
    rc = (int)s->refcnt;
  }
  unlock(h);
  return rc;
}

// Atomic pin+lookup for readers: pins the object so delete/eviction cannot
// free the bytes under a live zero-copy view, then returns its offset.
uint64_t rts_acquire(void* vh, const char* id, uint64_t* size) {
  Handle* h = static_cast<Handle*>(vh);
  lock(h);
  IndexSlot* s = find_slot(h, id, false);
  uint64_t off = 0;
  if (s && s->state == kSealed) {
    pin_add(s, h->pid, 1);
    s->tick = ++h->hdr->tick;
    off = s->offset;
    *size = s->size;
  }
  unlock(h);
  return off;
}

// Force-release every pin a (dead) process holds and reclaim its unsealed
// creations. The counterpart of plasma releasing a disconnected client's
// references: without it, a crashed worker's put-time owner pins and
// reader pins condemn blocks forever. Returns the number of slots touched.
uint64_t rts_release_all(void* vh, uint32_t pid) {
  Handle* h = static_cast<Handle*>(vh);
  lock(h);
  IndexSlot* tab = slots(h);
  uint64_t touched = 0;
  for (uint32_t i = 0; i < h->hdr->num_slots; ++i) {
    IndexSlot* s = &tab[i];
    if (s->state != kSealed && s->state != kCreated &&
        s->state != kCondemned)
      continue;
    for (uint32_t j = 0; j < kMaxPinners; ++j) {
      PinEntry* e = &s->pinners[j];
      if (e->count != 0 && e->pid == pid) {
        uint32_t c = e->count;
        e->count = 0;
        s->refcnt = (s->refcnt >= c) ? s->refcnt - c : 0;
        maybe_reap_locked(h, s);
        touched++;
        break;
      }
    }
    if (s->state == kCreated && s->creator_pid == pid && s->refcnt == 0) {
      // crashed mid-put: the reservation would never be sealed or deleted
      if (!h->hdr->poisoned) {
        free_block(h, s->offset);
        s->state = kTombstone;
      } else {
        s->state = kCondemned;
      }
      h->hdr->num_objects--;
      touched++;
    }
  }
  unlock(h);
  return touched;
}

uint64_t rts_evict(void* vh, uint64_t nbytes) {
  Handle* h = static_cast<Handle*>(vh);
  lock(h);
  uint64_t freed = evict_locked(h, nbytes);
  unlock(h);
  return freed;
}

int rts_poisoned(void* vh) {
  Handle* h = static_cast<Handle*>(vh);
  return (int)h->hdr->poisoned;
}

// out[6] = {capacity, used, num_objects, num_evictions, data_off, map_len}
void rts_stats(void* vh, uint64_t* out) {
  Handle* h = static_cast<Handle*>(vh);
  lock(h);
  out[0] = h->hdr->capacity;
  out[1] = h->hdr->used;
  out[2] = h->hdr->num_objects;
  out[3] = h->hdr->num_evictions;
  out[4] = h->hdr->data_off;
  out[5] = h->map_len;
  unlock(h);
}

// Base pointer of this process's mapping (payload offsets are relative to it).
void* rts_base(void* vh) { return static_cast<Handle*>(vh)->base; }

}  // extern "C"
