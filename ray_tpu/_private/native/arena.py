"""ctypes client for the native shared-memory arena store (store.cc).

Counterpart of the reference's plasma client (`plasma/client.h`): create →
write payload → seal; lookup returns a zero-copy memoryview into this
process's mapping of the arena. One arena per session lives at
`<session_dir>/arena.shm`; creation is serialized across processes with an
flock'd sidecar file so exactly one process initializes the header.
"""

from __future__ import annotations

import ctypes
import fcntl
import mmap
import os

from ray_tpu._private import native as _native

def _default_capacity() -> int:
    # re-resolved per open (not the import-time constant): arena creation
    # happens after process start, and tests/operators set the override in
    # an already-running process
    from ray_tpu._private import config, constants  # noqa: F401
    v = config.get("OBJECT_STORE_BYTES")
    if v:
        return v
    # auto: 20% of system RAM, min 512 MiB — tmpfs-backed and sparse, so
    # the file costs only the pages actually written
    try:
        pages = os.sysconf("SC_PHYS_PAGES") * os.sysconf("SC_PAGE_SIZE")
        return max(512 << 20, int(pages * 0.20))
    except (ValueError, OSError):
        return 512 << 20


class _Lib:
    """Lazily-loaded libstore.so with typed signatures."""
    _instance = None

    def __init__(self, path: str):
        lib = ctypes.CDLL(path)
        lib.rts_open.restype = ctypes.c_void_p
        lib.rts_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int]
        lib.rts_close.argtypes = [ctypes.c_void_p]
        lib.rts_create.restype = ctypes.c_uint64
        lib.rts_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_uint64]
        lib.rts_seal.restype = ctypes.c_int
        lib.rts_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rts_lookup.restype = ctypes.c_uint64
        lib.rts_lookup.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.POINTER(ctypes.c_uint64)]
        lib.rts_contains.restype = ctypes.c_int
        lib.rts_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rts_delete.restype = ctypes.c_int
        lib.rts_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rts_pin.restype = ctypes.c_int
        lib.rts_pin.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
        lib.rts_acquire.restype = ctypes.c_uint64
        lib.rts_acquire.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.POINTER(ctypes.c_uint64)]
        lib.rts_poisoned.restype = ctypes.c_int
        lib.rts_poisoned.argtypes = [ctypes.c_void_p]
        lib.rts_evict.restype = ctypes.c_uint64
        lib.rts_evict.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.rts_release_all.restype = ctypes.c_uint64
        lib.rts_release_all.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        lib.rts_stats.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_uint64)]
        self.lib = lib

    @classmethod
    def get(cls):
        if cls._instance is None:
            so = _native.build_extension("store")
            if so is None:
                return None
            cls._instance = cls(so)
        return cls._instance


class Arena:
    """Per-process handle to the session arena. None-safe factory: use
    Arena.open(session_dir), which returns None when native is unavailable."""

    def __init__(self, lib: _Lib, handle: int, path: str):
        self._lib = lib.lib
        self._h = handle
        self._path = path
        stats = (ctypes.c_uint64 * 6)()
        self._lib.rts_stats(self._h, stats)
        self._map_len = stats[5]
        # Map the arena once in this process for zero-copy reads/writes.
        # ctypes gives us the .so's mapping base; re-deriving a Python
        # memoryview needs our own mmap of the same file.
        fd = os.open(path, os.O_RDWR)
        try:
            self._mm = mmap.mmap(fd, self._map_len)
        finally:
            os.close(fd)
        self._view = memoryview(self._mm)

    @classmethod
    def open(cls, session_dir: str,
             capacity: int | None = None) -> "Arena | None":
        if capacity is None:
            capacity = _default_capacity()
        lib = _Lib.get()
        if lib is None:
            return None
        path = os.path.join(session_dir, "arena.shm")
        lockpath = path + ".lock"
        lock_fd = os.open(lockpath, os.O_CREAT | os.O_RDWR, 0o600)
        try:
            fcntl.flock(lock_fd, fcntl.LOCK_EX)
            handle = lib.lib.rts_open(path.encode(), capacity, 1)
        finally:
            fcntl.flock(lock_fd, fcntl.LOCK_UN)
            os.close(lock_fd)
        if not handle:
            return None
        return cls(lib, handle, path)

    # -- plasma-style verbs --------------------------------------------------

    def create(self, object_id: str, size: int) -> memoryview | None:
        """Reserve `size` bytes; returns a writable view or None if full."""
        off = self._lib.rts_create(self._h, object_id.encode(), size)
        if off == 0:
            return None
        return self._view[off:off + size]

    def seal(self, object_id: str) -> bool:
        return self._lib.rts_seal(self._h, object_id.encode()) == 0

    def lookup(self, object_id: str) -> memoryview | None:
        """Zero-copy read view of a sealed object, or None if absent."""
        size = ctypes.c_uint64()
        off = self._lib.rts_lookup(self._h, object_id.encode(),
                                   ctypes.byref(size))
        if off == 0:
            return None
        return self._view[off:off + size.value].toreadonly()

    def acquire(self, object_id: str) -> memoryview | None:
        """Pin + zero-copy read view, atomically: the returned view stays
        valid even if the object is later deleted (block is condemned, not
        freed, until the pin is released)."""
        size = ctypes.c_uint64()
        off = self._lib.rts_acquire(self._h, object_id.encode(),
                                    ctypes.byref(size))
        if off == 0:
            return None
        return self._view[off:off + size.value].toreadonly()

    def acquire_mapped(self, object_id: str):
        """Pin + zero-copy view over a DEDICATED per-object mmap.

        Buffer exports from deserialized consumers (numpy arrays etc.)
        land on the underlying exporter object. With the shared arena
        map, that exporter is one mmap for every object, so nothing can
        tell whose bytes are still borrowed; with a per-object mmap,
        `mmap.close()` raising BufferError is a precise
        "still-borrowed" probe, which the store's free path uses to keep
        the pin (condemning the block) instead of letting the allocator
        reuse bytes underneath live zero-copy arrays.

        Returns (mmap, view) or (None, None).
        """
        size = ctypes.c_uint64()
        off = self._lib.rts_acquire(self._h, object_id.encode(),
                                    ctypes.byref(size))
        if off == 0:
            return None, None
        page = mmap.ALLOCATIONGRANULARITY
        base = (off // page) * page
        delta = off - base
        try:
            fd = os.open(self._path, os.O_RDONLY)
            try:
                m = mmap.mmap(fd, delta + size.value, offset=base,
                              access=mmap.ACCESS_READ)
            finally:
                os.close(fd)
        except Exception:
            # rts_acquire already pinned the block; failing to map must
            # not leak the pin (a leaked pin condemns the block forever).
            self._lib.rts_pin(self._h, object_id.encode(), -1)
            raise
        return m, memoryview(m)[delta:delta + size.value]

    def poisoned(self) -> bool:
        return self._lib.rts_poisoned(self._h) == 1

    def contains(self, object_id: str) -> bool:
        return self._lib.rts_contains(self._h, object_id.encode()) == 1

    def delete(self, object_id: str) -> bool:
        return self._lib.rts_delete(self._h, object_id.encode()) == 0

    def pin(self, object_id: str, delta: int = 1) -> int:
        return self._lib.rts_pin(self._h, object_id.encode(), delta)

    def evict(self, nbytes: int) -> int:
        return self._lib.rts_evict(self._h, nbytes)

    def release_all(self, pid: int) -> int:
        """Force-release every pin a (dead) process holds and reclaim its
        unsealed creations; returns slots touched. The plasma
        disconnected-client-release analog."""
        return self._lib.rts_release_all(self._h, pid)

    def stats(self) -> dict:
        out = (ctypes.c_uint64 * 6)()
        self._lib.rts_stats(self._h, out)
        return {"capacity": out[0], "used": out[1], "num_objects": out[2],
                "num_evictions": out[3]}

    def close(self) -> None:
        if self._h:
            try:
                self._view.release()
                self._mm.close()
            except BufferError:
                pass  # live object views reference the map; dies with process
            self._lib.rts_close(self._h)
            self._h = 0
