"""Native (C++) runtime components, built on demand with the host toolchain.

The reference ships its native core prebuilt via bazel
(`src/ray/BUILD.bazel`); here the native pieces are small enough to compile
at first import with `g++ -O2 -shared -fPIC` and cache next to the source.
Set RAY_TPU_DISABLE_NATIVE=1 to force the pure-Python fallbacks.
"""

from __future__ import annotations

import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_LOCK = threading.Lock()


def native_disabled() -> bool:
    return os.environ.get("RAY_TPU_DISABLE_NATIVE", "") == "1"


def build_extension(name: str) -> str | None:
    """Compile native/<name>.cc -> native/lib<name>.so if stale; return the
    .so path, or None if native is disabled or the toolchain fails.

    RAY_TPU_SANITIZE=thread|address builds a separate sanitizer-
    instrumented library (lib<name>.tsan.so / .asan.so) — the stress
    harness runs against it the way the reference's plasma tests run
    under bazel's TSAN/ASAN configs (ci/)."""
    if native_disabled():
        return None
    sanitize = os.environ.get("RAY_TPU_SANITIZE", "")
    src = os.path.join(_DIR, name + ".cc")
    suffix = {"thread": ".tsan", "address": ".asan"}.get(sanitize, "")
    out = os.path.join(_DIR, "lib" + name + suffix + ".so")
    flags = ["-O2"]
    if sanitize in ("thread", "address"):
        flags = ["-O1", "-g", f"-fsanitize={sanitize}",
                 "-fno-omit-frame-pointer"]
    with _BUILD_LOCK:
        try:
            if (os.path.exists(out)
                    and os.path.getmtime(out) >= os.path.getmtime(src)):
                return out
            tmp = out + ".tmp.%d" % os.getpid()
            subprocess.run(
                ["g++", *flags, "-std=c++17", "-shared", "-fPIC",
                 "-o", tmp, src, "-lpthread"],
                check=True, capture_output=True, timeout=120)
            os.replace(tmp, out)  # atomic: concurrent builders race safely
            return out
        except (OSError, subprocess.SubprocessError):
            return None
