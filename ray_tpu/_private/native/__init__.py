"""Native (C++) runtime components, built on demand with the host toolchain.

The reference ships its native core prebuilt via bazel
(`src/ray/BUILD.bazel`); here the native pieces are small enough to compile
at first import with `g++ -O2 -shared -fPIC` and cache next to the source.
Set RAY_TPU_DISABLE_NATIVE=1 to force the pure-Python fallbacks.
"""

from __future__ import annotations

import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_LOCK = threading.Lock()


def native_disabled() -> bool:
    return os.environ.get("RAY_TPU_DISABLE_NATIVE", "") == "1"


def build_extension(name: str) -> str | None:
    """Compile native/<name>.cc -> native/lib<name>.so if stale; return the
    .so path, or None if native is disabled or the toolchain fails."""
    if native_disabled():
        return None
    src = os.path.join(_DIR, name + ".cc")
    out = os.path.join(_DIR, "lib" + name + ".so")
    with _BUILD_LOCK:
        try:
            if (os.path.exists(out)
                    and os.path.getmtime(out) >= os.path.getmtime(src)):
                return out
            tmp = out + ".tmp.%d" % os.getpid()
            subprocess.run(
                ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                 "-o", tmp, src, "-lpthread"],
                check=True, capture_output=True, timeout=120)
            os.replace(tmp, out)  # atomic: concurrent builders race safely
            return out
        except (OSError, subprocess.SubprocessError):
            return None
