"""Chunked object-pull data plane shared by the head and HostDaemons.

Counterpart of the reference's object-manager transfer internals
(`object_manager.h:130,139` HandlePush/HandlePull + `object_buffer_pool.h`
chunking): one side asks for an object's serialized bytes with a
PullRequest, the other streams PullChunks back on the same channel. Both
the head (node.py) and the daemons (daemon.py) embed a `PullClient` for
their outgoing pulls and call `serve_pull` for incoming ones, so the
protocol lives in exactly one place.
"""

from __future__ import annotations

import itertools
import threading
import time

from ray_tpu._private import protocol
from ray_tpu._private.constants import PULL_CHUNK_BYTES, PULL_TIMEOUT_S
from ray_tpu.exceptions import ObjectLostError


class _PullBuf:
    """Reassembly buffer for one in-flight chunked pull: preallocated
    when the first chunk announces the total, else an append list."""
    __slots__ = ("parts", "data", "offset", "done", "error")

    def __init__(self):
        self.parts = []
        self.data = None       # bytearray once total is known
        self.offset = 0
        self.done = False
        self.error = None

    def feed(self, msg) -> None:
        if self.data is None and msg.total >= 0 and not self.parts:
            self.data = bytearray(msg.total)
        if self.data is not None:
            n = len(msg.data)
            self.data[self.offset:self.offset + n] = msg.data
            self.offset += n
        else:
            self.parts.append(msg.data)

    def payload(self):
        if self.data is not None:
            return self.data
        return b"".join(self.parts)


class PullClient:
    """Issues PullRequests and reassembles PullChunk streams. The owner
    routes every incoming PullChunk to `on_chunk` (from whichever channel
    reader received it — req ids are process-global, so replies can't
    collide across channels)."""

    def __init__(self):
        self._req = itertools.count(1)
        self._bufs: dict[int, _PullBuf] = {}
        self._cv = threading.Condition()

    def on_chunk(self, msg: protocol.PullChunk) -> None:
        with self._cv:
            buf = self._bufs.get(msg.req_id)
            if buf is None:
                return
            if msg.error is not None:
                buf.error = msg.error
                buf.done = True
            else:
                buf.feed(msg)
                if msg.last:
                    buf.done = True
            if buf.done:
                self._cv.notify_all()

    def abort_all(self) -> None:
        """Wake every waiter (e.g. a source node died) so their
        abort_check can run immediately."""
        with self._cv:
            self._cv.notify_all()

    def pull(self, send, oid: str, abort_check=None,
             timeout: float | None = None) -> bytes:
        """Send a PullRequest via `send` and block for the reassembled
        payload. `abort_check()` (optional) is polled while waiting;
        returning a truthy string aborts with that cause."""
        if timeout is None:
            timeout = PULL_TIMEOUT_S
        req = next(self._req)
        buf = _PullBuf()
        with self._cv:
            self._bufs[req] = buf
        send(protocol.PullRequest(req, oid))
        deadline = time.monotonic() + timeout
        with self._cv:
            while not buf.done:
                cause = abort_check() if abort_check is not None else None
                rem = deadline - time.monotonic()
                if rem <= 0 or cause:
                    self._bufs.pop(req, None)
                    raise ObjectLostError(
                        f"pull of {oid} {cause or 'timed out'}")
                self._cv.wait(min(rem, 0.5))
            self._bufs.pop(req, None)
        if buf.error is not None:
            raise ObjectLostError(f"pull of {oid} failed: {buf.error}")
        return buf.payload()


def serve_pull(send, msg: protocol.PullRequest, payload) -> None:
    """Stream `payload` back as PullChunks on `send`. `payload` may be a
    memoryview over the store's own mapping (ObjectStore.raw_view), so a
    multi-GiB object is never materialized as one extra copy on the
    serve side; an exception/None streams a failure chunk."""
    if payload is None or isinstance(payload, BaseException):
        send(protocol.PullChunk(
            msg.req_id, 0, b"", last=True,
            error=str(payload) if payload is not None
            else "object not on this node"))
        return
    n = len(payload)
    seq = 0
    for off in range(0, max(n, 1), PULL_CHUNK_BYTES):
        chunk = bytes(payload[off:off + PULL_CHUNK_BYTES])
        send(protocol.PullChunk(msg.req_id, seq, chunk,
                                last=off + PULL_CHUNK_BYTES >= n,
                                total=n if seq == 0 else -1))
        seq += 1
