"""Chunked object-pull data plane shared by the head and HostDaemons.

Counterpart of the reference's object-manager transfer internals
(`object_manager.h:130,139` HandlePush/HandlePull + `object_buffer_pool.h`
chunking): one side asks for an object's serialized bytes with a
PullRequest, the other streams PullChunks back on the same channel. Both
the head (node.py) and the daemons (daemon.py) embed a `PullClient` for
their outgoing pulls and call `serve_pull` for incoming ones, so the
protocol lives in exactly one place.
"""

from __future__ import annotations

import itertools
import threading
import time

from ray_tpu._private import protocol
from ray_tpu.exceptions import ObjectLostError

PULL_CHUNK_BYTES = 1 << 20
PULL_TIMEOUT_S = 120.0


class _PullBuf:
    """Reassembly buffer for one in-flight chunked pull."""
    __slots__ = ("parts", "done", "error")

    def __init__(self):
        self.parts = []
        self.done = False
        self.error = None


class PullClient:
    """Issues PullRequests and reassembles PullChunk streams. The owner
    routes every incoming PullChunk to `on_chunk` (from whichever channel
    reader received it — req ids are process-global, so replies can't
    collide across channels)."""

    def __init__(self):
        self._req = itertools.count(1)
        self._bufs: dict[int, _PullBuf] = {}
        self._cv = threading.Condition()

    def on_chunk(self, msg: protocol.PullChunk) -> None:
        with self._cv:
            buf = self._bufs.get(msg.req_id)
            if buf is None:
                return
            if msg.error is not None:
                buf.error = msg.error
                buf.done = True
            else:
                buf.parts.append(msg.data)
                if msg.last:
                    buf.done = True
            if buf.done:
                self._cv.notify_all()

    def abort_all(self) -> None:
        """Wake every waiter (e.g. a source node died) so their
        abort_check can run immediately."""
        with self._cv:
            self._cv.notify_all()

    def pull(self, send, oid: str, abort_check=None,
             timeout: float = PULL_TIMEOUT_S) -> bytes:
        """Send a PullRequest via `send` and block for the reassembled
        payload. `abort_check()` (optional) is polled while waiting;
        returning a truthy string aborts with that cause."""
        req = next(self._req)
        buf = _PullBuf()
        with self._cv:
            self._bufs[req] = buf
        send(protocol.PullRequest(req, oid))
        deadline = time.monotonic() + timeout
        with self._cv:
            while not buf.done:
                cause = abort_check() if abort_check is not None else None
                rem = deadline - time.monotonic()
                if rem <= 0 or cause:
                    self._bufs.pop(req, None)
                    raise ObjectLostError(
                        f"pull of {oid} {cause or 'timed out'}")
                self._cv.wait(min(rem, 0.5))
            self._bufs.pop(req, None)
        if buf.error is not None:
            raise ObjectLostError(f"pull of {oid} failed: {buf.error}")
        return b"".join(buf.parts)


def serve_pull(send, msg: protocol.PullRequest, payload) -> None:
    """Stream `payload` (bytes, or an exception/None for failure) back as
    PullChunks on `send`."""
    if payload is None or isinstance(payload, BaseException):
        send(protocol.PullChunk(
            msg.req_id, 0, b"", last=True,
            error=str(payload) if payload is not None
            else "object not on this node"))
        return
    n = len(payload)
    seq = 0
    for off in range(0, max(n, 1), PULL_CHUNK_BYTES):
        chunk = bytes(payload[off:off + PULL_CHUNK_BYTES])
        send(protocol.PullChunk(msg.req_id, seq, chunk,
                                last=off + PULL_CHUNK_BYTES >= n))
        seq += 1
