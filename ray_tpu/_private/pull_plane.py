"""Chunked object-pull data plane shared by the head and HostDaemons.

Counterpart of the reference's object-manager transfer internals
(`object_manager.h:130,139` HandlePush/HandlePull + `object_buffer_pool.h`
chunking): one side asks for an object's serialized bytes with a
PullRequest, the other streams PullChunks back on the same channel. Both
the head (node.py) and the daemons (daemon.py) embed a `PullClient` for
their outgoing pulls and call `serve_pull` for incoming ones, so the
protocol lives in exactly one place.

Copy discipline (the difference between 0.08 and >1 GB/s on one core):
the serve side writes a small pickled header then the chunk body as a
raw `send_bytes` frame straight out of the store's own mapping (no
bytes() slice, no pickle of the payload); the receive side lands the
frame with `recv_bytes_into` directly in the pull's destination buffer
— ideally an arena allocation (`alloc`), so the socket write on one
side and one kernel copy into shared memory on the other are the ONLY
per-byte costs, and the object is seal-ready on arrival.
"""

from __future__ import annotations

import itertools
import threading
import time

from ray_tpu._private import protocol
from ray_tpu._private.constants import PULL_CHUNK_BYTES, PULL_TIMEOUT_S
from ray_tpu.exceptions import ObjectLostError


class _PullBuf:
    """Reassembly state for one in-flight chunked pull."""
    __slots__ = ("view", "data", "alloc", "into_alloc", "done", "error",
                 "cleanup", "aborted", "tombstone_ts")

    def __init__(self, alloc=None, cleanup=None):
        self.alloc = alloc      # optional: total -> writable memoryview
        self.cleanup = cleanup  # optional: release an aborted allocation
        self.view = None        # destination (memoryview over data/arena)
        self.data = None        # bytearray fallback when alloc declines
        self.into_alloc = False
        self.done = False
        self.error = None
        self.aborted = False
        self.tombstone_ts = 0.0

    def ensure(self, total: int) -> None:
        if self.view is not None or total < 0:
            return
        if self.alloc is not None:
            v = self.alloc(total)
            if v is not None:
                self.view = memoryview(v).cast("B")
                self.into_alloc = True
                return
        self.data = bytearray(total)
        self.view = memoryview(self.data)

    def release(self) -> None:
        if self.cleanup is not None and self.into_alloc:
            try:
                self.view.release()
            except BufferError:
                pass
            try:
                self.cleanup()
            except Exception:
                pass
            self.cleanup = None

    def payload(self):
        if self.into_alloc:
            return self.view
        return self.data if self.data is not None else b""


class PullClient:
    """Issues PullRequests and reassembles PullChunk streams. The owner
    routes every incoming PullChunk to `on_chunk` / `on_chunk_raw` (from
    whichever channel reader received it — req ids are process-global, so
    replies can't collide across channels)."""

    def __init__(self):
        self._req = itertools.count(1)
        self._bufs: dict[int, _PullBuf] = {}
        self._cv = threading.Condition()

    def on_chunk(self, msg: protocol.PullChunk) -> None:
        """Inline (error / empty / legacy) chunks."""
        with self._cv:
            buf = self._bufs.get(msg.req_id)
            if buf is None:
                return
            if msg.error is not None:
                buf.error = msg.error
                buf.done = True
            else:
                if msg.data:
                    buf.ensure(msg.total if msg.total >= 0
                               else len(msg.data))
                    n = len(msg.data)
                    buf.view[msg.offset:msg.offset + n] = msg.data
                if msg.last:
                    buf.done = True
            if buf.done:
                self._cv.notify_all()

    def on_chunk_raw(self, msg: protocol.PullChunk, conn) -> None:
        """Header announcing a raw body frame: land it with
        recv_bytes_into. MUST be called synchronously from the channel's
        reader (the body is the very next frame). The body frame is
        consumed on EVERY path — leaving it queued would desync the
        channel's framing and tear down a healthy connection."""
        try:
            with self._cv:
                buf = self._bufs.get(msg.req_id)
                if buf is not None:
                    buf.ensure(msg.total)
        except BaseException as e:
            # allocation failed (e.g. MemoryError on a huge bytearray):
            # fail THIS pull, keep the channel aligned
            conn.recv_bytes()
            with self._cv:
                if buf is not None:
                    buf.error = repr(e)
                    buf.done = True
                    self._cv.notify_all()
            return
        if buf is None or buf.view is None:
            conn.recv_bytes()        # unclaimed — drain
            return
        # An ABORTED (timed-out) pull still owns its allocation until the
        # stream ends: landing into it is safe, freeing it early would
        # let a recycled arena block be overwritten by this very frame.
        conn.recv_bytes_into(
            buf.view[msg.offset:msg.offset + msg.nbytes])
        if msg.last:
            with self._cv:
                if buf.aborted:
                    buf.release()    # reader-side ownership handoff
                    self._bufs.pop(msg.req_id, None)
                else:
                    buf.done = True
                    self._cv.notify_all()

    def abort_all(self) -> None:
        """Wake every waiter (e.g. a source node died) so their
        abort_check can run immediately. Also sweeps expired tombstones:
        a node that stops pulling would otherwise never reclaim condemned
        arena blocks (the sweep normally runs at the start of the next
        pull)."""
        with self._cv:
            self._sweep_tombstones_locked()
            self._cv.notify_all()

    def sweep(self) -> None:
        """Reclaim expired tombstoned allocations; safe to call from a
        periodic maintenance loop (daemon spill pass)."""
        with self._cv:
            self._sweep_tombstones_locked()

    def pull(self, send, oid: str, abort_check=None,
             timeout: float | None = None, alloc=None, cleanup=None):
        """Send a PullRequest via `send` and block for the reassembled
        payload. `abort_check()` (optional) is polled while waiting;
        returning a truthy string aborts with that cause. `alloc(total)`
        (optional) provides the destination buffer — e.g. an arena
        allocation — and the same buffer (memoryview) is returned;
        `cleanup()` releases that allocation and is owned by THIS client
        once the pull starts: on abort the buffer stays alive until the
        in-flight stream ends (a reader mid-recv_bytes_into must never
        write into a recycled block)."""
        return self._pull(send, oid, abort_check, timeout, alloc,
                          cleanup)[0]

    def pull_into(self, send, oid: str, abort_check=None,
                  timeout: float | None = None, alloc=None, cleanup=None):
        """Like pull() but returns (payload, landed_in_alloc)."""
        return self._pull(send, oid, abort_check, timeout, alloc, cleanup)

    def _sweep_tombstones_locked(self):
        now = time.monotonic()
        for req, b in list(self._bufs.items()):
            if b.aborted and now - b.tombstone_ts > 2 * PULL_TIMEOUT_S:
                # the stream never finished (source channel died with
                # frames outstanding): reclaim the allocation now
                b.release()
                self._bufs.pop(req, None)

    def _pull(self, send, oid, abort_check, timeout, alloc, cleanup):
        if timeout is None:
            timeout = PULL_TIMEOUT_S
        req = next(self._req)
        buf = _PullBuf(alloc, cleanup)
        with self._cv:
            self._sweep_tombstones_locked()
            self._bufs[req] = buf
        send(protocol.PullRequest(req, oid))
        deadline = time.monotonic() + timeout
        with self._cv:
            while not buf.done:
                cause = abort_check() if abort_check is not None else None
                rem = deadline - time.monotonic()
                if rem <= 0 or cause:
                    if buf.view is not None and buf.into_alloc:
                        # stream may still be landing into the buffer:
                        # hand ownership to the reader (released at the
                        # last frame, or by the tombstone sweep)
                        buf.aborted = True
                        buf.tombstone_ts = time.monotonic()
                    else:
                        self._bufs.pop(req, None)
                    raise ObjectLostError(
                        f"pull of {oid} {cause or 'timed out'}")
                self._cv.wait(min(rem, 0.5))
            self._bufs.pop(req, None)
        if buf.error is not None:
            buf.release()
            raise ObjectLostError(f"pull of {oid} failed: {buf.error}")
        return buf.payload(), buf.into_alloc


def serve_pull(raw, msg: protocol.PullRequest, payload) -> None:
    """Stream `payload` back as raw-framed PullChunks. `raw` is
    (conn, send_lock) — header + body are written under ONE lock hold so
    interleaved senders on a shared channel can't split the pair.
    `payload` may be a memoryview over the store's own mapping
    (ObjectStore.raw_view): the bytes go socket-ward with zero
    serve-side copies. An exception/None streams a failure chunk."""
    conn, lock = raw
    if payload is None or isinstance(payload, BaseException):
        err = (str(payload) if payload is not None
               else "object not on this node")
        with lock:
            try:
                conn.send(protocol.PullChunk(msg.req_id, 0, b"",
                                             last=True, error=err))
            except (OSError, ValueError, BrokenPipeError):
                pass
        return
    view = memoryview(payload).cast("B")
    n = len(view)
    if n == 0:
        with lock:
            try:
                conn.send(protocol.PullChunk(msg.req_id, 0, b"",
                                             last=True, total=0))
            except (OSError, ValueError, BrokenPipeError):
                pass
        return
    seq = 0
    for off in range(0, n, PULL_CHUNK_BYTES):
        end = min(off + PULL_CHUNK_BYTES, n)
        hdr = protocol.PullChunk(
            msg.req_id, seq, None, last=end >= n,
            total=n if seq == 0 else -1, nbytes=end - off, offset=off)
        with lock:
            try:
                conn.send(hdr)
                conn.send_bytes(view[off:end])
            except (OSError, ValueError, BrokenPipeError):
                return          # channel died; puller times out/retries
        seq += 1
