"""Worker-process site hook.

This directory is prepended to every worker's PYTHONPATH so this module
shadows any platform sitecustomize (e.g. the TPU image's PJRT registration
hook, which force-sets jax_platforms and would make CPU-only pool workers
grab — or hang on — the TPU runtime).

- CPU workers (RAY_TPU_WORKER_FORCE_CPU=1): skip platform registration
  entirely; JAX honors JAX_PLATFORMS=cpu.
- TPU workers: chain-exec the next sitecustomize.py found on sys.path so
  the accelerator plugin registers exactly as it would in the driver.

This is the counterpart of the reference hiding GPUs from non-GPU workers
via CUDA_VISIBLE_DEVICES="" (_private/utils.py:342-355) — but on TPU the
runtime is process-exclusive, so exclusion must happen before any jax
import, hence a site hook rather than an env var alone.
"""

import os
import sys

_here = os.path.dirname(os.path.abspath(__file__))

if os.environ.get("RAY_TPU_WORKER_FORCE_CPU") == "1":
    os.environ["JAX_PLATFORMS"] = "cpu"
    # Belt and braces: neutralize common accelerator-registration triggers
    # for any grandchild processes too.
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
else:
    for _p in list(sys.path):
        if not _p:
            continue
        try:
            if os.path.abspath(_p) == _here:
                continue
        except OSError:
            continue
        _cand = os.path.join(_p, "sitecustomize.py")
        if os.path.exists(_cand):
            with open(_cand) as _f:
                _code = _f.read()
            exec(compile(_code, _cand, "exec"),
                 {"__name__": "sitecustomize", "__file__": _cand})
            break
