"""ray_tpu — a TPU-native distributed compute & ML framework.

Public core API, counterpart of the reference's `ray` package surface
(`python/ray/_private/worker.py`: init :1106, get :2408, put :2517,
wait :2580, remote :3022, get_actor :2711, kill :2746, cancel :2777).

Import stays light: JAX and the ML libraries (`ray_tpu.train`, `.tune`,
`.data`, `.parallel`, `.models`) load lazily so spawning a worker process
costs milliseconds, not a JAX import.
"""

from __future__ import annotations

import glob
import os

from ray_tpu._private import constants, ids
from ray_tpu._private import worker as _worker
from ray_tpu._private.worker import ObjectRef, get, put, wait
from ray_tpu.actor import ActorClass, ActorHandle, get_actor, kill, method
from ray_tpu.remote_function import RemoteFunction
from ray_tpu.runtime_context import get_runtime_context
from ray_tpu import exceptions

__version__ = "0.1.0"

__all__ = [
    "init", "shutdown", "is_initialized", "remote", "get", "put", "wait",
    "get_actor", "kill", "cancel", "free", "method", "ObjectRef",
    "ActorHandle",
    "available_resources", "cluster_resources", "get_runtime_context",
    "exceptions", "__version__",
]


def _detect_tpu_chips() -> int:
    """Count local TPU chips without importing JAX (the reference detects
    GPUs via NVML-free heuristics similarly, _private/resource_spec.py)."""
    env = os.environ.get("RAY_TPU_NUM_TPUS")
    if env is not None:
        return int(env)
    chips = glob.glob("/dev/accel*") or glob.glob("/dev/vfio/*")
    chips = [c for c in chips if not c.endswith("vfio")]
    return len(chips)


def init(num_cpus: int | None = None,
         num_tpus: int | None = None,
         resources: dict | None = None,
         *,
         address: str | None = None,
         ignore_reinit_error: bool = False,
         namespace: str | None = None,
         logging_level: str = "INFO",
         dashboard_port: int | None = None,
         log_to_driver: bool | None = None,
         **kwargs):
    """Start a session (driver mode), or — with `address` — connect this
    process as a SECOND driver to an existing session (the reference's Ray
    Client, `util/client/worker.py:81`: `ray.init("ray://...")`).

    `address` accepts "auto" (newest live session on this host), a session
    directory, or its node.sock path. Client drivers get the full
    get/put/remote/actor API over the worker protocol; shutdown() just
    disconnects them — the session stays up.
    """
    if address is not None:
        dropped = [name for name, v in (
            ("num_cpus", num_cpus), ("num_tpus", num_tpus),
            ("resources", resources), ("namespace", namespace),
            ("dashboard_port", dashboard_port)) if v is not None]
        if dropped or kwargs:
            raise ValueError(
                f"init(address=...) joins an EXISTING session; "
                f"{dropped + sorted(kwargs)} cannot be configured from a "
                "client driver")
        return _connect_client(address, ignore_reinit_error, log_to_driver)
    if _worker.is_initialized():
        if ignore_reinit_error:
            return _worker.get_client()
        raise RuntimeError("ray_tpu.init() called twice "
                           "(pass ignore_reinit_error=True to allow)")
    if num_cpus is None:
        num_cpus = os.cpu_count() or 1
    if num_tpus is None:
        num_tpus = _detect_tpu_chips()
    total = {"CPU": float(num_cpus)}
    if num_tpus:
        total["TPU"] = float(num_tpus)
    for k, v in (resources or {}).items():
        total[k] = float(v)

    from ray_tpu._private.node import NodeServer
    if constants.GC_STALE_SESSIONS:
        _gc_stale_sessions()
    session_dir = os.path.join(
        constants.SHM_ROOT,
        constants.SESSION_PREFIX + ids.new_node_id())
    os.makedirs(session_dir, exist_ok=True)
    node = NodeServer(total, session_dir, num_tpu_chips=int(num_tpus or 0))
    client = _worker.connect_driver_mode(node)
    if log_to_driver is None:
        # jobs stream their cluster's logs by default (the job log file
        # then carries worker output); interactive drivers opt in
        log_to_driver = os.environ.get("RAY_TPU_LOG_TO_DRIVER") == "1"
    if log_to_driver:
        client.control("log_subscribe")
    if dashboard_port is not None:
        from ray_tpu.dashboard import start_dashboard
        try:
            start_dashboard(dashboard_port)
        except BaseException:
            # don't leak a live, un-reinitializable session behind a
            # failed init (e.g. dashboard port already in use)
            shutdown()
            raise
    return client


def _connect_client(address: str, ignore_reinit_error: bool = False,
                    log_to_driver: bool | None = None):
    """Join an existing session as a remote driver: register on the head's
    socket with an attach-class worker id (never dispatched to) and run
    the full worker protocol — get/put/submit/actors all work."""
    import threading
    import uuid

    if _worker.is_initialized():
        if ignore_reinit_error:
            return _worker.get_client()
        raise RuntimeError("ray_tpu.init() called twice "
                           "(pass ignore_reinit_error=True to allow)")
    from ray_tpu._private import netaddr
    if netaddr.is_tcp(address):
        # cross-machine driver: dial the head's TCP listener; the secret
        # comes from RAY_TPU_AUTHKEY (hex), like the reference's
        # redis-password handoff for remote `ray.init(address=...)`
        key = os.environ.get("RAY_TPU_AUTHKEY")
        if not key:
            raise ConnectionError(
                "joining a remote head over TCP requires RAY_TPU_AUTHKEY "
                "(hex of the session authkey file)")
        sock, authkey = address, bytes.fromhex(key)
    else:
        if address == "auto":
            from ray_tpu._private.attach import find_sessions
            sessions = find_sessions(constants.SHM_ROOT)
            if not sessions:
                raise ConnectionError(
                    f"no live ray_tpu session found under "
                    f"{constants.SHM_ROOT}")
            session_dir = sessions[0]
        elif address.endswith("node.sock"):
            session_dir = os.path.dirname(address)
        else:
            session_dir = address
        sock = os.path.join(session_dir, "node.sock")
        if not os.path.exists(sock):
            raise ConnectionError(f"no session socket at {sock}")
        with open(os.path.join(session_dir, "authkey"), "rb") as f:
            authkey = f.read()
    from ray_tpu._private import protocol
    from ray_tpu._private.worker_main import WorkerRuntime
    wid = f"attach_client_{os.getpid()}_{uuid.uuid4().hex[:6]}"
    rt = WorkerRuntime(sock, wid, authkey, exit_on_disconnect=False)
    rt.send(protocol.RegisterWorker(wid, os.getpid()))
    threading.Thread(target=rt.reader_loop, daemon=True,
                     name="ray_tpu-client-reader").start()
    client = _worker.connect_worker_mode(rt)
    if log_to_driver or (log_to_driver is None and
                         os.environ.get("RAY_TPU_LOG_TO_DRIVER") == "1"):
        client.control("log_subscribe")
    return client


def _gc_stale_sessions():
    """Remove session dirs whose driver process is gone (crash leftovers)."""
    import shutil
    for d in glob.glob(os.path.join(constants.SHM_ROOT,
                                    constants.SESSION_PREFIX + "*")):
        pidfile = os.path.join(d, "driver.pid")
        try:
            with open(pidfile) as f:
                pid = int(f.read().strip())
            os.kill(pid, 0)       # raises if the driver is dead
        except (FileNotFoundError, ValueError, ProcessLookupError):
            for sub in glob.glob(os.path.join(d, "nodes", "*")):
                shutil.rmtree(
                    os.path.join(constants.OBJECT_SPILL_ROOT,
                                 os.path.basename(sub)),
                    ignore_errors=True)
            shutil.rmtree(
                os.path.join(constants.OBJECT_SPILL_ROOT,
                             os.path.basename(d)), ignore_errors=True)
            shutil.rmtree(d, ignore_errors=True)
        except PermissionError:
            pass                  # someone else's live session


def shutdown():
    if not _worker.is_initialized():
        return
    from ray_tpu.dashboard import stop_dashboard
    stop_dashboard()
    client = _worker.get_client()
    if client.mode == "driver":
        client.node.shutdown()
    elif getattr(client, "rt", None) is not None and \
            client.rt.worker_id.startswith("attach_client_"):
        # remote driver: just drop the connection; the session stays up
        client.rt.shutdown = True      # stops the ref-flush loop too
        try:
            client.rt.conn.close()
        except OSError:
            pass
    _worker.disconnect()


def is_initialized() -> bool:
    return _worker.is_initialized()


def remote(*args, **kwargs):
    """`@remote` decorator for functions and classes (reference:
    worker.py:3022). Usable bare or with options:

        @ray_tpu.remote
        def f(x): ...

        @ray_tpu.remote(num_cpus=2, num_tpus=1)
        class Learner: ...
    """
    import inspect

    def make(target, options):
        if inspect.isclass(target):
            return ActorClass(target, options)
        if not callable(target):
            raise TypeError("@remote target must be a function or class")
        return RemoteFunction(target, options)

    if len(args) == 1 and not kwargs and callable(args[0]):
        return make(args[0], {})
    if args:
        raise TypeError("@remote() takes only keyword options")
    return lambda target: make(target, kwargs)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    """Best-effort cancel of a pending task (reference: worker.py:2777).
    Running tasks are not interrupted in v1."""
    return _worker.get_client().control(
        "cancel", {"object_id": ref._id, "force": force})


def free(refs) -> int:
    """Unconditionally release objects (reference:
    `_private/internal_api.py free()`): the caller asserts nothing will
    read these refs again. Exists for bulk-intermediate lifecycles
    (e.g. shuffle shards) whose refs rode inside other objects and
    therefore escaped normal refcounting; returns how many objects were
    still live."""
    from ray_tpu._private.worker import ObjectRef as _Ref
    oids = [r._id if isinstance(r, _Ref) else str(r) for r in refs]
    return _worker.get_client().control("free_objects", oids)


def cluster_resources() -> dict:
    return _worker.get_client().control("cluster_resources")


def available_resources() -> dict:
    return _worker.get_client().control("available_resources")


def nodes() -> list:
    res = cluster_resources()
    return [{"NodeID": "local", "Alive": True, "Resources": res}]


def timeline(filename: str | None = None):
    """Chrome-trace task timeline (`ray.timeline` counterpart)."""
    from ray_tpu.util import state as _state
    return _state.timeline(filename)
