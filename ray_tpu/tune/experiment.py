"""Trial state and experiment-level checkpointing.

Counterpart of the reference's `tune/experiment/trial.py` (Trial state
machine PENDING/RUNNING/PAUSED/TERMINATED/ERROR) and
`tune/execution/experiment_state.py:98` (`_ExperimentCheckpointManager` —
periodic experiment snapshots enabling `Tuner.restore`).
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Optional

from ray_tpu.train.checkpoint import Checkpoint

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"

EXPERIMENT_STATE_FILE = "experiment_state.json"


class Trial:
    def __init__(self, trial_id: str, config: dict, experiment_dir: str,
                 resources: Optional[dict] = None,
                 pg_factory: Optional[dict] = None):
        self.trial_id = trial_id
        self.config = dict(config)
        self.resources = dict(resources or {"CPU": 1.0})
        # Gang-reservation spec: every trial runs inside a placement
        # group built from these bundles (reference:
        # tune/execution/placement_groups.py:9 — each trial IS a
        # PlacementGroupFactory). Bundle 0 hosts the trial executor;
        # trainer trials append one bundle per training worker.
        self.pg_factory = dict(pg_factory) if pg_factory else {
            "bundles": [dict(self.resources)], "strategy": "PACK"}
        self.status = PENDING
        self.last_result: dict = {}
        self.metrics_history: list = []
        self.error: Optional[str] = None
        self.num_failures = 0
        self.local_dir = os.path.join(experiment_dir, f"trial_{trial_id}")
        os.makedirs(self.local_dir, exist_ok=True)
        # Latest persisted checkpoint (dict-backed checkpoints are written
        # to disk on save so experiment resume survives a driver restart).
        self.checkpoint_path: Optional[str] = None
        # runtime-only fields (not persisted)
        self.actor = None
        self.pg = None              # live PlacementGroup reservation
        self._pbt_exploit = None
        # remote mirror of this trial's dir (reference: tune/syncer.py);
        # set by the Tuner when storage_path is a URI
        self.sync_uri: Optional[str] = None

    # -- persistence ------------------------------------------------------

    def persist_checkpoint(self, ckpt: Checkpoint, iteration: int) -> str:
        name = f"checkpoint_{iteration:06d}"
        path = os.path.join(self.local_dir, name)
        ckpt.to_directory(path)
        self.checkpoint_path = path
        if self.sync_uri:
            # a transient remote-storage failure must not kill the run;
            # the local checkpoint is intact and the next sync retries
            # (reference: syncer errors are logged, not fatal)
            from ray_tpu.util import storage
            try:
                storage.upload_dir_committed(
                    path, storage.uri_join(self.sync_uri, name))
            except Exception:
                import logging
                logging.getLogger("ray_tpu.tune").exception(
                    "checkpoint sync to %s failed", self.sync_uri)
        return path

    def latest_checkpoint(self) -> Optional[Checkpoint]:
        if self.checkpoint_path and os.path.isdir(self.checkpoint_path):
            return Checkpoint.from_directory(self.checkpoint_path)
        return None

    def to_state(self) -> dict:
        cp = self.checkpoint_path
        if cp and cp.startswith(self.local_dir):
            # store relative so a restore into a DIFFERENT staging dir
            # (URI experiments) still resolves
            cp = os.path.relpath(cp, self.local_dir)
        return {
            "trial_id": self.trial_id,
            "config": _jsonable(self.config),
            "resources": self.resources,
            "pg_factory": self.pg_factory,
            "status": self.status,
            "last_result": _jsonable(self.last_result),
            "error": self.error,
            "num_failures": self.num_failures,
            "checkpoint_path": cp,
        }

    @classmethod
    def from_state(cls, state: dict, experiment_dir: str) -> "Trial":
        t = cls(state["trial_id"], state["config"], experiment_dir,
                state.get("resources"), state.get("pg_factory"))
        t.status = state["status"]
        t.last_result = state.get("last_result", {})
        t.error = state.get("error")
        t.num_failures = state.get("num_failures", 0)
        cp = state.get("checkpoint_path")
        if cp and not os.path.isabs(cp):
            cp = os.path.join(t.local_dir, cp)
        t.checkpoint_path = cp
        if t.status in (RUNNING, PAUSED):
            t.status = PENDING      # was in flight when the driver died
        return t

    def __repr__(self):
        return f"Trial({self.trial_id}, {self.status})"


def _jsonable(obj):
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        if isinstance(obj, dict):
            return {k: _jsonable(v) for k, v in obj.items()}
        return repr(obj)


def new_trial_id() -> str:
    return uuid.uuid4().hex[:8]


class ExperimentState:
    """Periodic snapshot of all trial states → experiment_state.json."""

    def __init__(self, experiment_dir: str, save_period_s: float = 5.0,
                 sync_uri: Optional[str] = None):
        self.experiment_dir = experiment_dir
        os.makedirs(experiment_dir, exist_ok=True)
        self.save_period_s = save_period_s
        self.sync_uri = sync_uri
        self._last_save = 0.0

    @property
    def path(self) -> str:
        return os.path.join(self.experiment_dir, EXPERIMENT_STATE_FILE)

    def save(self, trials: list, force: bool = False) -> None:
        now = time.time()
        if not force and now - self._last_save < self.save_period_s:
            return
        self._last_save = now
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"timestamp": now,
                       "trials": [t.to_state() for t in trials]}, f)
        os.replace(tmp, self.path)
        if self.sync_uri:
            from ray_tpu.util import storage
            try:
                with open(self.path, "rb") as f:
                    storage.write_bytes(
                        storage.uri_join(self.sync_uri,
                                         EXPERIMENT_STATE_FILE),
                        f.read())
            except Exception:
                import logging
                logging.getLogger("ray_tpu.tune").exception(
                    "experiment-state sync to %s failed", self.sync_uri)

    @classmethod
    def load_trials(cls, experiment_dir: str) -> list:
        path = os.path.join(experiment_dir, EXPERIMENT_STATE_FILE)
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"no experiment state at {path}; cannot restore")
        with open(path) as f:
            state = json.load(f)
        return [Trial.from_state(s, experiment_dir)
                for s in state["trials"]]
