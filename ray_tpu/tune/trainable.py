"""Trainable API: the unit of work a Tune trial executes.

Counterpart of the reference's `tune/trainable/trainable.py:68` (class
Trainable: setup/step/save_checkpoint/load_checkpoint, driven by
train()/save()/restore()) and `tune/trainable/function_trainable.py:292`
(user function running in a thread, reports bridged through a queue — the
same concurrency shape as the Train session, which we reuse directly).
"""

from __future__ import annotations

import os
import threading
import time
import traceback
import queue as _queue

from ray_tpu.train.checkpoint import Checkpoint

# Result bookkeeping keys (reference: tune/result.py)
TRAINING_ITERATION = "training_iteration"
DONE = "done"
TRIAL_ID = "trial_id"
TIME_TOTAL_S = "time_total_s"


class Trainable:
    """Class API: subclass and implement setup/step/save/load_checkpoint.

    train() is called repeatedly by the controller; each call returns one
    result dict (one "iteration").
    """

    def __init__(self, config: dict | None = None, trial_dir: str | None = None):
        self.config = dict(config or {})
        self._iteration = 0
        self._time_total = 0.0
        self._trial_dir = trial_dir or os.getcwd()
        self.setup(self.config)

    # -- subclass surface -------------------------------------------------

    def setup(self, config: dict) -> None:
        pass

    def step(self) -> dict:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str) -> dict | str | None:
        """Return a dict (stored for you) or write files into
        checkpoint_dir and return it."""
        return None

    def load_checkpoint(self, checkpoint: dict | str) -> None:
        pass

    def reset_config(self, new_config: dict) -> bool:
        """Return True if the trainable reconfigured in place (lets PBT
        reuse the actor; reference: trainable.py reset_config)."""
        return False

    def cleanup(self) -> None:
        pass

    # -- controller surface ----------------------------------------------

    @property
    def iteration(self) -> int:
        return self._iteration

    @property
    def trial_dir(self) -> str:
        return self._trial_dir

    def train(self) -> dict:
        start = time.time()
        result = self.step() or {}
        self._iteration += 1
        self._time_total += time.time() - start
        result.setdefault(TRAINING_ITERATION, self._iteration)
        result.setdefault(TIME_TOTAL_S, self._time_total)
        result.setdefault(DONE, False)
        return result

    def save(self) -> Checkpoint:
        ckpt_dir = os.path.join(
            self._trial_dir, f"checkpoint_{self._iteration:06d}")
        os.makedirs(ckpt_dir, exist_ok=True)
        data = self.save_checkpoint(ckpt_dir)
        if isinstance(data, dict):
            ckpt = Checkpoint.from_dict(
                {**data, "_tune_iteration": self._iteration})
        else:
            ckpt = Checkpoint.from_directory(data or ckpt_dir)
        return ckpt

    def restore(self, checkpoint: Checkpoint) -> None:
        try:
            data = checkpoint.to_dict()
            self._iteration = int(data.pop("_tune_iteration", 0))
            self.load_checkpoint(data)
        except (ValueError, NotImplementedError, FileNotFoundError):
            self.load_checkpoint(checkpoint.as_directory())

    def reset(self, new_config: dict) -> bool:
        ok = self.reset_config(dict(new_config))
        if ok:
            self.config = dict(new_config)
        return ok

    def stop(self) -> None:
        self.cleanup()


class FunctionTrainable(Trainable):
    """Wraps `fn(config)` that calls `ray_tpu.tune.report(...)`.

    The function runs in a daemon thread; train() blocks until the next
    report (or function return, which yields a final done=True result) —
    the reference's `function_trainable.py` shape, minus its Tune/Train
    session duplication.
    """

    _fn = None          # set by subclassing in wrap_function

    def setup(self, config: dict) -> None:
        self._queue: _queue.Queue = _queue.Queue(1)
        self._consumed = threading.Semaphore(0)
        self._stop_event = threading.Event()
        self._error: list = []
        self._restore_checkpoint: Checkpoint | None = None
        self._last_report_checkpoint: Checkpoint | None = None
        # checkpoint of the last CONSUMED report — what save() persists;
        # _last_report_checkpoint may already belong to a report the
        # controller hasn't seen (the fn thread runs ahead by one).
        self._consumed_checkpoint: Checkpoint | None = None
        self._last_metrics: dict = {}
        self._thread: threading.Thread | None = None

    def _runner(self) -> None:
        _session._install(self)
        try:
            self._fn(self.config)
            kind = "return"
        except SystemExit:
            kind = "return"
        except BaseException:       # surfaces in train() as an error result
            self._error.append(traceback.format_exc())
            kind = "error"
        # After a stop(), an unconsumed report may still occupy the
        # size-1 queue; a blocking put here would hang this thread
        # forever. Nobody reads the sentinel post-stop, so best-effort.
        try:
            self._queue.put_nowait((kind, None))
        except _queue.Full:
            pass

    # called from the user thread via tune.report
    def _report(self, metrics: dict, checkpoint=None) -> None:
        if self._stop_event.is_set():
            raise SystemExit(0)
        if checkpoint is not None and not isinstance(checkpoint, Checkpoint):
            checkpoint = Checkpoint.from_dict(dict(checkpoint))
        self._last_report_checkpoint = checkpoint
        self._queue.put(("report", {"metrics": dict(metrics),
                                    "checkpoint": checkpoint}))
        self._consumed.acquire()

    def _get_checkpoint(self) -> Checkpoint | None:
        return self._restore_checkpoint

    def step(self) -> dict:
        if self._thread is None:
            self._thread = threading.Thread(target=self._runner, daemon=True)
            self._thread.start()
        kind, payload = self._queue.get()
        if kind == "return":
            # Function finished: final result = last reported metrics,
            # flagged done (reference: function_trainable.py final report).
            return {**self._last_metrics, DONE: True}
        if kind == "error":
            raise RuntimeError(self._error[0])
        metrics = payload["metrics"]
        self._last_metrics = dict(metrics)
        self._consumed_checkpoint = payload["checkpoint"]
        self._consumed.release()
        return metrics

    def save_checkpoint(self, checkpoint_dir: str):
        if self._consumed_checkpoint is not None:
            return dict(self._consumed_checkpoint.to_dict())
        return {"_no_user_checkpoint": True}

    def load_checkpoint(self, checkpoint) -> None:
        if isinstance(checkpoint, dict):
            checkpoint = {k: v for k, v in checkpoint.items()
                          if k != "_no_user_checkpoint"}
            self._restore_checkpoint = (
                Checkpoint.from_dict(checkpoint) if checkpoint else None)
        else:
            self._restore_checkpoint = Checkpoint.from_directory(checkpoint)

    def stop(self) -> None:
        self._stop_event.set()
        # Drop an unconsumed report so the runner's final sentinel (or a
        # report in flight) can't block on the full size-1 queue, then
        # unblock a report waiting on the consumption semaphore.
        try:
            self._queue.get_nowait()
        except _queue.Empty:
            pass
        self._consumed.release()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.cleanup()


class _Session:
    """Worker-side singleton bridging tune.report to the live trainable."""

    def __init__(self):
        self._local = threading.local()

    def _install(self, trainable: FunctionTrainable) -> None:
        self._local.trainable = trainable

    def _get(self) -> FunctionTrainable:
        t = getattr(self._local, "trainable", None)
        if t is None:
            raise RuntimeError(
                "tune.report() may only be called inside a Tune trial")
        return t

    def report(self, metrics: dict, checkpoint=None) -> None:
        self._get()._report(metrics, checkpoint)

    def get_checkpoint(self) -> Checkpoint | None:
        return self._get()._get_checkpoint()


_session = _Session()


def report(metrics: dict | None = None, *, checkpoint=None, **kwargs) -> None:
    """`tune.report` (reference exposes both kwargs and dict forms)."""
    merged = dict(metrics or {})
    merged.update(kwargs)
    _session.report(merged, checkpoint)


def get_checkpoint() -> Checkpoint | None:
    return _session.get_checkpoint()


def wrap_function(fn) -> type:
    """Build a FunctionTrainable subclass for `fn` (reference:
    function_trainable.py wrap_function)."""
    name = getattr(fn, "__name__", "func")
    return type(f"FunctionTrainable_{name}", (FunctionTrainable,),
                {"_fn": staticmethod(fn)})


def with_parameters(fn, **heavy_kwargs):
    """Bind large objects by reference so they're put in the object store
    once (reference: tune/trainable/util.py with_parameters)."""
    import functools
    import ray_tpu
    refs = {k: ray_tpu.put(v) for k, v in heavy_kwargs.items()}

    @functools.wraps(fn)
    def inner(config):
        resolved = {k: ray_tpu.get(r) for k, r in refs.items()}
        return fn(config, **resolved)

    return inner


def with_resources(trainable, resources: dict):
    """Attach per-trial resource requests (reference: tune.with_resources).

    Returns a wrapper; the original class/function is left untouched so
    resource requests cannot leak into unrelated tune.run calls that
    reuse the same trainable object.
    """
    import functools
    import inspect
    if inspect.isclass(trainable):
        wrapped = type(trainable.__name__, (trainable,),
                       {"_tune_resources": dict(resources)})
        return wrapped

    @functools.wraps(trainable)
    def fn_wrapper(*args, **kwargs):
        return trainable(*args, **kwargs)

    fn_wrapper._tune_resources = dict(resources)
    return fn_wrapper
