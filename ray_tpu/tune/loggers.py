"""Per-trial logger callbacks: CSV, JSONL, and TensorBoard event files.

Counterpart of the reference's `tune/logger/` (csv.py, json.py,
tensorboardx.py) as controller callbacks. The TensorBoard writer encodes
the tfrecord/Event-proto format by hand (this image vendors no tensorboard
library): records are [len u64le][masked-crc32c(len) u32le][payload]
[masked-crc32c(payload) u32le], and the Event/Summary protos only need
three scalar fields each, so the wire format is ~40 lines.
"""

from __future__ import annotations

import csv
import json
import os
import struct
import time

# Record framing (shared with the Data tfrecord codec): re-exported so
# existing imports of write_record/read_records keep working.
from ray_tpu._private.tfrecord import (  # noqa: F401
    read_records,
    write_record,
)

# ---------------------------------------------------------------------------
# minimal protobuf wire encoding for Event{wall_time, step, summary}
# ---------------------------------------------------------------------------

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        bits = n & 0x7F
        n >>= 7
        if n:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _field(num: int, wire: int) -> bytes:
    return _varint((num << 3) | wire)


def _encode_value(tag: str, value: float) -> bytes:
    # Summary.Value: 1 tag (string), 2 simple_value (float)
    t = tag.encode()
    return (_field(1, 2) + _varint(len(t)) + t
            + _field(2, 5) + struct.pack("<f", float(value)))


def encode_event(step: int, scalars: dict, wall_time: float | None = None
                 ) -> bytes:
    """Event: 1 wall_time (double), 2 step (int64), 5 summary (Summary);
    Summary: repeated 1 value (Summary.Value)."""
    summary = b""
    for tag, val in scalars.items():
        v = _encode_value(tag, val)
        summary += _field(1, 2) + _varint(len(v)) + v
    ev = (_field(1, 1) + struct.pack("<d", wall_time or time.time())
          + _field(2, 0) + _varint(step & 0xFFFFFFFFFFFFFFFF)
          + _field(5, 2) + _varint(len(summary)) + summary)
    return ev


# ---------------------------------------------------------------------------
# callbacks (duck-typed against tune_controller's _safe dispatch)
# ---------------------------------------------------------------------------

def _scalar_items(result: dict):
    for k, v in result.items():
        if isinstance(v, bool):
            continue
        if isinstance(v, (int, float)):
            yield k, float(v)


class JsonLoggerCallback:
    """result.json: one JSON line per reported result per trial
    (reference: tune/logger/json.py)."""

    def on_trial_result(self, trial, result):
        with open(os.path.join(trial.local_dir, "result.json"), "a") as f:
            f.write(json.dumps(
                {k: v for k, v in result.items()
                 if isinstance(v, (int, float, str, bool, list, dict,
                                   type(None)))},
                default=str) + "\n")


class CSVLoggerCallback:
    """progress.csv per trial; the header is the union of the first
    result's scalar keys (reference: tune/logger/csv.py)."""

    def __init__(self):
        self._writers = {}

    def on_trial_result(self, trial, result):
        key = trial.trial_id
        scalars = dict(_scalar_items(result))
        if key not in self._writers:
            path = os.path.join(trial.local_dir, "progress.csv")
            f = open(path, "a", newline="")
            w = csv.DictWriter(f, fieldnames=sorted(scalars))
            if f.tell() == 0:
                w.writeheader()
            self._writers[key] = (f, w)
        f, w = self._writers[key]
        w.writerow({k: scalars.get(k) for k in w.fieldnames})
        f.flush()

    def on_experiment_end(self, trials):
        for f, _ in self._writers.values():
            try:
                f.close()
            except OSError:
                pass
        self._writers.clear()


class TensorBoardLoggerCallback:
    """events.out.tfevents.* per trial with every numeric result as a
    scalar summary (reference: tune/logger/tensorboardx.py — but with a
    built-in encoder instead of the tensorboardX dependency)."""

    def __init__(self):
        self._files = {}

    def _file(self, trial):
        key = trial.trial_id
        if key not in self._files:
            path = os.path.join(
                trial.local_dir,
                f"events.out.tfevents.{int(time.time())}.{key}")
            f = open(path, "ab")
            # file header event: wall_time only, step 0
            write_record(f, encode_event(0, {}, wall_time=time.time()))
            self._files[key] = f
        return self._files[key]

    def on_trial_result(self, trial, result):
        step = int(result.get("training_iteration",
                              result.get("step", 0)) or 0)
        scalars = {f"ray_tpu/{k}": v for k, v in _scalar_items(result)}
        if not scalars:
            return
        f = self._file(trial)
        write_record(f, encode_event(step, scalars))
        f.flush()

    def on_experiment_end(self, trials):
        for f in self._files.values():
            try:
                f.close()
            except OSError:
                pass
        self._files.clear()
