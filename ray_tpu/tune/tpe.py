"""Tree-structured Parzen Estimator searcher — native model-based HPO.

Counterpart surface of the reference's model-based searcher wrappers
(`tune/search/optuna/optuna_search.py`, hyperopt) — but implemented
natively (the image vendors no HPO library), following Bergstra et al.
2011: observations split into the best gamma-quantile ("good") and the
rest ("bad"); each numeric dimension is modeled with Gaussian Parzen
windows over the good/bad sets, candidates are drawn from the good
density and ranked by the density ratio l(x)/g(x); categoricals use
smoothed count ratios. Dimensions are treated independently (the standard
TPE factorization).
"""

from __future__ import annotations

import math
import random
from typing import Optional

from ray_tpu.tune.search import (
    Categorical,
    Domain,
    Float,
    Function,
    Integer,
    Searcher,
    _is_grid,
    _walk,
    _set_path,
)


class TPESearcher(Searcher):
    """Suggest-based TPE over a param_space of sample domains.

    Args:
        param_space: dict of Domains (grid_search entries are treated as
            categorical choices).
        metric: result key to optimize.
        mode: "min" or "max".
        n_initial: random-exploration suggestions before the model engages.
        gamma: fraction of observations modeled as "good".
        n_candidates: candidates scored per suggestion.
    """

    # configs must be suggested lazily, AFTER earlier trials report
    # (tuner.py defers suggest() to trial launch when this is set)
    requires_results = True

    def __init__(self, param_space: dict, metric: str, mode: str = "min",
                 n_initial: int = 10, gamma: float = 0.25,
                 n_candidates: int = 24, seed: Optional[int] = None):
        super().__init__(metric, mode)
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be min|max, got {mode!r}")
        self.param_space = param_space
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._dims = {}     # path tuple -> Domain (or Categorical for grid)
        for path, dom in _walk(param_space):
            if _is_grid(dom):
                self._dims[path] = Categorical(dom["grid_search"])
            elif isinstance(dom, Domain):
                self._dims[path] = dom
            # constant leaves pass through via the deepcopy in _unflatten
        self._live: dict[str, dict] = {}       # trial_id -> flat config
        self._history: list[tuple[dict, float]] = []   # (flat cfg, score)

    # -- Searcher interface ----------------------------------------------

    def suggest(self, trial_id: str) -> Optional[dict]:
        if len(self._history) < self.n_initial:
            flat = {p: self._rand(d) for p, d in self._dims.items()}
        else:
            flat = {p: self._suggest_dim(p, d)
                    for p, d in self._dims.items()}
        self._live[trial_id] = flat
        cfg = _unflatten(self.param_space, flat)
        return cfg

    def on_trial_complete(self, trial_id, result=None, error=False) -> None:
        flat = self._live.pop(trial_id, None)
        if flat is None or error or not result:
            return
        val = result.get(self.metric)
        if val is None:
            return
        score = float(val) if self.mode == "min" else -float(val)
        self._history.append((flat, score))

    # -- model ------------------------------------------------------------

    def _rand(self, dom: Domain):
        return dom.sample(self._rng)

    def _split(self):
        """Top sqrt-scaled slice is "good" (hyperopt's default_gamma:
        ceil(gamma * sqrt(n)), capped) — a linear fraction lets the
        model's own near-duplicate suggestions crowd the good set and the
        search collapses onto its incumbent cluster."""
        ordered = sorted(self._history, key=lambda t: t[1])
        n_good = min(
            max(2, int(math.ceil(self.gamma * math.sqrt(len(ordered))))),
            25, len(ordered))
        return ordered[:n_good], ordered[n_good:]

    def _suggest_dim(self, path, dom: Domain):
        if isinstance(dom, Function):
            return self._rand(dom)      # opaque: cannot model
        good, bad = self._split()
        gvals = [cfg[path] for cfg, _ in good if path in cfg]
        bvals = [cfg[path] for cfg, _ in bad if path in cfg]
        if not gvals:
            return self._rand(dom)
        if isinstance(dom, Categorical):
            return self._categorical(dom, gvals, bvals)
        if isinstance(dom, (Float, Integer)):
            return self._numeric(dom, gvals, bvals)
        return self._rand(dom)

    def _categorical(self, dom: Categorical, gvals, bvals):
        cats = dom.categories
        prior = 1.0 / max(len(cats), 1)

        def probs(vals):
            counts = {repr(c): prior for c in cats}
            for v in vals:
                counts[repr(v)] = counts.get(repr(v), prior) + 1.0
            total = sum(counts.values())
            return {k: v / total for k, v in counts.items()}

        pg, pb = probs(gvals), probs(bvals)
        # sample candidates from the good distribution, rank by ratio
        keys = [repr(c) for c in cats]
        weights = [pg[k] for k in keys]
        best, best_ratio = None, -1.0
        for _ in range(self.n_candidates):
            k = self._rng.choices(range(len(cats)), weights=weights)[0]
            ratio = pg[keys[k]] / max(pb[keys[k]], 1e-12)
            if ratio > best_ratio:
                best, best_ratio = cats[k], ratio
        return best

    def _numeric(self, dom, gvals, bvals):
        log = bool(getattr(dom, "log", False))

        def xform(v):
            return math.log(v) if log else float(v)

        lo, hi = xform(dom.lower), xform(dom.upper)
        span = max(hi - lo, 1e-12)
        sqrt2pi = math.sqrt(2 * math.pi)
        prior = 1.0 / span

        def model(vals):
            """Adaptive Parzen (hyperopt-style): DEDUPED sorted points,
            each with a bandwidth from its neighbor distances (extended
            by the domain bounds). Dedup matters: repeated suggestions of
            the incumbent would otherwise flood the good set with clones,
            shrink a global bandwidth to zero, and collapse the search
            onto one point."""
            pts = sorted({round(xform(v), 12) for v in vals})
            if not pts:
                return [], []
            bws = []
            for i, p in enumerate(pts):
                gaps = []
                if i > 0:
                    gaps.append(p - pts[i - 1])
                if i + 1 < len(pts):
                    gaps.append(pts[i + 1] - p)
                # smallest neighbor gap = most local scale; lone points
                # default to a quarter of the range
                bw = min(gaps) if gaps else span / 4.0
                bws.append(min(max(bw, span * 1e-3), span))
            return pts, bws

        gp, gbw = model(gvals)
        bp, bbw = model(bvals)

        def dens(x, pts, bws):
            if not pts:
                return prior
            s = 0.0
            for c, w in zip(pts, bws):
                z = (x - c) / w
                s += math.exp(-0.5 * z * z) / (w * sqrt2pi)
            return (prior + s) / (len(pts) + 1)

        best_x, best_ratio = None, -1.0
        for i in range(self.n_candidates):
            if i % 4 == 3 or not gp:
                # prior-draw candidates keep exploring the full range
                x = self._rng.uniform(lo, hi)
            else:
                j = self._rng.randrange(len(gp))
                x = min(max(self._rng.gauss(gp[j], gbw[j]), lo), hi)
            ratio = dens(x, gp, gbw) / max(dens(x, bp, bbw), 1e-300)
            if ratio > best_ratio:
                best_x, best_ratio = x, ratio
        val = math.exp(best_x) if log else best_x
        q = getattr(dom, "q", None)
        if q:
            val = round(val / q) * q
        if isinstance(dom, Integer):
            val = int(round(val))
            val = min(max(val, dom.lower), dom.upper - 1)
        else:
            val = min(max(val, dom.lower), dom.upper)
        return val


def _unflatten(space: dict, flat: dict) -> dict:
    import copy
    out = copy.deepcopy(space)
    for path, value in flat.items():
        _set_path(out, path, value)
    return out
