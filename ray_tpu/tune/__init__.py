"""ray_tpu.tune — experiment runner / hyperparameter search.

Counterpart of the reference's `python/ray/tune/` (SURVEY.md §2.6): the
Tuner/tune.run APIs, Trainable class + function APIs, grid/random search
with a pluggable Searcher seam, ASHA/HyperBand/median-stopping/PBT
schedulers, per-trial checkpointing and experiment-level resume.
"""

from ray_tpu.tune.search import (
    BasicVariantGenerator,
    Categorical,
    ConcurrencyLimiter,
    Domain,
    Searcher,
    choice,
    grid_search,
    lograndint,
    loguniform,
    qloguniform,
    qrandint,
    quniform,
    randint,
    randn,
    sample_from,
    uniform,
)
from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    AsyncHyperBandScheduler,
    FIFOScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
    TrialScheduler,
)
from ray_tpu.tune.trainable import (
    Trainable,
    get_checkpoint,
    report,
    with_parameters,
    with_resources,
)
from ray_tpu.tune.tuner import ResultGrid, TuneConfig, Tuner, run
from ray_tpu.tune.experiment import Trial
from ray_tpu.tune.tpe import TPESearcher
from ray_tpu.tune.bayesopt import BayesOptSearcher
from ray_tpu.tune.loggers import (
    CSVLoggerCallback,
    JsonLoggerCallback,
    TensorBoardLoggerCallback,
)

__all__ = [
    # search space
    "uniform", "quniform", "loguniform", "qloguniform", "randint",
    "qrandint", "lograndint", "choice", "sample_from", "randn",
    "grid_search", "Domain", "Categorical",
    # searchers
    "Searcher", "BasicVariantGenerator", "ConcurrencyLimiter",
    "TPESearcher",
    "BayesOptSearcher",
    # loggers
    "CSVLoggerCallback", "JsonLoggerCallback", "TensorBoardLoggerCallback",
    # schedulers
    "TrialScheduler", "FIFOScheduler", "ASHAScheduler",
    "AsyncHyperBandScheduler", "HyperBandScheduler", "MedianStoppingRule",
    "PopulationBasedTraining",
    # trainable + session
    "Trainable", "report", "get_checkpoint", "with_parameters",
    "with_resources",
    # runner
    "Tuner", "TuneConfig", "ResultGrid", "run", "Trial",
]

from ray_tpu._private.usage_stats import record_library_usage as _rlu
_rlu("tune")
del _rlu
