"""Trial schedulers: early stopping and population-based training.

Counterparts of the reference's `tune/schedulers/`: FIFO (trial_scheduler.py),
ASHA (`async_hyperband.py` — the recommended default), HyperBand
(`hyperband.py`), median stopping (`median_stopping_rule.py`), and PBT
(`pbt.py`). Decisions use the same CONTINUE/PAUSE/STOP contract.
"""

from __future__ import annotations

import math
import random
from typing import Dict, List, Optional

from ray_tpu.tune.trainable import TRAINING_ITERATION


class TrialScheduler:
    CONTINUE = "CONTINUE"
    PAUSE = "PAUSE"
    STOP = "STOP"

    metric: Optional[str] = None
    mode: str = "max"

    def set_metric(self, metric: Optional[str], mode: Optional[str]) -> None:
        if self.metric is None:
            self.metric = metric
        if mode:
            self.mode = mode

    def on_trial_add(self, trial) -> None:
        pass

    def on_trial_result(self, trial, result: dict) -> str:
        return self.CONTINUE

    def on_trial_complete(self, trial, result: Optional[dict]) -> None:
        pass

    def on_trial_remove(self, trial) -> None:
        pass

    def choose_trial_to_run(self, pending: List) -> Optional[object]:
        return pending[0] if pending else None


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion (the default)."""


def _score(result: dict, metric: str, mode: str) -> float:
    val = result.get(metric)
    if val is None:
        return -math.inf
    return float(val) if mode == "max" else -float(val)


class _Rung:
    """One milestone of a successive-halving bracket."""

    def __init__(self, milestone: float, rf: float):
        self.milestone = milestone
        self.rf = rf
        self.recorded: Dict[str, float] = {}

    def cutoff(self) -> Optional[float]:
        if not self.recorded:
            return None
        vals = sorted(self.recorded.values())
        idx = int(len(vals) * (1 - 1 / self.rf))
        return vals[min(idx, len(vals) - 1)]


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference: `async_hyperband.py:27` AsyncHyperBandScheduler).

    Each trial is assigned to a bracket; at every rung milestone the trial
    must be in the top 1/reduction_factor of results recorded at that rung
    or it is stopped. Asynchronous: no waiting for a full rung cohort.
    """

    def __init__(self, time_attr: str = TRAINING_ITERATION,
                 metric: Optional[str] = None, mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 4, brackets: int = 1):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.max_t, self.grace = max_t, grace_period
        self.rf = reduction_factor
        self._brackets: List[List[_Rung]] = []
        for s in range(brackets):
            rungs = []
            t = grace_period * (reduction_factor ** s)
            while t < max_t:
                rungs.append(_Rung(t, reduction_factor))
                t *= reduction_factor
            self._brackets.append(sorted(rungs, key=lambda r: -r.milestone))
        self._trial_bracket: Dict[str, int] = {}
        self._rng = random.Random(0)

    def on_trial_add(self, trial) -> None:
        self._trial_bracket[trial.trial_id] = (
            self._rng.randrange(len(self._brackets)))

    def on_trial_result(self, trial, result: dict) -> str:
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return self.STOP
        score = _score(result, self.metric, self.mode)
        bracket = self._brackets[self._trial_bracket.get(trial.trial_id, 0)]
        action = self.CONTINUE
        for rung in bracket:
            if t < rung.milestone or trial.trial_id in rung.recorded:
                continue
            cutoff = rung.cutoff()
            rung.recorded[trial.trial_id] = score
            if cutoff is not None and score < cutoff:
                action = self.STOP
            break
        return action


# The reference aliases this too (schedulers/__init__.py).
ASHAScheduler = AsyncHyperBandScheduler


class HyperBandScheduler(TrialScheduler):
    """Simplified synchronous-flavored HyperBand: ASHA brackets with
    staggered aggressiveness (reference: `hyperband.py`; the async variant
    is what the reference itself recommends, so this shares machinery)."""

    def __init__(self, time_attr: str = TRAINING_ITERATION,
                 metric: Optional[str] = None, mode: str = "max",
                 max_t: int = 81, reduction_factor: float = 3):
        self._asha = AsyncHyperBandScheduler(
            time_attr=time_attr, metric=metric, mode=mode, max_t=max_t,
            grace_period=1, reduction_factor=reduction_factor,
            brackets=max(1, int(math.log(max_t, reduction_factor))))

    def set_metric(self, metric, mode) -> None:
        self._asha.set_metric(metric, mode)
        self.metric, self.mode = self._asha.metric, self._asha.mode

    def on_trial_add(self, trial) -> None:
        self._asha.on_trial_add(trial)

    def on_trial_result(self, trial, result: dict) -> str:
        return self._asha.on_trial_result(trial, result)


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result so far is worse than the median of
    other trials' running means at the same time step (reference:
    `median_stopping_rule.py:18`)."""

    def __init__(self, time_attr: str = TRAINING_ITERATION,
                 metric: Optional[str] = None, mode: str = "max",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.grace = grace_period
        self.min_samples = min_samples_required
        self._history: Dict[str, List[float]] = {}

    def on_trial_result(self, trial, result: dict) -> str:
        score = _score(result, self.metric, self.mode)
        hist = self._history.setdefault(trial.trial_id, [])
        hist.append(score)
        if result.get(self.time_attr, 0) < self.grace:
            return self.CONTINUE
        others = [sum(h) / len(h) for tid, h in self._history.items()
                  if tid != trial.trial_id and h]
        if len(others) < self.min_samples:
            return self.CONTINUE
        median = sorted(others)[len(others) // 2]
        best = max(hist)
        return self.STOP if best < median else self.CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: `pbt.py:135`): at each perturbation interval, trials
    in the bottom quantile clone the checkpoint + config of a top-quantile
    trial and perturb the hyperparameters (explore).

    The controller performs the actual exploit (restore from the donor's
    checkpoint + reset config); this class only decides and records it via
    `trial._pbt_exploit = (donor_trial, new_config)`.
    """

    def __init__(self, time_attr: str = TRAINING_ITERATION,
                 metric: Optional[str] = None, mode: str = "max",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[dict] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.interval = perturbation_interval
        self.mutations = dict(hyperparam_mutations or {})
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, float] = {}
        self._scores: Dict[str, float] = {}
        self._trials: Dict[str, object] = {}

    def on_trial_add(self, trial) -> None:
        self._trials[trial.trial_id] = trial
        self._last_perturb[trial.trial_id] = 0

    def on_trial_remove(self, trial) -> None:
        self._trials.pop(trial.trial_id, None)
        self._scores.pop(trial.trial_id, None)

    on_trial_complete = lambda self, trial, result: self.on_trial_remove(trial)  # noqa: E731

    def _explore(self, config: dict) -> dict:
        new = dict(config)
        for key, spec in self.mutations.items():
            if self._rng.random() < self.resample_prob or key not in new:
                if callable(spec):
                    new[key] = spec()
                elif isinstance(spec, list):
                    new[key] = self._rng.choice(spec)
                elif hasattr(spec, "sample"):
                    new[key] = spec.sample(self._rng)
            else:
                cur = new[key]
                if isinstance(spec, list):
                    # move to a neighboring listed value
                    try:
                        i = spec.index(cur)
                        j = max(0, min(len(spec) - 1,
                                       i + self._rng.choice([-1, 1])))
                        new[key] = spec[j]
                    except ValueError:
                        new[key] = self._rng.choice(spec)
                elif isinstance(cur, (int, float)):
                    factor = self._rng.choice([0.8, 1.2])
                    new[key] = type(cur)(cur * factor) or cur
        return new

    def on_trial_result(self, trial, result: dict) -> str:
        t = result.get(self.time_attr, 0)
        self._scores[trial.trial_id] = _score(result, self.metric, self.mode)
        if t - self._last_perturb.get(trial.trial_id, 0) < self.interval:
            return self.CONTINUE
        self._last_perturb[trial.trial_id] = t
        scored = sorted(self._scores.items(), key=lambda kv: kv[1])
        n = len(scored)
        k = max(1, int(n * self.quantile))
        if n < 2 or k * 2 > n:
            return self.CONTINUE
        bottom = {tid for tid, _ in scored[:k]}
        top = [tid for tid, _ in scored[-k:]]
        if trial.trial_id in bottom:
            donor_id = self._rng.choice(top)
            donor = self._trials.get(donor_id)
            if donor is not None and donor is not trial:
                trial._pbt_exploit = (donor, self._explore(donor.config))
        return self.CONTINUE
