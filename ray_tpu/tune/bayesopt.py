"""Gaussian-process Bayesian optimization searcher — native model-based
HPO.

Counterpart surface of the reference's BayesOpt wrapper
(`tune/search/bayesopt/bayesopt_search.py`, which wraps the external
`bayesian-optimization` package) — implemented natively (the image
vendors no HPO library): an RBF-kernel GP over the normalized search
space with expected-improvement acquisition maximized over random
candidates. Float/Integer dims normalize to [0,1] (log domains in log
space); categoricals ride one-hot coordinates, the standard mixed-space
embedding.
"""

from __future__ import annotations

import math
import random
from typing import Optional

import numpy as np

from ray_tpu.tune.search import (
    Categorical,
    Domain,
    Float,
    Function,
    Integer,
    Searcher,
    _is_grid,
    _walk,
)


class BayesOptSearcher(Searcher):
    """Suggest-based GP-EI search over a param_space of sample domains.

    Args:
        param_space: dict of Domains (grid_search entries become
            categorical choices; Function leaves fall back to random).
        metric: result key to optimize.
        mode: "min" or "max".
        n_initial: random suggestions before the GP engages.
        n_candidates: random acquisition candidates per suggestion.
        length_scale: RBF kernel length scale in normalized coordinates.
        noise: observation noise added to the kernel diagonal.
        xi: EI exploration bonus.
    """

    requires_results = True    # suggest lazily, after earlier reports

    def __init__(self, param_space: dict, metric: str, mode: str = "min",
                 n_initial: int = 8, n_candidates: int = 256,
                 length_scale: float = 0.25, noise: float = 1e-4,
                 xi: float = 0.01, seed: Optional[int] = None):
        super().__init__(metric, mode)
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be min|max, got {mode!r}")
        self.param_space = param_space
        self.n_initial = n_initial
        self.n_candidates = n_candidates
        self.length_scale = length_scale
        self.noise = noise
        self.xi = xi
        self._rng = random.Random(seed)
        self._dims = {}
        for path, dom in _walk(param_space):
            if _is_grid(dom):
                self._dims[path] = Categorical(dom["grid_search"])
            elif isinstance(dom, Domain):
                self._dims[path] = dom
        self._live: dict[str, dict] = {}
        self._X: list[np.ndarray] = []      # embedded observations
        self._y: list[float] = []           # scores (min-oriented)
        self._flat: list[dict] = []

    # -- embedding ---------------------------------------------------------

    def _embed_dim(self, dom, value) -> list[float]:
        if isinstance(dom, Categorical):
            out = [0.0] * len(dom.categories)
            try:
                out[dom.categories.index(value)] = 1.0
            except ValueError:
                pass
            return out
        if isinstance(dom, (Float, Integer)):
            lo, hi = float(dom.lower), float(dom.upper)
            v = float(value)
            if getattr(dom, "log", False):
                lo, hi, v = math.log(lo), math.log(hi), math.log(max(v,
                                                                     1e-300))
            return [min(1.0, max(0.0, (v - lo) / max(hi - lo, 1e-12)))]
        return [0.0]    # Function/constant: uninformative coordinate

    def _embed(self, flat: dict) -> np.ndarray:
        out: list[float] = []
        for path, dom in self._dims.items():
            out.extend(self._embed_dim(dom, flat.get(path)))
        return np.asarray(out)

    def _random_flat(self) -> dict:
        return {path: dom.sample(self._rng)
                for path, dom in self._dims.items()}

    # -- GP ----------------------------------------------------------------

    def _kernel(self, A: np.ndarray, B: np.ndarray) -> np.ndarray:
        d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
        return np.exp(-d2 / (2 * self.length_scale ** 2))

    def _posterior(self, Xs: np.ndarray):
        X = np.stack(self._X)
        y = np.asarray(self._y)
        mu0 = y.mean()
        K = self._kernel(X, X) + self.noise * np.eye(len(X))
        L = np.linalg.cholesky(K)
        alpha = np.linalg.solve(L.T, np.linalg.solve(L, y - mu0))
        Ks = self._kernel(Xs, X)
        mu = mu0 + Ks @ alpha
        v = np.linalg.solve(L, Ks.T)
        var = np.maximum(1.0 - (v ** 2).sum(0), 1e-12)
        return mu, np.sqrt(var)

    @staticmethod
    def _norm_cdf(z):
        return 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2.0)))

    def _expected_improvement(self, mu, sigma, best):
        # minimization EI
        imp = best - mu - self.xi
        z = imp / sigma
        pdf = np.exp(-0.5 * z ** 2) / math.sqrt(2 * math.pi)
        return imp * self._norm_cdf(z) + sigma * pdf

    # -- Searcher API ------------------------------------------------------

    def suggest(self, trial_id: str) -> Optional[dict]:
        from ray_tpu.tune.search import _set_path
        if len(self._y) < max(1, self.n_initial):
            flat = self._random_flat()
        else:
            cands = [self._random_flat()
                     for _ in range(self.n_candidates)]
            Xs = np.stack([self._embed(f) for f in cands])
            mu, sigma = self._posterior(Xs)
            ei = self._expected_improvement(mu, sigma, min(self._y))
            flat = cands[int(np.argmax(ei))]
        self._live[trial_id] = flat
        import copy
        cfg = copy.deepcopy(self.param_space)
        # every Domain/grid leaf is in self._dims, so this overwrites
        # ALL sampled leaves; constants pass through the deepcopy
        for path, value in flat.items():
            _set_path(cfg, path, value)
        return cfg

    def on_trial_complete(self, trial_id, result=None,
                          error: bool = False) -> None:
        flat = self._live.pop(trial_id, None)
        if flat is None or error or result is None:
            return
        value = result.get(self.metric)
        if value is None or not math.isfinite(float(value)):
            return
        score = float(value) if self.mode == "min" else -float(value)
        self._X.append(self._embed(flat))
        self._y.append(score)
        self._flat.append(flat)
