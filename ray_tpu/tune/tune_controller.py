"""Trial execution loop.

Counterpart of the reference's `tune/execution/tune_controller.py:49`
(actor-manager-based TuneController) and `ray_trial_executor.py:188`:
every trial runs inside a dedicated actor; the controller is an event loop
over in-flight `train()` futures — process a result, consult the
scheduler, launch/stop/restore trials, snapshot experiment state.

Simplifications vs the reference, on purpose: one in-flight future per
trial (the reference multiplexes arbitrary actor calls), and checkpoints
save synchronously (cheap at trial granularity).
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional

import ray_tpu
from ray_tpu import exceptions as _exc
from ray_tpu.tune import experiment as _exp
from ray_tpu.tune.experiment import (
    ERROR, PENDING, RUNNING, TERMINATED, ExperimentState, Trial)
from ray_tpu.tune.schedulers import FIFOScheduler, TrialScheduler
from ray_tpu.tune.search import Searcher
from ray_tpu.tune.trainable import DONE, TRAINING_ITERATION

logger = logging.getLogger("ray_tpu.tune")


class _TrialExecutor:
    """The per-trial actor (reference: each Trainable IS an actor)."""

    def __init__(self, trainable_cls, config, trial_id, trial_dir):
        self.trainable = trainable_cls(config, trial_dir)
        self.trial_id = trial_id

    def ready(self):
        return True

    def train(self) -> dict:
        result = self.trainable.train()
        result.setdefault("trial_id", self.trial_id)
        return result

    def save(self):
        return self.trainable.save()

    def restore(self, checkpoint) -> None:
        self.trainable.restore(checkpoint)

    def reset(self, new_config: dict) -> bool:
        return self.trainable.reset(new_config)

    def stop(self) -> None:
        self.trainable.stop()


class TuneController:
    def __init__(self,
                 trainable_cls,
                 trials: List[Trial],
                 experiment_dir: str,
                 scheduler: Optional[TrialScheduler] = None,
                 searcher: Optional[Searcher] = None,
                 metric: Optional[str] = None,
                 mode: str = "max",
                 stop: Optional[dict] = None,
                 max_concurrent: Optional[int] = None,
                 max_failures: int = 0,
                 checkpoint_frequency: int = 0,
                 checkpoint_at_end: bool = False,
                 callbacks: Optional[list] = None,
                 sync_uri: Optional[str] = None):
        self.trainable_cls = trainable_cls
        self.trials = list(trials)
        self.scheduler = scheduler or FIFOScheduler()
        self.scheduler.set_metric(metric, mode)
        self.searcher = searcher
        self.metric, self.mode = metric, mode
        self.stop_criteria = dict(stop or {})
        self.max_failures = max_failures
        self.checkpoint_frequency = checkpoint_frequency
        self.checkpoint_at_end = checkpoint_at_end
        self.callbacks = list(callbacks or [])
        self.state = ExperimentState(experiment_dir, sync_uri=sync_uri)
        self.experiment_dir = experiment_dir
        if max_concurrent is None:
            cpus = ray_tpu.cluster_resources().get("CPU", 1)
            per_trial = max(
                (sum(b.get("CPU", 0.0)
                     for b in (t.pg_factory or {}).get("bundles", []))
                 or t.resources.get("CPU", 1.0))
                for t in self.trials) if self.trials else 1.0
            max_concurrent = max(1, int(cpus // max(per_trial, 0.001)))
        self.max_concurrent = max_concurrent
        self._futures: Dict[object, Trial] = {}   # train() future -> trial

    # ------------------------------------------------------------------

    def run(self) -> List[Trial]:
        for t in self.trials:
            if t.status == PENDING:
                self.scheduler.on_trial_add(t)
        for cb in self.callbacks:
            _safe(cb, "on_experiment_start", trials=self.trials)
        try:
            while not self._finished():
                self._launch_pending()
                if not self._futures:
                    if self._has_pending():
                        time.sleep(0.05)
                        continue
                    break
                self._process_one_event()
                self.state.save(self.trials)
        finally:
            self._cleanup()
            self.state.save(self.trials, force=True)
            for cb in self.callbacks:
                _safe(cb, "on_experiment_end", trials=self.trials)
        return self.trials

    # ------------------------------------------------------------------

    def _finished(self) -> bool:
        return all(t.status in (TERMINATED, ERROR) for t in self.trials) \
            and not self._futures

    def _has_pending(self) -> bool:
        return any(t.status == PENDING for t in self.trials)

    def _running_count(self) -> int:
        return sum(1 for t in self.trials if t.status == RUNNING)

    def _launch_pending(self) -> None:
        blocked: set = set()
        while self._running_count() < self.max_concurrent:
            pending = [t for t in self.trials
                       if t.status == PENDING and id(t) not in blocked]
            trial = self.scheduler.choose_trial_to_run(pending)
            if trial is None:
                break
            if not self._reserve_trial(trial):
                if (not self._futures and self._running_count() == 0
                        and not self._gang_fits_cluster(trial)):
                    # The gang exceeds the cluster's TOTAL capacity (not
                    # merely what's currently free — other workloads may
                    # release theirs): it can never fit. Fail the trial
                    # instead of spinning.
                    trial.status = ERROR
                    trial.error = ("placement group infeasible: "
                                   f"{trial.pg_factory}")
                    for cb in self.callbacks:
                        _safe(cb, "on_trial_error", trial=trial)
                    continue
                # Cluster full: the whole-gang reservation didn't fit.
                # Leave the trial PENDING and retry after a running trial
                # frees its group (reference: a trial's PG stays pending
                # in the scheduler, tune/execution/placement_groups.py).
                blocked.add(id(trial))
                continue
            self._start_trial(trial)

    def _gang_fits_cluster(self, trial: Trial) -> bool:
        """Whether the trial's bundles fit the cluster's total capacity
        (per resource type, summed over bundles)."""
        totals = ray_tpu.cluster_resources()
        need: Dict[str, float] = {}
        for b in (trial.pg_factory or {}).get("bundles") \
                or [dict(trial.resources)]:
            for k, v in b.items():
                need[k] = need.get(k, 0.0) + float(v)
        return all(totals.get(k, 0.0) >= v for k, v in need.items())

    def _reserve_trial(self, trial: Trial) -> bool:
        """Atomically reserve the trial's FULL resource footprint (trial
        executor + any training workers) as one placement group, so two
        multi-worker trials can never each grab half their actors and
        livelock."""
        if trial.pg is not None:
            return True
        from ray_tpu.util.placement_group import placement_group
        spec = trial.pg_factory or {}
        bundles = [dict(b) for b in spec.get("bundles")
                   or [dict(trial.resources)]]
        try:
            trial.pg = placement_group(
                bundles, strategy=spec.get("strategy", "PACK"))
        except _exc.PlacementGroupError:
            return False
        return True

    def _release_trial_pg(self, trial: Trial) -> None:
        if trial.pg is None:
            return
        from ray_tpu.util.placement_group import remove_placement_group
        try:
            remove_placement_group(trial.pg)
        except _exc.RayTpuError:
            pass
        trial.pg = None

    def _executor_config(self, trial: Trial, config: dict) -> dict:
        """Config as the trial executor sees it. Trainer trials place
        their worker group inside the trial's own reservation (bundles
        1..N) instead of creating a second group — the gang the
        controller reserved IS the gang the trainer uses."""
        config = dict(config)
        if getattr(self.trainable_cls, "_consumes_trial_pg", False) \
                and trial.pg is not None:
            config["_tune_trial_pg"] = {
                "id": trial.pg.id, "bundles": trial.pg.bundles,
                "strategy": trial.pg.strategy}
        return config

    def _start_trial(self, trial: Trial) -> None:
        from ray_tpu.tune.search import ConcurrencyLimiter
        inner = (self.searcher.searcher
                 if isinstance(self.searcher, ConcurrencyLimiter)
                 else self.searcher)
        if (not trial.config
                and getattr(inner, "requires_results", False)):
            # model-based searchers suggest lazily at launch, AFTER
            # earlier trials reported — an upfront batch would be pure
            # random exploration. The requires_results guard keeps this
            # off upfront-generated searchers (whose iterator is already
            # exhausted and would TERMINATE every trial).
            cfg = self.searcher.suggest(trial.trial_id)
            if cfg is None:
                self._release_trial_pg(trial)
                if isinstance(self.searcher, ConcurrencyLimiter):
                    # at capacity, not exhausted: leave PENDING and retry
                    # on a later scheduling pass
                    return
                trial.status = TERMINATED
                return
            trial.config = dict(cfg)
        actor_cls = ray_tpu.remote(
            **_actor_opts(trial.resources, trial.pg))(_TrialExecutor)
        trial.actor = actor_cls.remote(
            self.trainable_cls, self._executor_config(trial, trial.config),
            trial.trial_id, trial.local_dir)
        ckpt = trial.latest_checkpoint()
        if ckpt is not None:
            try:
                ray_tpu.get(trial.actor.restore.remote(ckpt), timeout=300)
            except _exc.RayTpuError as e:
                # A silently-failed restore would retrain from scratch
                # while bookkeeping thinks it resumed; treat as failure.
                self._handle_failure(trial, e)
                return
        trial.status = RUNNING
        for cb in self.callbacks:
            _safe(cb, "on_trial_start", trial=trial)
        self._submit_train(trial)

    def _submit_train(self, trial: Trial) -> None:
        fut = trial.actor.train.remote()
        self._futures[fut] = trial

    def _process_one_event(self) -> None:
        ready, _ = ray_tpu.wait(list(self._futures), num_returns=1,
                                timeout=60.0)
        if not ready:
            return
        fut = ready[0]
        trial = self._futures.pop(fut)
        try:
            result = ray_tpu.get(fut)
        except (_exc.TaskError, _exc.ActorDiedError,
                _exc.WorkerCrashedError, _exc.RayTpuError) as e:
            self._handle_failure(trial, e)
            return
        self._handle_result(trial, result)

    # ------------------------------------------------------------------

    def _handle_result(self, trial: Trial, result: dict) -> None:
        trial.last_result = result
        trial.metrics_history.append(result)
        if self.searcher is not None:
            self.searcher.on_trial_result(trial.trial_id, result)
        for cb in self.callbacks:
            _safe(cb, "on_trial_result", trial=trial, result=result)

        it = result.get(TRAINING_ITERATION, 0)
        if (self.checkpoint_frequency
                and it % self.checkpoint_frequency == 0
                and not result.get(DONE)):
            self._save_now(trial)

        if result.get(DONE) or self._hit_stop_criteria(result):
            self._stop_trial(trial, TERMINATED, result)
            return

        decision = self.scheduler.on_trial_result(trial, result)
        if decision == TrialScheduler.STOP:
            self._stop_trial(trial, TERMINATED, result)
            return

        exploit = getattr(trial, "_pbt_exploit", None)
        if exploit is not None:
            trial._pbt_exploit = None
            self._exploit(trial, *exploit)
        self._submit_train(trial)

    def _hit_stop_criteria(self, result: dict) -> bool:
        for key, threshold in self.stop_criteria.items():
            val = result.get(key)
            if val is None:
                continue
            if key == TRAINING_ITERATION or key.startswith("time_"):
                if val >= threshold:
                    return True
            elif (self.mode == "max" and val >= threshold) or \
                 (self.mode == "min" and val <= threshold):
                return True
        return False

    def _save_now(self, trial: Trial) -> None:
        try:
            ckpt = ray_tpu.get(trial.actor.save.remote(), timeout=120)
            it = trial.last_result.get(TRAINING_ITERATION, 0)
            trial.persist_checkpoint(ckpt, it)
        except _exc.RayTpuError as e:
            logger.warning("checkpoint save failed for %s: %s",
                           trial.trial_id, e)

    def _exploit(self, trial: Trial, donor: Trial, new_config: dict) -> None:
        """PBT exploit+explore: clone donor weights, adopt mutated config."""
        if donor.actor is None:
            return
        try:
            ckpt = ray_tpu.get(donor.actor.save.remote(), timeout=120)
            ok = ray_tpu.get(trial.actor.reset.remote(new_config),
                             timeout=60)
            if not ok:
                # Recreate the actor with the new config (the reference
                # falls back to a fresh actor when reset_config declines).
                ray_tpu.get(trial.actor.stop.remote(), timeout=30)
                ray_tpu.kill(trial.actor)
                actor_cls = ray_tpu.remote(
                    **_actor_opts(trial.resources, trial.pg))(
                        _TrialExecutor)
                trial.actor = actor_cls.remote(
                    self.trainable_cls,
                    self._executor_config(trial, new_config),
                    trial.trial_id, trial.local_dir)
            ray_tpu.get(trial.actor.restore.remote(ckpt), timeout=120)
            trial.config = dict(new_config)
            logger.info("PBT: trial %s exploited %s", trial.trial_id,
                        donor.trial_id)
        except _exc.RayTpuError as e:
            logger.warning("PBT exploit failed for %s: %s",
                           trial.trial_id, e)

    def _handle_failure(self, trial: Trial, err: Exception) -> None:
        trial.num_failures += 1
        trial.error = str(err)
        logger.warning("trial %s failed (%d): %s", trial.trial_id,
                       trial.num_failures, err)
        self._kill_actor(trial)
        # Release the gang so other pending trials can use the capacity
        # while this one waits to relaunch; _reserve_trial re-reserves.
        self._release_trial_pg(trial)
        unlimited = self.max_failures < 0
        if unlimited or trial.num_failures <= self.max_failures:
            trial.status = PENDING      # relaunched; restores from ckpt
        else:
            trial.status = ERROR
            if self.searcher is not None:
                self.searcher.on_trial_complete(trial.trial_id, error=True)
            self.scheduler.on_trial_complete(trial, None)
            for cb in self.callbacks:
                _safe(cb, "on_trial_error", trial=trial)

    def _stop_trial(self, trial: Trial, status: str, result: dict) -> None:
        if self.checkpoint_at_end:
            self._save_now(trial)
        if self.searcher is not None:
            self.searcher.on_trial_complete(trial.trial_id, result)
        self.scheduler.on_trial_complete(trial, result)
        self._kill_actor(trial)
        self._release_trial_pg(trial)
        trial.status = status
        for cb in self.callbacks:
            _safe(cb, "on_trial_complete", trial=trial, result=result)

    def _kill_actor(self, trial: Trial) -> None:
        if trial.actor is None:
            return
        # Drop any orphaned future for this trial.
        for fut, t in list(self._futures.items()):
            if t is trial:
                del self._futures[fut]
        try:
            ray_tpu.get(trial.actor.stop.remote(), timeout=10)
        except _exc.RayTpuError:
            pass
        try:
            ray_tpu.kill(trial.actor)
        except _exc.RayTpuError:
            pass
        trial.actor = None

    def _cleanup(self) -> None:
        for trial in self.trials:
            if trial.actor is not None:
                self._kill_actor(trial)
            self._release_trial_pg(trial)
            if trial.status == RUNNING:
                # Interrupted (Ctrl-C/driver exit), NOT finished: persist
                # as PENDING so Tuner.restore resumes it from its latest
                # checkpoint (reference: trials in flight are re-pended on
                # resume, experiment_state.py:441).
                trial.status = PENDING


def _actor_opts(resources: dict, pg=None) -> dict:
    opts = {}
    res = dict(resources)
    if "CPU" in res:
        opts["num_cpus"] = res.pop("CPU")
    if "TPU" in res:
        opts["num_tpus"] = res.pop("TPU")
    if res:
        opts["resources"] = res
    if pg is not None:
        from ray_tpu.util.scheduling_strategies import (
            PlacementGroupSchedulingStrategy)
        opts["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=0)
    return opts


def _safe(cb, method: str, **kwargs) -> None:
    fn = getattr(cb, method, None)
    if fn is None:
        return
    try:
        fn(**kwargs)
    except Exception:       # callbacks must never kill the experiment
        logger.exception("callback %s.%s failed", cb, method)
