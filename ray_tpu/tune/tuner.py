"""Tuner — the user-facing experiment API.

Counterpart of the reference's `tune/tuner.py:53` (Tuner.fit :320), the
functional `tune.run` (`tune/tune.py:293`), `TuneConfig`
(`tune/tune_config.py`), and `ResultGrid` (`tune/result_grid.py`).

Also the integration seam with the Train-equivalent: passing a
`JaxTrainer` to Tuner sweeps its `train_loop_config` — but unlike the
reference (where Train.fit secretly routes THROUGH Tune,
`base_trainer.py:570`), the coupling here points one way: Tune wraps
Train (SURVEY.md §7.2 M6).
"""

from __future__ import annotations

import inspect
import os
from dataclasses import dataclass
from typing import Callable, List, Optional, Type, Union

from ray_tpu.train.config import RunConfig
from ray_tpu.train.trainer import Result
from ray_tpu.tune.experiment import (
    ERROR, ExperimentState, Trial, new_trial_id)
from ray_tpu.tune.schedulers import TrialScheduler
from ray_tpu.tune.search import (
    BasicVariantGenerator, Searcher, count_variants, generate_variants)
from ray_tpu.tune.trainable import (
    Trainable, wrap_function)
from ray_tpu.tune.tune_controller import TuneController


@dataclass
class TuneConfig:
    """Reference: tune/tune_config.py."""
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    search_alg: Optional[Searcher] = None
    scheduler: Optional[TrialScheduler] = None
    seed: Optional[int] = None
    # stop criteria dict (e.g. {"training_iteration": 10}); the reference
    # puts this on tune.run / RunConfig.stop.
    stop: Optional[dict] = None


class ResultGrid:
    """Reference: tune/result_grid.py."""

    def __init__(self, results: List[Result], trials: List[Trial],
                 experiment_path: str):
        self._results = results
        self._trials = trials
        self.experiment_path = experiment_path

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self) -> List[str]:
        return [t.error for t in self._trials if t.status == ERROR]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: str = "max") -> Result:
        scored = [r for r in self._results
                  if metric is None or metric in r.metrics]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        key = (lambda r: r.metrics.get(metric, float("-inf"))) \
            if metric else (lambda r: 0)
        return (max if mode == "max" else min)(scored, key=key)

    def get_dataframe(self):
        import pandas as pd
        rows = []
        for t, r in zip(self._trials, self._results):
            row = {f"config/{k}": v for k, v in t.config.items()
                   if not isinstance(v, dict)}
            row.update(r.metrics)
            row["trial_id"] = t.trial_id
            rows.append(row)
        return pd.DataFrame(rows)


class Tuner:
    def __init__(self,
                 trainable: Union[Callable, Type[Trainable], object] = None,
                 *,
                 param_space: Optional[dict] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 _restore_path: Optional[str] = None):
        self.trainable = trainable
        self.param_space = dict(param_space or {})
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self._restore_path = _restore_path

    @classmethod
    def restore(cls, path: str, trainable=None) -> "Tuner":
        """Resume an interrupted experiment from its directory
        (reference: Tuner.restore, experiment_state.py:441)."""
        return cls(trainable, _restore_path=path)

    # ------------------------------------------------------------------

    def _resolve_trainable(self):
        """(trainable_cls, default_resources, pg_factory).

        Every trial is a gang reservation (reference:
        tune/execution/placement_groups.py:9 — trials schedule through
        PlacementGroupFactory): bundle 0 is the trial executor, and a
        trainer trial adds one bundle per training worker so the whole
        worker group reserves atomically.
        """
        t = self.trainable
        req = dict(getattr(t, "_tune_resources", {"CPU": 1.0}))
        if "bundles" in req:
            # with_resources(..., {"bundles": [...], "strategy": ...})
            bundles = [dict(b) for b in req["bundles"]]
            pg_factory = {"bundles": bundles,
                          "strategy": req.get("strategy", "PACK")}
            resources = dict(bundles[0])
        else:
            resources = req
            pg_factory = {"bundles": [dict(req)], "strategy": "PACK"}
        # JaxTrainer instance → function trainable that runs trainer.fit()
        # inside the trial with the sampled config merged in.
        from ray_tpu.train.trainer import JaxTrainer
        if isinstance(t, JaxTrainer):
            sc = t.scaling
            pg_factory = {
                "bundles": [dict(resources)] + [
                    dict(sc.worker_resources())
                    for _ in range(sc.num_workers)],
                "strategy": sc.placement_strategy,
            }
            return _trainer_as_trainable(t), resources, pg_factory
        if inspect.isclass(t) and issubclass(t, Trainable):
            return t, resources, pg_factory
        if callable(t):
            return wrap_function(t), resources, pg_factory
        raise TypeError(f"cannot tune {t!r}")

    def _make_trials(self, experiment_dir: str, resources: dict,
                     pg_factory: Optional[dict] = None) -> List[Trial]:
        tc = self.tune_config
        if tc.search_alg is not None:
            # Trials are generated upfront; a ConcurrencyLimiter caps
            # running trials via max_concurrent_trials instead (its
            # suggest() gate would truncate the experiment here).
            from ray_tpu.tune.search import ConcurrencyLimiter
            searcher = tc.search_alg
            if isinstance(searcher, ConcurrencyLimiter):
                if tc.max_concurrent_trials is None:
                    tc.max_concurrent_trials = searcher.max_concurrent
                searcher = searcher.searcher
            if getattr(searcher, "requires_results", False):
                # model-based searcher: configs resolve lazily at launch
                # (tune_controller._start_trial), so later suggestions see
                # earlier results instead of being one upfront batch
                return [Trial(new_trial_id(), {}, experiment_dir,
                              resources, pg_factory)
                        for _ in range(tc.num_samples)]
            trials = []
            tid = new_trial_id()
            total = tc.num_samples
            while len(trials) < total:
                cfg = searcher.suggest(tid)
                if cfg is None:
                    break
                trials.append(Trial(tid, cfg, experiment_dir, resources,
                                    pg_factory))
                tid = new_trial_id()
            return trials
        return [
            Trial(new_trial_id(), cfg, experiment_dir, resources,
                  pg_factory)
            for cfg in generate_variants(self.param_space, tc.num_samples,
                                         tc.seed)
        ]

    def fit(self) -> ResultGrid:
        from ray_tpu.util import storage as storage_mod
        tc = self.tune_config
        trainable_cls, resources, pg_factory = self._resolve_trainable()
        sync_uri = None
        if self._restore_path:
            if storage_mod.is_uri(self._restore_path):
                # remote experiment (reference: Tuner.restore("s3://...")
                # via tune/syncer.py sync-down): pull into local staging
                sync_uri = self._restore_path
                experiment_dir = storage_mod.staging_dir(sync_uri)
                storage_mod.download_dir(sync_uri, experiment_dir)
            else:
                experiment_dir = self._restore_path
            trials = ExperimentState.load_trials(experiment_dir)
        else:
            resolved = self.run_config.resolved_storage_path()
            if storage_mod.is_uri(resolved):
                sync_uri = resolved
                experiment_dir = storage_mod.staging_dir(resolved)
            else:
                experiment_dir = resolved
            os.makedirs(experiment_dir, exist_ok=True)
            trials = self._make_trials(experiment_dir, resources,
                                       pg_factory)
        if not trials:
            raise ValueError("search space produced no trials")
        if sync_uri:
            for t in trials:
                t.sync_uri = storage_mod.uri_join(
                    sync_uri, f"trial_{t.trial_id}")

        ckpt_cfg = self.run_config.checkpoint_config
        controller = TuneController(
            trainable_cls, trials, experiment_dir, sync_uri=sync_uri,
            scheduler=tc.scheduler,
            searcher=tc.search_alg,
            metric=tc.metric, mode=tc.mode,
            stop=tc.stop,
            max_concurrent=tc.max_concurrent_trials,
            max_failures=self.run_config.failure_config.max_failures,
            checkpoint_frequency=ckpt_cfg.checkpoint_frequency,
            checkpoint_at_end=bool(ckpt_cfg.num_to_keep
                                   or ckpt_cfg.checkpoint_frequency),
            callbacks=self.run_config.callbacks,
        )
        trials = controller.run()
        results = [
            Result(metrics=t.last_result,
                   checkpoint=t.latest_checkpoint(),
                   error=t.error,
                   metrics_history=t.metrics_history,
                   path=t.local_dir)
            for t in trials
        ]
        return ResultGrid(results, trials, experiment_dir)


def _trainer_as_trainable(trainer) -> type:
    """Each trial runs a full JaxTrainer.fit with the trial config merged
    into train_loop_config; worker actors are created from inside the
    trial actor (nested actors, like the reference's trial→WorkerGroup)
    but placed into the TRIAL's placement group (bundles 1..N), so the
    gang the controller reserved is the gang the trainer fills."""
    import copy

    def run_trainer(config: dict):
        from ray_tpu.tune.trainable import report
        config = dict(config)
        pg_spec = config.pop("_tune_trial_pg", None)
        t = copy.copy(trainer)
        t.config = {**trainer.config, **config}
        if pg_spec is not None:
            from ray_tpu.util.placement_group import PlacementGroup
            t._external_pg = PlacementGroup(
                pg_spec["id"], pg_spec["bundles"], pg_spec["strategy"])
        result = t.fit()
        final = dict(result.metrics)
        report(final, checkpoint=result.checkpoint)

    cls = wrap_function(run_trainer)
    cls._consumes_trial_pg = True
    return cls


def run(trainable, *, config: Optional[dict] = None, num_samples: int = 1,
        metric: Optional[str] = None, mode: str = "max",
        scheduler: Optional[TrialScheduler] = None,
        search_alg: Optional[Searcher] = None,
        stop: Optional[dict] = None,
        resources_per_trial: Optional[dict] = None,
        max_concurrent_trials: Optional[int] = None,
        name: Optional[str] = None,
        storage_path: Optional[str] = None,
        checkpoint_freq: int = 0,
        max_failures: int = 0,
        verbose: int = 1) -> ResultGrid:
    """Functional API (reference: tune.run, tune/tune.py:293)."""
    from ray_tpu.train.config import CheckpointConfig, FailureConfig
    if resources_per_trial:
        trainable = _with_res(trainable, resources_per_trial)
    tuner = Tuner(
        trainable,
        param_space=config,
        tune_config=TuneConfig(metric=metric, mode=mode,
                               num_samples=num_samples,
                               scheduler=scheduler, search_alg=search_alg,
                               max_concurrent_trials=max_concurrent_trials,
                               stop=stop),
        run_config=RunConfig(
            name=name or "tune_run", storage_path=storage_path,
            verbose=verbose,
            checkpoint_config=CheckpointConfig(
                checkpoint_frequency=checkpoint_freq),
            failure_config=FailureConfig(max_failures=max_failures)))
    return tuner.fit()


def _with_res(trainable, resources):
    from ray_tpu.tune.trainable import with_resources
    return with_resources(trainable, resources)
