"""Search spaces and search algorithms.

Counterpart of the reference's `tune/search/` package: sample domains
(`tune/search/sample.py` — Float/Integer/Categorical/Function), the
grid/random `BasicVariantGenerator` (`tune/search/basic_variant.py`), the
`Searcher` interface (`tune/search/searcher.py`) and `ConcurrencyLimiter`
(`tune/search/concurrency_limiter.py`).

The external-library wrappers the reference ships (optuna/hyperopt/...) are
deliberately not vendored; `Searcher` is the plug-in seam for them.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Dict, Iterator, List, Optional

# ---------------------------------------------------------------------------
# Sample domains (reference: tune/search/sample.py)
# ---------------------------------------------------------------------------


class Domain:
    """A distribution to sample a hyperparameter from."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Float(Domain):
    def __init__(self, lower: float, upper: float, *, log: bool = False,
                 q: Optional[float] = None):
        if log and lower <= 0:
            raise ValueError("loguniform needs a positive lower bound")
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng: random.Random) -> float:
        import math
        if self.log:
            val = math.exp(rng.uniform(math.log(self.lower),
                                       math.log(self.upper)))
        else:
            val = rng.uniform(self.lower, self.upper)
        if self.q:
            val = round(round(val / self.q) * self.q, 10)
        return val


class Integer(Domain):
    def __init__(self, lower: int, upper: int, *, log: bool = False,
                 q: int = 1):
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng: random.Random) -> int:
        import math
        if self.log:
            val = int(math.exp(rng.uniform(math.log(max(self.lower, 1)),
                                           math.log(self.upper))))
        else:
            # upper is exclusive, matching the reference's randint.
            val = rng.randrange(self.lower, self.upper)
        if self.q > 1:
            val = int(round(val / self.q) * self.q)
        return max(self.lower, min(val, self.upper - 1))


class Categorical(Domain):
    def __init__(self, categories: List[Any]):
        self.categories = list(categories)

    def sample(self, rng: random.Random) -> Any:
        return rng.choice(self.categories)


class Function(Domain):
    """`sample_from`: arbitrary callable, optionally of the partial spec."""

    def __init__(self, fn: Callable):
        self.fn = fn

    def sample(self, rng: random.Random, spec: Optional[dict] = None) -> Any:
        try:
            return self.fn(spec)
        except TypeError:
            return self.fn()


# Public constructors (reference exposes these on `ray.tune`).

def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def quniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, q=q)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def qloguniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, log=True, q=q)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def qrandint(lower: int, upper: int, q: int) -> Integer:
    return Integer(lower, upper, q=q)


def lograndint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper, log=True)


def choice(categories: List[Any]) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable) -> Function:
    return Function(fn)


def randn(mean: float = 0.0, sd: float = 1.0) -> Function:
    return Function(lambda: random.gauss(mean, sd))


def grid_search(values: List[Any]) -> Dict[str, List[Any]]:
    """Marker dict, identical shape to the reference's
    (`tune/search/variant_generator.py` looks for {"grid_search": [...]})."""
    return {"grid_search": list(values)}


# ---------------------------------------------------------------------------
# Variant generation (reference: tune/search/variant_generator.py)
# ---------------------------------------------------------------------------


def _is_grid(value: Any) -> bool:
    return isinstance(value, dict) and set(value.keys()) == {"grid_search"}


def _walk(spec: Any, path=()):
    """Yield (path, leaf) for every leaf of a nested dict."""
    if isinstance(spec, dict) and not _is_grid(spec):
        for k, v in spec.items():
            yield from _walk(v, path + (k,))
    else:
        yield path, spec


def _set_path(spec: dict, path, value) -> None:
    node = spec
    for key in path[:-1]:
        node = node.setdefault(key, {})
    node[path[-1]] = value


def generate_variants(param_space: dict, num_samples: int,
                      seed: Optional[int] = None) -> Iterator[dict]:
    """Expand grid axes × num_samples random draws of the sample domains.

    Matches the reference's semantics: `num_samples` multiplies the grid
    (`basic_variant.py`: each sample iterates the full grid).
    """
    rng = random.Random(seed)
    leaves = list(_walk(param_space))
    grid_axes = [(p, v["grid_search"]) for p, v in leaves if _is_grid(v)]
    sample_leaves = [(p, v) for p, v in leaves if isinstance(v, Domain)]
    const_leaves = [(p, v) for p, v in leaves
                    if not _is_grid(v) and not isinstance(v, Domain)]

    grid_paths = [p for p, _ in grid_axes]
    grid_values = [vals for _, vals in grid_axes]
    for _ in range(num_samples):
        for combo in itertools.product(*grid_values) if grid_values else [()]:
            cfg: dict = {}
            for p, v in const_leaves:
                _set_path(cfg, p, v)
            for p, v in zip(grid_paths, combo):
                _set_path(cfg, p, v)
            for p, dom in sample_leaves:
                if isinstance(dom, Function):
                    _set_path(cfg, p, dom.sample(rng, cfg))
                else:
                    _set_path(cfg, p, dom.sample(rng))
            yield cfg


def count_variants(param_space: dict, num_samples: int) -> int:
    n = num_samples
    for _, v in _walk(param_space):
        if _is_grid(v):
            n *= len(v["grid_search"])
    return n


# ---------------------------------------------------------------------------
# Searcher interface (reference: tune/search/searcher.py)
# ---------------------------------------------------------------------------


class Searcher:
    """Suggest-based search algorithm. Subclass to plug in BO/TPE/etc."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max"):
        self.metric, self.mode = metric, mode

    def suggest(self, trial_id: str) -> Optional[dict]:
        """Next config, or None when the search space is exhausted."""
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        pass

    def on_trial_complete(self, trial_id: str, result: Optional[dict] = None,
                          error: bool = False) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid + random search (the reference's default searcher)."""

    def __init__(self, param_space: dict, num_samples: int = 1,
                 seed: Optional[int] = None):
        super().__init__()
        self._it = generate_variants(param_space, num_samples, seed)
        self.total = count_variants(param_space, num_samples)

    def suggest(self, trial_id: str) -> Optional[dict]:
        return next(self._it, None)


class ConcurrencyLimiter(Searcher):
    """Cap in-flight suggestions from a wrapped searcher
    (reference: tune/search/concurrency_limiter.py)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def suggest(self, trial_id: str) -> Optional[dict]:
        if len(self._live) >= self.max_concurrent:
            return None     # controller retries later
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None:
            self._live.add(trial_id)
        return cfg

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False) -> None:
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)
