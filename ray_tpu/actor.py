"""Actor classes and handles.

Counterpart of the reference's `python/ray/actor.py` (`ActorClass` :383,
`ActorHandle` :1024): `@remote` on a class yields an `ActorClass`;
`.remote(...)` spawns a dedicated worker process that constructs the
instance; the returned `ActorHandle` routes ordered method calls to it.
Handles pickle into tasks (reference: actor handle serialization in
`actor_handle.h`) and can be looked up by name via `get_actor`.
"""

from __future__ import annotations

import hashlib
import inspect

import cloudpickle

from ray_tpu._private import ids, protocol
from ray_tpu._private.constants import DEFAULT_ACTOR_LIFETIME_CPUS
from ray_tpu._private.worker import ObjectRef, get_client
from ray_tpu.exceptions import RayTpuError
from ray_tpu.remote_function import _encode_args, _resources_from_options


def method(**opts):
    """Decorator setting per-method options, e.g. @method(num_returns=2)
    (reference: ray.method, actor.py)."""
    def wrap(fn):
        fn.__ray_tpu_method_options__ = opts
        return fn
    return wrap


def _is_async_class(cls) -> bool:
    """An actor is ASYNC iff any of its methods is a coroutine function
    (reference: `_private/async_compat.py:19` has_async_methods) — its
    methods then run on a per-actor event loop instead of threads."""
    return any(inspect.iscoroutinefunction(fn)
               for _, fn in inspect.getmembers(cls, inspect.isfunction))


def _collect_method_meta(cls) -> dict:
    meta = {}
    for name, fn in inspect.getmembers(cls, inspect.isfunction):
        if name.startswith("__") and name != "__call__":
            continue
        opts = getattr(fn, "__ray_tpu_method_options__", {})
        meta[name] = {"num_returns": int(opts.get("num_returns", 1))}
    return meta


class ActorClass:
    def __init__(self, cls, options: dict | None = None):
        self._cls = cls
        self._options = dict(options or {})
        self._pickled: bytes | None = None
        self._function_id: str | None = None
        self.__name__ = getattr(cls, "__name__", "Actor")

    def _materialize(self):
        if self._pickled is None:
            self._pickled = cloudpickle.dumps(self._cls, protocol=5)
            self._function_id = ("cls_" +
                                 hashlib.sha1(self._pickled).hexdigest()[:16])
        return self._pickled, self._function_id

    def options(self, **opts) -> "ActorClass":
        new = ActorClass(self._cls, {**self._options, **opts})
        new._pickled, new._function_id = self._materialize()
        return new

    def __call__(self, *a, **kw):
        raise TypeError(
            f"actor class {self.__name__} cannot be instantiated directly; "
            f"use .remote()")

    def bind(self, *args, **kwargs):
        """Lazy DAG node: the actor is created at execute() time
        (reference: `dag/class_node.py`)."""
        from ray_tpu.dag import ClassNode
        return ClassNode(self, args, kwargs)

    def remote(self, *args, **kwargs) -> "ActorHandle":
        blob, function_id = self._materialize()
        o = self._options
        actor_id = ids.new_actor_id()
        task_id = ids.new_task_id()
        creation_return = ids.new_object_id()
        enc_args, enc_kwargs = _encode_args(args, kwargs)
        method_meta = _collect_method_meta(self._cls)
        pg_id = None
        strategy_enc = None
        strategy = o.get("scheduling_strategy")
        if strategy is not None and hasattr(strategy, "placement_group"):
            pg_id = strategy.placement_group.id
        elif strategy is not None:
            from ray_tpu.remote_function import encode_strategy
            strategy_enc = encode_strategy(strategy)
        spec = protocol.TaskSpec(
            task_id=task_id,
            function_id=function_id,
            function_blob=blob,
            function_desc=self.__name__ + ".__init__",
            args=enc_args,
            kwargs=enc_kwargs,
            num_returns=1,
            return_ids=[creation_return],
            resources=_resources_from_options(
                o, DEFAULT_ACTOR_LIFETIME_CPUS),
            actor_id=actor_id,
            actor_creation=True,
            runtime_env=o.get("runtime_env"),
            actor_options={
                # async actors (any `async def` method) default to high
                # concurrency — awaits overlap on one event loop, so
                # serial pumping would defeat their whole point
                # (reference: ray DEFAULT_MAX_CONCURRENCY_ASYNC=1000 vs 1
                # for threaded actors, actor.py)
                "max_concurrency": int(o.get(
                    "max_concurrency",
                    1000 if _is_async_class(self._cls) else 1)),
                "max_restarts": int(o.get("max_restarts", 0)),
                "max_task_retries": int(o.get("max_task_retries", 0)),
                "name": o.get("name"),
                "method_meta": method_meta,
            },
            scheduling_strategy=strategy_enc,
            placement_group_id=pg_id,
            name=o.get("name") or self.__name__,
        )
        get_client().submit(spec)
        return ActorHandle(actor_id, self.__name__, method_meta,
                           creation_return)


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns: int):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns

    def options(self, **opts) -> "ActorMethod":
        return ActorMethod(self._handle, self._name,
                           int(opts.get("num_returns", self._num_returns)))

    def remote(self, *args, **kwargs):
        h = self._handle
        task_id = ids.new_task_id()
        return_ids = [ids.new_object_id() for _ in range(self._num_returns)]
        enc_args, enc_kwargs = _encode_args(args, kwargs)
        spec = protocol.TaskSpec(
            task_id=task_id,
            function_id="method",
            function_blob=None,
            function_desc=f"{h._class_name}.{self._name}",
            args=enc_args,
            kwargs=enc_kwargs,
            num_returns=self._num_returns,
            return_ids=return_ids,
            actor_id=h._actor_id,
            method_name=self._name,
            name=f"{h._class_name}.{self._name}",
        )
        get_client().submit(spec)
        refs = [ObjectRef(oid) for oid in return_ids]
        return refs[0] if self._num_returns == 1 else refs


class ActorHandle:
    def __init__(self, actor_id: str, class_name: str, method_meta: dict,
                 creation_return: str | None = None):
        self._actor_id = actor_id
        self._class_name = class_name
        self._method_meta = method_meta
        self._creation_return = creation_return

    def __getattr__(self, name):
        meta = self._method_meta.get(name)
        if meta is None:
            raise AttributeError(
                f"actor {self._class_name} has no method {name!r}")
        return ActorMethod(self, name, meta["num_returns"])

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name,
                              self._method_meta, self._creation_return))

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id})"


def get_actor(name: str) -> ActorHandle:
    """Look up a named actor (reference: ray.get_actor, worker.py:2711)."""
    info = get_client().control("get_actor", name)
    if info is None:
        raise ValueError(f"no actor named {name!r}")
    return ActorHandle(info["actor_id"], name, info["method_meta"],
                       info["creation_return"])


def kill(actor: ActorHandle, *, no_restart: bool = True):
    """Forcibly terminate an actor process (reference: ray.kill,
    worker.py:2746)."""
    if not isinstance(actor, ActorHandle):
        raise TypeError("kill() expects an ActorHandle")
    get_client().control(
        "kill_actor", {"actor_id": actor._actor_id, "no_restart": no_restart})


def wait_for_actor_ready(actor: ActorHandle, timeout: float | None = None):
    """Block until the actor constructor has finished (internal utility)."""
    from ray_tpu._private import worker
    if actor._creation_return is None:
        raise RayTpuError("handle has no creation future")
    worker.get(ObjectRef(actor._creation_return), timeout=timeout)
