"""Mixture-of-Experts transformer, expert-parallel over the mesh.

Absent from the reference (SURVEY.md §2.4: no EP anywhere in Ray) — on
TPU expert parallelism is a sharding spec, so the framework ships it as a
first-class model family. Design (Mesh-TensorFlow / Switch formulation,
the one that maps onto MXU + ICI all-to-alls):

- Expert FFN weights carry a leading ``expert`` logical axis; sharding
  them over the mesh's ``expert`` axis makes XLA insert the dispatch/
  combine all-to-alls.
- Routing is dense one-hot dispatch/combine einsums with a fixed
  per-expert **capacity** (static shapes — no data-dependent gather, so
  the whole thing jits and tiles onto the MXU). Overflowing tokens are
  dropped by the mask, standard Switch behavior.
- Top-1 (Switch) or top-2 (GShard/Mixtral-style) routing with the
  load-balancing auxiliary loss from Shazeer et al.: mean(fraction of
  tokens * fraction of router probability) * n_experts.

Same conventions as models/gpt.py: stacked-layer pytree + lax.scan,
bfloat16 activations with f32 accumulation, logical axes for every param.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ray_tpu.models import gpt as gpt_mod
from ray_tpu.models.gpt import _attention, _rms_norm


@dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 50304
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 2048              # per-expert FFN width
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_coeff: float = 0.01
    max_seq_len: int = 1024
    dtype: str = "bfloat16"
    remat: bool = True
    attn_impl: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def activation_dtype(self):
        return jnp.dtype(self.dtype)


def small(**kw) -> MoEConfig:
    return MoEConfig(**{**dict(vocab_size=512, d_model=128, n_layers=2,
                               n_heads=4, d_ff=256, n_experts=4, top_k=2,
                               max_seq_len=128), **kw})


def param_logical_axes(cfg: MoEConfig):
    layer = {
        "ln1_scale": (None, "embed"),
        "ln2_scale": (None, "embed"),
        "wq": (None, "embed", "heads"),
        "wk": (None, "embed", "heads"),
        "wv": (None, "embed", "heads"),
        "wo": (None, "heads", "embed"),
        "router": (None, "embed", "expert"),
        "w_up": (None, "expert", "embed", "mlp"),
        "w_gate": (None, "expert", "embed", "mlp"),
        "w_down": (None, "expert", "mlp", "embed"),
    }
    return {
        "embed": ("vocab", "embed"),
        "pos_embed": (None, "embed"),
        "final_ln_scale": ("embed",),
        "layers": layer,
    }


def init_params(rng, cfg: MoEConfig):
    k_emb, k_pos, k_layers = jax.random.split(rng, 3)
    d = cfg.d_model
    h = cfg.n_heads * cfg.head_dim
    f, E, L = cfg.d_ff, cfg.n_experts, cfg.n_layers

    def norm(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (1.0 / np.sqrt(fan_in)))

    ks = jax.random.split(k_layers, 8)
    layers = {
        "ln1_scale": jnp.ones((L, d), jnp.float32),
        "ln2_scale": jnp.ones((L, d), jnp.float32),
        "wq": norm(ks[0], (L, d, h), d),
        "wk": norm(ks[1], (L, d, h), d),
        "wv": norm(ks[2], (L, d, h), d),
        "wo": norm(ks[3], (L, h, d), h) / np.sqrt(2 * L),
        "router": norm(ks[4], (L, d, E), d) * 0.1,
        "w_up": norm(ks[5], (L, E, d, f), d),
        "w_gate": norm(ks[6], (L, E, d, f), d),
        "w_down": norm(ks[7], (L, E, f, d), f) / np.sqrt(2 * L),
    }
    return {
        "embed": norm(k_emb, (cfg.vocab_size, d), 1.0) * 0.02,
        "pos_embed": norm(k_pos, (cfg.max_seq_len, d), 1.0) * 0.01,
        "final_ln_scale": jnp.ones((d,), jnp.float32),
        "layers": layers,
    }


# ---------------------------------------------------------------------------
# routing + expert FFN
# ---------------------------------------------------------------------------

def _route(h, router_w, cfg: MoEConfig):
    """-> (dispatch [N, E, C] one-hot-ish mask, combine [N, E, C] weights,
    aux load-balance loss). N = B*T flattened tokens."""
    n = h.shape[0]
    E, K = cfg.n_experts, cfg.top_k
    capacity = max(1, int(cfg.capacity_factor * K * n / E))

    logits = jnp.einsum("nd,de->ne", h.astype(jnp.float32),
                        router_w.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)                  # [N, E]

    dispatch = jnp.zeros((n, E, capacity), jnp.float32)
    combine = jnp.zeros((n, E, capacity), jnp.float32)
    # running per-expert fill count, updated after each of the K choices
    fill = jnp.zeros((E,), jnp.float32)
    masked = probs
    top1_assign = None
    for k in range(K):
        idx = jnp.argmax(masked, axis=-1)                    # [N]
        onehot = jax.nn.one_hot(idx, E)                      # [N, E]
        if top1_assign is None:
            top1_assign = onehot
        gate = jnp.sum(probs * onehot, axis=-1)              # [N]
        # position of each token within its chosen expert's buffer
        pos = jnp.cumsum(onehot, axis=0) - onehot + fill[None]   # [N, E]
        pos_tok = jnp.sum(pos * onehot, axis=-1)             # [N]
        keep = pos_tok < capacity
        pos_oh = jax.nn.one_hot(pos_tok.astype(jnp.int32), capacity)
        contrib = (onehot[:, :, None] * pos_oh[:, None, :]
                   * keep[:, None, None])
        dispatch = dispatch + contrib
        combine = combine + contrib * gate[:, None, None]
        fill = fill + jnp.sum(onehot * keep[:, None], axis=0)
        masked = masked * (1.0 - onehot)                     # next choice

    # Shazeer load-balance aux: E * mean_e(frac_tokens_e * frac_prob_e),
    # on the top-1 assignment
    frac_tokens = jnp.mean(top1_assign, axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = cfg.n_experts * jnp.sum(frac_tokens * frac_probs)
    # renormalize combine weights over the K picks (Mixtral-style)
    denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
    combine = combine / jnp.maximum(denom, 1e-9)
    return dispatch, combine, aux


def _moe_ffn(x, lp, cfg: MoEConfig):
    """x: [B, T, D] -> (out [B, T, D], aux loss). Dense dispatch/combine
    einsums; expert dim `e` is the sharded axis."""
    adt = cfg.activation_dtype()
    b, t, d = x.shape
    h = x.reshape(b * t, d)
    dispatch, combine, aux = _route(h, lp["router"], cfg)
    # tokens -> expert buffers [E, C, D]
    xs = jnp.einsum("nec,nd->ecd", dispatch.astype(adt), h,
                    preferred_element_type=jnp.float32).astype(adt)
    up = jnp.einsum("ecd,edf->ecf", xs, lp["w_up"].astype(adt),
                    preferred_element_type=jnp.float32).astype(adt)
    gate = jnp.einsum("ecd,edf->ecf", xs, lp["w_gate"].astype(adt),
                      preferred_element_type=jnp.float32).astype(adt)
    act = jax.nn.silu(gate) * up
    down = jnp.einsum("ecf,efd->ecd", act, lp["w_down"].astype(adt),
                      preferred_element_type=jnp.float32).astype(adt)
    out = jnp.einsum("nec,ecd->nd", combine.astype(adt), down,
                     preferred_element_type=jnp.float32).astype(adt)
    return out.reshape(b, t, d), aux


def _block(x, lp, cfg: MoEConfig, mesh: Mesh | None):
    adt = cfg.activation_dtype()
    b, t, d = x.shape
    nh, hd = cfg.n_heads, cfg.head_dim

    h = _rms_norm(x, lp["ln1_scale"].astype(adt))
    q = jnp.einsum("btd,dh->bth", h, lp["wq"].astype(adt),
                   preferred_element_type=jnp.float32).astype(adt)
    k = jnp.einsum("btd,dh->bth", h, lp["wk"].astype(adt),
                   preferred_element_type=jnp.float32).astype(adt)
    v = jnp.einsum("btd,dh->bth", h, lp["wv"].astype(adt),
                   preferred_element_type=jnp.float32).astype(adt)
    gpt_cfg = gpt_mod.GPTConfig(
        d_model=cfg.d_model, n_heads=cfg.n_heads, dtype=cfg.dtype,
        attn_impl=cfg.attn_impl)
    att = _attention(q.reshape(b, t, nh, hd), k.reshape(b, t, nh, hd),
                     v.reshape(b, t, nh, hd), gpt_cfg,
                     mesh).reshape(b, t, nh * hd)
    att = jnp.einsum("bth,hd->btd", att, lp["wo"].astype(adt),
                     preferred_element_type=jnp.float32).astype(adt)
    x = x + att

    h = _rms_norm(x, lp["ln2_scale"].astype(adt))
    ff, aux = _moe_ffn(h, lp, cfg)
    return x + ff, aux


def forward(params, tokens, cfg: MoEConfig, mesh: Mesh | None = None):
    """tokens [B, T] -> (logits [B, T, vocab] f32, aux loss scalar)."""
    adt = cfg.activation_dtype()
    t = tokens.shape[1]
    x = params["embed"].astype(adt)[tokens]
    x = x + params["pos_embed"].astype(adt)[:t][None]

    block = partial(_block, cfg=cfg, mesh=mesh)
    if cfg.remat:
        block = jax.checkpoint(block)

    def scan_body(carry, lp):
        x, aux_sum = carry
        x, aux = block(x, lp)
        return (x, aux_sum + aux), None

    (x, aux_sum), _ = jax.lax.scan(
        scan_body, (x, jnp.zeros((), jnp.float32)), params["layers"])
    x = _rms_norm(x, params["final_ln_scale"].astype(adt))
    logits = jnp.einsum("btd,vd->btv", x, params["embed"].astype(adt),
                        preferred_element_type=jnp.float32)
    return logits, aux_sum / cfg.n_layers


def loss_fn(params, batch, cfg: MoEConfig, mesh: Mesh | None = None):
    tokens = batch["tokens"]
    logits, aux = forward(params, tokens[:, :-1], cfg, mesh)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll) + cfg.aux_loss_coeff * aux


def num_params(params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
