"""ResNet in flax.linen — benchmark workhorse.

Counterpart workload of the reference's MLPerf-style ResNet-50 Train
benchmark (`release/air_tests/air_benchmarks/mlperf-train/
resnet50_ray_air.py:199-201`) and the BASELINE.md milestone config
"ResNet-18 CIFAR-10 (2 workers, DP, CPU-runnable)". Written TPU-first:
NHWC layout (TPU conv-native), bfloat16 compute / float32 params & BN
statistics.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class ResNetBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: str = "bfloat16"

    @nn.compact
    def __call__(self, x, train: bool = True):
        dt = jnp.dtype(self.dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=dt,
                       param_dtype=jnp.float32)
        bn = partial(nn.BatchNorm, use_running_average=not train,
                     momentum=0.9, dtype=dt, param_dtype=jnp.float32)
        residual = x
        y = conv(self.filters, (3, 3), (self.strides, self.strides))(x)
        y = bn()(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3))(y)
        y = bn(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1),
                            (self.strides, self.strides))(residual)
            residual = bn()(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: str = "bfloat16"

    @nn.compact
    def __call__(self, x, train: bool = True):
        dt = jnp.dtype(self.dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=dt,
                       param_dtype=jnp.float32)
        bn = partial(nn.BatchNorm, use_running_average=not train,
                     momentum=0.9, dtype=dt, param_dtype=jnp.float32)
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = nn.relu(bn()(y))
        y = conv(self.filters, (3, 3), (self.strides, self.strides))(y)
        y = nn.relu(bn()(y))
        y = conv(self.filters * 4, (1, 1))(y)
        y = bn(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            (self.strides, self.strides))(residual)
            residual = bn()(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: type = ResNetBlock
    num_classes: int = 10
    num_filters: int = 64
    dtype: str = "bfloat16"
    small_inputs: bool = True   # CIFAR stem (3x3, no maxpool)

    @nn.compact
    def __call__(self, x, train: bool = True):
        dt = jnp.dtype(self.dtype)
        x = x.astype(dt)
        if self.small_inputs:
            x = nn.Conv(self.num_filters, (3, 3), use_bias=False, dtype=dt,
                        param_dtype=jnp.float32)(x)
        else:
            x = nn.Conv(self.num_filters, (7, 7), (2, 2), use_bias=False,
                        dtype=dt, param_dtype=jnp.float32)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=dt, param_dtype=jnp.float32)(x)
        x = nn.relu(x)
        if not self.small_inputs:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(self.num_filters * 2 ** i,
                                   strides=strides, dtype=self.dtype)(
                                       x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32)(x)
        return x


def resnet18(num_classes: int = 10, **kw) -> ResNet:
    return ResNet(stage_sizes=[2, 2, 2, 2], block_cls=ResNetBlock,
                  num_classes=num_classes, **kw)


def resnet50(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock,
                  num_classes=num_classes, small_inputs=False, **kw)
