"""Vision Transformer (ViT) classifier, TPU-first.

Third model family next to GPT (language) and ResNet (conv vision):
patchify → linear embed → pre-norm transformer encoder (bidirectional
attention) → mean-pool → linear head. Same conventions as models/gpt.py:
stacked-layer pytree + lax.scan, bf16 activations / f32 accumulation,
logical sharding axes so DP/FSDP/TP come from the MeshSpec. Counterpart
of the reference release benchmarks' vision workloads
(`release/air_tests/air_benchmarks/mlperf-train/resnet50_ray_air.py`).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ray_tpu.models.gpt import _rms_norm


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    channels: int = 3
    dtype: str = "bfloat16"
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.channels * self.patch_size ** 2

    def activation_dtype(self):
        return jnp.dtype(self.dtype)


def small(**kw) -> ViTConfig:
    return ViTConfig(**{**dict(image_size=32, patch_size=4, num_classes=10,
                               d_model=128, n_layers=2, n_heads=4,
                               d_ff=256), **kw})


def param_logical_axes(cfg: ViTConfig):
    layer = {
        "ln1_scale": (None, "embed"),
        "ln2_scale": (None, "embed"),
        "wq": (None, "embed", "heads"),
        "wk": (None, "embed", "heads"),
        "wv": (None, "embed", "heads"),
        "wo": (None, "heads", "embed"),
        "w_up": (None, "embed", "mlp"),
        "w_down": (None, "mlp", "embed"),
    }
    return {
        "patch_embed": (None, "embed"),
        "pos_embed": (None, "embed"),
        "final_ln_scale": ("embed",),
        "head": ("embed", None),
        "head_bias": (None,),
        "layers": layer,
    }


def init_params(rng, cfg: ViTConfig):
    k_patch, k_pos, k_head, k_layers = jax.random.split(rng, 4)
    d = cfg.d_model
    h = cfg.n_heads * cfg.head_dim
    f, L = cfg.d_ff, cfg.n_layers

    def norm(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (1.0 / np.sqrt(fan_in)))

    ks = jax.random.split(k_layers, 6)
    layers = {
        "ln1_scale": jnp.ones((L, d), jnp.float32),
        "ln2_scale": jnp.ones((L, d), jnp.float32),
        "wq": norm(ks[0], (L, d, h), d),
        "wk": norm(ks[1], (L, d, h), d),
        "wv": norm(ks[2], (L, d, h), d),
        "wo": norm(ks[3], (L, h, d), h) / np.sqrt(2 * L),
        "w_up": norm(ks[4], (L, d, f), d),
        "w_down": norm(ks[5], (L, f, d), f) / np.sqrt(2 * L),
    }
    return {
        "patch_embed": norm(k_patch, (cfg.patch_dim, d), cfg.patch_dim),
        "pos_embed": norm(k_pos, (cfg.num_patches, d), 1.0) * 0.02,
        "final_ln_scale": jnp.ones((d,), jnp.float32),
        "head": norm(k_head, (d, cfg.num_classes), d),
        "head_bias": jnp.zeros((cfg.num_classes,), jnp.float32),
        "layers": layers,
    }


def _patchify(images, cfg: ViTConfig):
    """[B, H, W, C] -> [B, N, patch_dim]."""
    b, hgt, wid, c = images.shape
    p = cfg.patch_size
    x = images.reshape(b, hgt // p, p, wid // p, p, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, (hgt // p) * (wid // p), p * p * c)


def _block(x, lp, cfg: ViTConfig):
    adt = cfg.activation_dtype()
    b, t, d = x.shape
    nh, hd = cfg.n_heads, cfg.head_dim

    h = _rms_norm(x, lp["ln1_scale"].astype(adt))
    q = jnp.einsum("btd,dh->bth", h, lp["wq"].astype(adt),
                   preferred_element_type=jnp.float32).astype(adt)
    k = jnp.einsum("btd,dh->bth", h, lp["wk"].astype(adt),
                   preferred_element_type=jnp.float32).astype(adt)
    v = jnp.einsum("btd,dh->bth", h, lp["wv"].astype(adt),
                   preferred_element_type=jnp.float32).astype(adt)
    q = q.reshape(b, t, nh, hd)
    k = k.reshape(b, t, nh, hd)
    v = v.reshape(b, t, nh, hd)
    # bidirectional attention — XLA fuses this softmax chain well at ViT
    # sequence lengths (<= ~1k patches), no flash kernel needed
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    probs = jax.nn.softmax(scores / np.sqrt(hd), axis=-1).astype(adt)
    att = jnp.einsum("bhqk,bkhd->bqhd", probs, v,
                     preferred_element_type=jnp.float32).astype(adt)
    att = att.reshape(b, t, nh * hd)
    att = jnp.einsum("bth,hd->btd", att, lp["wo"].astype(adt),
                     preferred_element_type=jnp.float32).astype(adt)
    x = x + att

    h = _rms_norm(x, lp["ln2_scale"].astype(adt))
    up = jnp.einsum("btd,df->btf", h, lp["w_up"].astype(adt),
                    preferred_element_type=jnp.float32).astype(adt)
    ff = jax.nn.gelu(up)
    down = jnp.einsum("btf,fd->btd", ff, lp["w_down"].astype(adt),
                      preferred_element_type=jnp.float32).astype(adt)
    return x + down


def forward(params, images, cfg: ViTConfig, mesh: Mesh | None = None):
    """images [B, H, W, C] float -> logits [B, num_classes] f32."""
    adt = cfg.activation_dtype()
    patches = _patchify(images.astype(adt), cfg)
    x = jnp.einsum("bnp,pd->bnd", patches, params["patch_embed"].astype(adt),
                   preferred_element_type=jnp.float32).astype(adt)
    x = x + params["pos_embed"].astype(adt)[None]

    block = partial(_block, cfg=cfg)
    if cfg.remat:
        block = jax.checkpoint(block)

    def scan_body(x, lp):
        return block(x, lp), None

    x, _ = jax.lax.scan(scan_body, x, params["layers"])
    x = _rms_norm(x, params["final_ln_scale"].astype(adt))
    pooled = jnp.mean(x.astype(jnp.float32), axis=1)
    return pooled @ params["head"] + params["head_bias"]


def loss_fn(params, batch, cfg: ViTConfig, mesh: Mesh | None = None):
    """Softmax cross entropy. batch: {"images": [B,H,W,C],
    "labels": [B]}."""
    logits = forward(params, batch["images"], cfg, mesh)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, batch["labels"][:, None], axis=-1)[:, 0]
    return -jnp.mean(ll)


def num_params(params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
