"""Flagship model: GPT-style decoder-only transformer, TPU-first.

Design choices that matter on TPU:

- **bfloat16 activations, float32 params/optimizer** — MXU-native compute
  with stable accumulation (einsums accumulate in f32 via
  ``preferred_element_type``).
- **One stacked layer pytree + ``lax.scan``** over layers: compile time is
  O(1) in depth and XLA pipelines the loop body.
- **Logical sharding axes on every parameter** (`ray_tpu.parallel.sharding`
  vocabulary): the same definition runs 1-chip, DP, FSDP, TP (megatron
  column/row split), and SP (ring attention over the ``seq`` axis) purely by
  changing the MeshSpec.
- **`jax.checkpoint` on the block** to trade FLOPs for HBM.

The reference has no model zoo of its own (models live in user code /
RLlib's catalog, `rllib/models/catalog.py`); this model is the framework's
train/serve/bench workhorse, counterpart of the reference release
benchmarks' ResNet/GPT-2 workloads (`release/air_tests/air_benchmarks/`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import Mesh

from ray_tpu.parallel.ring_attention import reference_attention, ring_attention


@dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304        # multiple of 128 for MXU-friendly vocab
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_seq_len: int = 1024
    dtype: str = "bfloat16"
    remat: bool = True
    # What the layer-scan checkpoint saves for backward:
    #   "nothing"  - recompute the whole block (min HBM, max recompute)
    #   "dots"     - save matmul/attention outputs, recompute elementwise
    #                (jax.checkpoint_policies.checkpoint_dots_with_no_
    #                batch_dims; bwd skips re-running the big einsums)
    #   "attn_out" - save only the attention-kernel outputs
    remat_policy: str = "nothing"
    attn_impl: str = "auto"        # auto | ring | flash | xla
    # Output dtype of the block einsums. MXU accumulation is f32 either
    # way; materializing f32 OUTPUTS doubles activation HBM writes, so
    # "activation" (= cfg.dtype, bf16) is the fast path. The logits
    # matmul always emits f32 (softmax stability).
    matmul_out: str = "activation"  # activation | float32
    # Unembed output dtype. float32 is the safe default (softmax
    # stability over a 50k vocab); bfloat16 halves the HBM traffic of
    # the single biggest activation tensor — the loss upcasts to f32
    # before logsumexp either way.
    logits_dtype: str = "float32"   # float32 | bfloat16
    # Cross-entropy implementation (validated at trace time, like
    # remat_policy):
    #   "dense" - materialize [B, T, vocab] logits, then softmax-xent.
    #   "fused" - ops/fused_xent.py streams the unembed matmul in vocab
    #             chunks with an online logsumexp (forward AND backward
    #             recompute per-chunk logits), so the loss's peak live
    #             activation is O(B*T*chunk) instead of O(B*T*vocab).
    #             At bench shape the dense logits tensor is 1.6 GB f32 —
    #             the single biggest array in the step and what capped
    #             batch size at 16. Accumulation is f32 either way;
    #             fused vs dense agrees to ~1e-6 with f32 logits.
    loss_impl: str = "dense"        # dense | fused
    # Vocab rows per online-softmax step of the fused loss (also its
    # preferred Pallas vocab block). The loss's transient logits block
    # is [B, T, loss_chunk]; smaller chunks mean less live memory and
    # more loop steps.
    loss_chunk: int = 512
    # Attention implementation for single-token decode over the KV cache
    # (decode_step). "auto" picks the Pallas decode kernel on TPU and the
    # pure-JAX fallback elsewhere; both share the same math
    # (ops/decode_attention.py).
    decode_attn_impl: str = "auto"   # auto | pallas | jax
    # Paged KV pool element type. "f32" keeps the pool in the activation
    # dtype (full precision — the bitwise-default path); "int8" stores
    # symmetric absmax int8 payloads with one f32 scale per
    # (position, head) row (ops/quant.py), quantized at write inside
    # prefill/decode/verify and dequantized inside the paged attention
    # kernels — the block table / COW / radix machinery never sees the
    # dtype. ~3-4x KV bytes/token vs an f32 pool (2x vs bf16).
    kv_dtype: str = "f32"            # f32 | int8
    # Weight precision for the paged inference forwards (prefill/decode/
    # verify — training and the unpaged path always run full precision).
    # "int8" expects params through `quantize_params` (per-output-channel
    # scales; dequant folds into each matmul's rhs read, accumulation
    # stays f32 via preferred_element_type).
    weight_dtype: str = "f32"        # f32 | int8
    # Attention implementation for chunked paged prefill. "auto" picks
    # the fused Pallas multi-query kernel on TPU (chunk scores stay
    # blockwise in VMEM) and the dense gather+einsum elsewhere; "jax" is
    # the legacy dense math, bit-identical to the pre-fused inline path.
    prefill_attn_impl: str = "auto"  # auto | pallas | jax

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def activation_dtype(self):
        return jnp.dtype(self.dtype)


def small(**kw) -> GPTConfig:
    return GPTConfig(**{**dict(vocab_size=512, d_model=128, n_layers=2,
                               n_heads=4, d_ff=512, max_seq_len=128), **kw})


# ---------------------------------------------------------------------------
# parameters
# ---------------------------------------------------------------------------

def param_logical_axes(cfg: GPTConfig):
    """Pytree of logical-axis tuples, mirroring init_params' structure.
    Leading layer-stack axis is unsharded (None)."""
    layer = {
        "ln1_scale": (None, "embed"),
        "ln2_scale": (None, "embed"),
        "wq": (None, "embed", "heads"),
        "wk": (None, "embed", "heads"),
        "wv": (None, "embed", "heads"),
        "wo": (None, "heads", "embed"),
        "w_up": (None, "embed", "mlp"),
        "w_gate": (None, "embed", "mlp"),
        "w_down": (None, "mlp", "embed"),
    }
    return {
        "embed": ("vocab", "embed"),
        "pos_embed": (None, "embed"),
        "final_ln_scale": ("embed",),
        "layers": layer,
    }


def init_params(rng, cfg: GPTConfig):
    """float32 master params; cast to cfg.dtype at use sites."""
    k_emb, k_pos, k_layers = jax.random.split(rng, 3)
    d, h, f, L = cfg.d_model, cfg.n_heads * cfg.head_dim, cfg.d_ff, cfg.n_layers

    def norm(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (1.0 / np.sqrt(fan_in)))

    ks = jax.random.split(k_layers, 7)
    layers = {
        "ln1_scale": jnp.ones((L, d), jnp.float32),
        "ln2_scale": jnp.ones((L, d), jnp.float32),
        "wq": norm(ks[0], (L, d, h), d),
        "wk": norm(ks[1], (L, d, h), d),
        "wv": norm(ks[2], (L, d, h), d),
        "wo": norm(ks[3], (L, h, d), h) / np.sqrt(2 * L),
        "w_up": norm(ks[4], (L, d, f), d),
        "w_gate": norm(ks[5], (L, d, f), d),
        "w_down": norm(ks[6], (L, f, d), f) / np.sqrt(2 * L),
    }
    return {
        "embed": norm(k_emb, (cfg.vocab_size, d), 1.0) * 0.02,
        "pos_embed": norm(k_pos, (cfg.max_seq_len, d), 1.0) * 0.01,
        "final_ln_scale": jnp.ones((d,), jnp.float32),
        "layers": layers,
    }


def _rms_norm(x, scale):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * scale


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _attention(q, k, v, cfg: GPTConfig, mesh: Mesh | None):
    impl = cfg.attn_impl
    if impl == "auto":
        if mesh is not None and mesh.shape.get("seq", 1) > 1:
            impl = "ring"
        else:
            impl = "flash"
    if impl == "ring":
        out = ring_attention(q, k, v, mesh, causal=True)
    elif impl == "flash":
        from ray_tpu.ops.flash_attention import flash_attention
        out = flash_attention(q, k, v, causal=True)
    else:
        out = reference_attention(q, k, v, causal=True)
    # Named for the remat policy: saving attention outputs means the bwd
    # pass re-runs only cheap matmuls/norms, never the attention kernel.
    return checkpoint_name(out, "attn_out")


def _block(x, lp, cfg: GPTConfig, mesh: Mesh | None, with_kv: bool = False):
    """One transformer block. x: [B, T, D] activations in cfg.dtype;
    lp: this layer's param slice (f32, cast here). With ``with_kv`` the
    block also returns this layer's (k, v) [B, T, H, Dh] — exactly what a
    KV cache stores — so prefill reuses the training forward verbatim."""
    adt = cfg.activation_dtype()
    pet = (jnp.float32 if cfg.matmul_out == "float32" else adt)
    b, t, d = x.shape
    nh, hd = cfg.n_heads, cfg.head_dim

    h = _rms_norm(x, lp["ln1_scale"].astype(adt))
    q = jnp.einsum("btd,dh->bth", h, lp["wq"].astype(adt),
                   preferred_element_type=pet).astype(adt)
    k = jnp.einsum("btd,dh->bth", h, lp["wk"].astype(adt),
                   preferred_element_type=pet).astype(adt)
    v = jnp.einsum("btd,dh->bth", h, lp["wv"].astype(adt),
                   preferred_element_type=pet).astype(adt)
    q = q.reshape(b, t, nh, hd)
    k = k.reshape(b, t, nh, hd)
    v = v.reshape(b, t, nh, hd)
    att = _attention(q, k, v, cfg, mesh).reshape(b, t, nh * hd)
    att = jnp.einsum("bth,hd->btd", att, lp["wo"].astype(adt),
                     preferred_element_type=pet).astype(adt)
    x = x + att

    h = _rms_norm(x, lp["ln2_scale"].astype(adt))
    up = jnp.einsum("btd,df->btf", h, lp["w_up"].astype(adt),
                    preferred_element_type=pet).astype(adt)
    gate = jnp.einsum("btd,df->btf", h, lp["w_gate"].astype(adt),
                      preferred_element_type=pet).astype(adt)
    ff = jax.nn.silu(gate) * up
    down = jnp.einsum("btf,fd->btd", ff, lp["w_down"].astype(adt),
                      preferred_element_type=pet).astype(adt)
    if with_kv:
        return x + down, (k, v)
    return x + down


def forward_features(params, tokens, cfg: GPTConfig,
                     mesh: Mesh | None = None, *, with_kv: bool = False):
    """tokens [B, T] int32 -> final-norm activations [B, T, d_model] in
    cfg.dtype — everything except the unembed matmul. The fused loss
    consumes these directly so [B, T, vocab] logits never exist.

    With ``with_kv`` (the prefill path) additionally returns the
    per-layer attention keys/values stacked over layers:
    ``(x, (k [L, B, T, H, Dh], v [L, B, T, H, Dh]))`` — the scan's ys
    stacking produces the KV-cache layout directly. No remat is applied
    in this mode (prefill has no backward pass to save memory for)."""
    adt = cfg.activation_dtype()
    t = tokens.shape[1]
    x = params["embed"].astype(adt)[tokens]
    x = x + params["pos_embed"].astype(adt)[:t][None]

    block = partial(_block, cfg=cfg, mesh=mesh)
    if with_kv:
        def scan_body_kv(x, lp):
            return block(x, lp, with_kv=True)

        x, kv = jax.lax.scan(scan_body_kv, x, params["layers"])
        return _rms_norm(x, params["final_ln_scale"].astype(adt)), kv
    if cfg.remat:
        # Measured on v5e (B=16, T=1024 bench shape): save-nothing beats
        # save_only_these_names("attn_out") and no remat — the recomputed
        # forward overlaps with backward HBM traffic, so saving
        # activations often only adds bandwidth. remat_policy exposes the
        # alternatives for shapes where recompute dominates instead.
        policy = None
        if cfg.remat_policy == "dots":
            policy = jax.checkpoint_policies \
                .checkpoint_dots_with_no_batch_dims
        elif cfg.remat_policy == "attn_out":
            policy = jax.checkpoint_policies.save_only_these_names(
                "attn_out")
        elif cfg.remat_policy != "nothing":
            raise ValueError(
                f"unknown remat_policy {cfg.remat_policy!r} "
                "(expected 'nothing' | 'dots' | 'attn_out')")
        block = jax.checkpoint(block, policy=policy)

    def scan_body(x, lp):
        return block(x, lp), None

    x, _ = jax.lax.scan(scan_body, x, params["layers"])
    return _rms_norm(x, params["final_ln_scale"].astype(adt))


def forward(params, tokens, cfg: GPTConfig, mesh: Mesh | None = None):
    """tokens [B, T] int32 -> logits [B, T, vocab] in cfg.logits_dtype
    (float32 by default)."""
    adt = cfg.activation_dtype()
    x = forward_features(params, tokens, cfg, mesh)
    logits = jnp.einsum("btd,vd->btv", x, params["embed"].astype(adt),
                        preferred_element_type=jnp.dtype(cfg.logits_dtype))
    return logits


def check_loss_impl(cfg: GPTConfig) -> str:
    """Trace-time validation of the loss_impl knob (remat_policy idiom:
    a typo'd config fails the first trace, not some later step)."""
    if cfg.loss_impl not in ("dense", "fused"):
        raise ValueError(
            f"unknown loss_impl {cfg.loss_impl!r} "
            "(expected 'dense' | 'fused')")
    return cfg.loss_impl


def loss_fn(params, batch, cfg: GPTConfig, mesh: Mesh | None = None):
    """Next-token cross entropy. batch: {"tokens": [B, T]} — token t
    predicts token t+1."""
    tokens = batch["tokens"]
    targets = tokens[:, 1:]
    if check_loss_impl(cfg) == "fused":
        from ray_tpu.ops.fused_xent import fused_softmax_xent
        x = forward_features(params, tokens[:, :-1], cfg, mesh)
        nll = fused_softmax_xent(
            x, params["embed"].astype(cfg.activation_dtype()), targets,
            vocab_chunk=cfg.loss_chunk, mesh=mesh)
        return jnp.mean(nll)
    logits = forward(params, tokens[:, :-1], cfg, mesh)
    # upcast before the softmax so logits_dtype="bfloat16" configs keep
    # an f32 logsumexp (same guard as spmd.softmax_xent)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def completion_logprobs(params, tokens, start, width, cfg: GPTConfig,
                        mesh: Mesh | None = None):
    """Per-token natural log-likelihoods of a completion region — the
    DIFFERENTIABLE counterpart of the inference engine's emitted
    ``TokenEvent.logprob`` (one full forward instead of the KV-cache
    path; same f32 log_softmax math, so the two agree to f32 tolerance).

    tokens [B, T] int32: full padded sequences (prompt + completion).
    start [B] int32: index of each row's first completion token (>= 1).
    width (static int): completion window; returns [B, width] f32 where
    out[b, j] = log p(tokens[b, start[b]+j] | tokens[b, :start[b]+j]).
    Positions past a row's real sequence are scored against padding —
    the caller masks them (ragged lengths stay static-shaped).
    Gradients flow to params; RL losses build ratios/REINFORCE terms on
    top of this.
    """
    logits = forward(params, tokens, cfg, mesh)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    t = tokens.shape[1]
    start = jnp.asarray(start, jnp.int32)
    # Absolute position of completion token j, clipped into range so
    # padded tails index safely (caller masks them out).
    idx = jnp.clip(start[:, None]
                   + jnp.arange(width, dtype=jnp.int32)[None, :],
                   1, t - 1)                                  # [B, W]
    rows = jnp.take_along_axis(
        logp, (idx - 1)[..., None], axis=1)                   # [B, W, V]
    toks = jnp.take_along_axis(tokens, idx, axis=1)           # [B, W]
    return jnp.take_along_axis(rows, toks[..., None],
                               axis=-1)[..., 0]


# ---------------------------------------------------------------------------
# autoregressive inference: KV cache, prefill, single-token decode
# ---------------------------------------------------------------------------
# The Podracer recipe (Hessel et al., 2104.06272) applied to serving: device
# shapes are static and resident. The cache is allocated ONCE at
# [L, slots, max_len, H, Dh]; sequences stream through fixed slots
# (serve/engine.py), so prefill compiles once per length bucket and
# decode_step compiles exactly once for the engine's lifetime.

def kv_cache_logical_axes():
    """Logical-axis tuples for the KV cache pytree (layer stack and cache
    length replicated; batch over the data axes, heads tensor-parallel —
    matching the wq/wk/wv column split, so each tensor shard owns its own
    heads' cache rows)."""
    axes = (None, "batch", None, "heads", None)
    return {"k": axes, "v": axes}


def init_kv_cache(cfg: GPTConfig, batch: int, max_len: int,
                  mesh: Mesh | None = None):
    """Preallocated ring cache {"k", "v"} of [L, batch, max_len, H, Dh]
    in cfg.dtype, zero-filled, placed with its sharding annotation when a
    mesh is given. `batch` is the number of resident decode slots, NOT a
    per-request batch — the engine multiplexes requests into it."""
    if max_len > cfg.max_seq_len:
        raise ValueError(
            f"max_len {max_len} exceeds cfg.max_seq_len "
            f"{cfg.max_seq_len} (pos_embed table size)")
    shape = (cfg.n_layers, batch, max_len, cfg.n_heads, cfg.head_dim)
    cache = {"k": jnp.zeros(shape, cfg.activation_dtype()),
             "v": jnp.zeros(shape, cfg.activation_dtype())}
    if mesh is not None:
        from ray_tpu.parallel.sharding import kv_cache_shardings
        sh = kv_cache_shardings(mesh)
        cache = {name: jax.device_put(arr, sh[name])
                 for name, arr in cache.items()}
    return cache


def prefill(params, tokens, cache, cfg: GPTConfig,
            mesh: Mesh | None = None, *, lengths=None, slot=None):
    """Process prompt tokens in one full-sequence forward, write their
    K/V into the cache, and return ``(last_logits [B, vocab] f32,
    cache)`` — the [B, T, vocab] logits tensor is never materialized
    (only the last/`lengths-1` position is unembedded).

    tokens: [B, T] int32, right-padded to the bucket length. `lengths`
    [B] gives each row's true prompt length (defaults to T); under causal
    attention right-padding cannot influence positions < length, and the
    pad garbage written to the cache tail is masked away by decode's
    position mask.

    `slot` (traced scalar ok): tokens must then be [1, T] and the
    sequence lands in cache row `slot` — the continuous-batching
    admission path, which therefore never retraces per slot. Without
    `slot`, tokens rows map 1:1 onto cache rows."""
    b, t = tokens.shape
    cache_b = cache["k"].shape[1]
    if slot is None and b != cache_b:
        raise ValueError(
            f"prefill batch {b} != cache slots {cache_b}; pass slot= to "
            "target one slot")
    if slot is not None and b != 1:
        raise ValueError(f"slot-targeted prefill wants tokens [1, T], "
                         f"got batch {b}")
    if t > cache["k"].shape[2]:
        raise ValueError(
            f"prompt length {t} exceeds cache max_len "
            f"{cache['k'].shape[2]}")
    x, (ks, vs) = forward_features(params, tokens, cfg, mesh,
                                   with_kv=True)
    if lengths is None:
        last = x[:, -1]
    else:
        last = jnp.take_along_axis(
            x, (lengths.astype(jnp.int32) - 1)[:, None, None], axis=1
        )[:, 0]
    logits = jnp.einsum(
        "bd,vd->bv", last, params["embed"].astype(cfg.activation_dtype()),
        preferred_element_type=jnp.float32)
    start = (0, 0 if slot is None else slot, 0, 0, 0)
    dt = cache["k"].dtype
    cache = {
        "k": jax.lax.dynamic_update_slice(cache["k"], ks.astype(dt),
                                          start),
        "v": jax.lax.dynamic_update_slice(cache["v"], vs.astype(dt),
                                          start),
    }
    return logits, cache


def decode_step(params, tokens, cache, pos, cfg: GPTConfig,
                mesh: Mesh | None = None):
    """One autoregressive step for every cache slot: ``tokens [B]`` int32
    (each slot's current token) at positions ``pos [B]`` int32. Writes
    each token's K/V at ``pos`` and attends over cache positions
    ``<= pos``, so no prefix is ever re-run. Returns
    ``(logits [B, vocab] f32, cache)``.

    All shapes are static — B is the slot count, the cache length is the
    preallocated max — so the engine's jitted wrapper compiles exactly
    once. Donate the cache argument at the jit boundary: XLA then aliases
    the cache in/out and the update is in-place in HBM."""
    from ray_tpu.ops.decode_attention import decode_attention
    adt = cfg.activation_dtype()
    pet = (jnp.float32 if cfg.matmul_out == "float32" else adt)
    b = tokens.shape[0]
    nh, hd = cfg.n_heads, cfg.head_dim
    rows = jnp.arange(b)
    pos = pos.astype(jnp.int32)
    x = params["embed"].astype(adt)[tokens]
    x = x + params["pos_embed"].astype(adt)[pos]

    def body(x, layer):
        lp, kc, vc = layer                      # kc/vc [B, S, H, Dh]
        h = _rms_norm(x, lp["ln1_scale"].astype(adt))
        q = jnp.einsum("bd,dh->bh", h, lp["wq"].astype(adt),
                       preferred_element_type=pet).astype(adt)
        k = jnp.einsum("bd,dh->bh", h, lp["wk"].astype(adt),
                       preferred_element_type=pet).astype(adt)
        v = jnp.einsum("bd,dh->bh", h, lp["wv"].astype(adt),
                       preferred_element_type=pet).astype(adt)
        q = q.reshape(b, nh, hd)
        kc = kc.at[rows, pos].set(k.reshape(b, nh, hd).astype(kc.dtype))
        vc = vc.at[rows, pos].set(v.reshape(b, nh, hd).astype(vc.dtype))
        att = decode_attention(q, kc, vc, pos,
                               impl=cfg.decode_attn_impl)
        att = jnp.einsum("bh,hd->bd", att.reshape(b, nh * hd),
                         lp["wo"].astype(adt),
                         preferred_element_type=pet).astype(adt)
        x = x + att
        h = _rms_norm(x, lp["ln2_scale"].astype(adt))
        up = jnp.einsum("bd,df->bf", h, lp["w_up"].astype(adt),
                        preferred_element_type=pet).astype(adt)
        gate = jnp.einsum("bd,df->bf", h, lp["w_gate"].astype(adt),
                          preferred_element_type=pet).astype(adt)
        ff = jax.nn.silu(gate) * up
        down = jnp.einsum("bf,fd->bd", ff, lp["w_down"].astype(adt),
                          preferred_element_type=pet).astype(adt)
        return x + down, (kc, vc)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"]))
    x = _rms_norm(x, params["final_ln_scale"].astype(adt))
    logits = jnp.einsum("bd,vd->bv", x, params["embed"].astype(adt),
                        preferred_element_type=jnp.float32)
    return logits, {"k": k_new, "v": v_new}


# ---------------------------------------------------------------------------
# paged KV cache: block pool + block tables
# ---------------------------------------------------------------------------
# Paging is the Podracer philosophy scaled to ragged traffic: the device
# allocation is still ONE static pool, but its unit is a block of
# `block_size` positions instead of a full max_len row. Sequences name
# their blocks through an int32 block table [B, max_blocks] that rides
# into the jits as data — shapes never change, so decode still compiles
# exactly once, while the host (serve/engine.py) is free to share,
# copy-on-write, and recycle blocks between requests.

def check_quant_cfg(cfg: GPTConfig) -> bool:
    """Trace-time validation of the quantization knobs (the
    check_loss_impl idiom: a typo'd config fails the first trace, not
    some later step). Returns True when the KV pool is int8."""
    if cfg.kv_dtype not in ("f32", "int8"):
        raise ValueError(
            f"unknown kv_dtype {cfg.kv_dtype!r} (expected 'f32' | "
            "'int8')")
    if cfg.weight_dtype not in ("f32", "int8"):
        raise ValueError(
            f"unknown weight_dtype {cfg.weight_dtype!r} (expected "
            "'f32' | 'int8')")
    if cfg.prefill_attn_impl not in ("auto", "pallas", "jax"):
        raise ValueError(
            f"unknown prefill_attn_impl {cfg.prefill_attn_impl!r} "
            "(expected 'auto' | 'pallas' | 'jax')")
    return cfg.kv_dtype == "int8"


# The per-layer matmul weights the int8 weight-only path quantizes.
# Norm scales, embed and pos_embed stay f32 — they are O(d) reads, not
# the bandwidth, and the unembed shares `embed`.
QUANTIZED_WEIGHTS = ("wq", "wk", "wv", "wo", "w_up", "w_gate", "w_down")


def quantize_params(params):
    """Per-output-channel int8 copy of a GPT param tree for the
    `weight_dtype="int8"` inference path: every `QUANTIZED_WEIGHTS`
    leaf ``[L, In, Out]`` becomes an int8 leaf plus an
    ``"<name>_scale"`` f32 ``[L, Out]`` sibling
    (`ops.quant.quantize_channels`). Embed/pos_embed/norm scales pass
    through untouched. Pure and jittable — the engine wraps it in a
    donating jit so the RL flywheel's swap path republishes f32 masters
    and quantization rides the swap."""
    from ray_tpu.ops import quant
    layers = dict(params["layers"])
    for name in QUANTIZED_WEIGHTS:
        q, s = quant.quantize_channels(layers[name])
        layers[name] = q
        layers[name + "_scale"] = s
    return {**params, "layers": layers}


def _w(lp, name, adt):
    """Resolve one per-layer matmul weight: dequantize (f32 scale per
    output channel, then cast to the activation dtype) when the layer
    dict carries a ``"<name>_scale"`` sibling, plain cast otherwise —
    a static dict-key check, so f32 configs trace byte-identical code."""
    w = lp[name]
    s = lp.get(name + "_scale")
    if s is None:
        return w.astype(adt)
    return (w.astype(jnp.float32) * s[..., None, :]).astype(adt)


def kv_pool_logical_axes(quantized: bool = False):
    """Logical-axis tuples for the paged block pool {"k", "v"} of
    [L, n_blocks, block_size, H, Dh]. Heads stay tensor-parallel
    (matching the wq/wk/wv column split, exactly like the unpaged
    cache); the block axis is replicated — any block must be assignable
    to any sequence, so it cannot ride the data axes the way dedicated
    slot rows could. With ``quantized`` the dict grows
    {"k_scale", "v_scale"} of [L, n_blocks, block_size, H] — heads
    sharded with their payload rows, blocks replicated the same way."""
    axes = (None, None, None, "heads", None)
    pool = {"k": axes, "v": axes}
    if quantized:
        scale_axes = (None, None, None, "heads")
        pool["k_scale"] = scale_axes
        pool["v_scale"] = scale_axes
    return pool


def init_kv_pool(cfg: GPTConfig, n_blocks: int, block_size: int,
                 mesh: Mesh | None = None):
    """Preallocated paged cache {"k", "v"} of
    [L, n_blocks, block_size, H, Dh], zero-filled, placed with its
    sharding annotation when a mesh is given. `cfg.kv_dtype="f32"`
    stores cfg.dtype payloads; "int8" stores int8 payloads plus
    {"k_scale", "v_scale"} f32 [L, n_blocks, block_size, H] per-row
    scales (zero rows dequantize to exact zeros, so the zero-init is
    inert either way). Block 0 is conventionally the engine's trash
    block (idle decode rows scatter there), but nothing here enforces
    that — allocation policy is the host's job."""
    quantized = check_quant_cfg(cfg)
    shape = (cfg.n_layers, n_blocks, block_size, cfg.n_heads,
             cfg.head_dim)
    payload_dt = jnp.int8 if quantized else cfg.activation_dtype()
    pool = {"k": jnp.zeros(shape, payload_dt),
            "v": jnp.zeros(shape, payload_dt)}
    if quantized:
        pool["k_scale"] = jnp.zeros(shape[:-1], jnp.float32)
        pool["v_scale"] = jnp.zeros(shape[:-1], jnp.float32)
    if mesh is not None:
        from ray_tpu.parallel.sharding import kv_pool_shardings
        sh = kv_pool_shardings(mesh, quantized=quantized)
        pool = {name: jax.device_put(arr, sh[name])
                for name, arr in pool.items()}
    return pool


def copy_block(cache, src, dst):
    """Copy physical block `src` onto `dst` in every entry of the pool —
    the device half of copy-on-write prefix sharing. Iterates the cache
    dict, so an int8 pool's scale rows travel with their payload and COW
    semantics never depend on the dtype (the block axis is axis 1 for
    payloads and scales alike). src/dst may be traced scalars, so one
    jit (with the cache donated) serves every copy the engine ever
    issues."""
    out = {}
    for name in cache:
        blk = jax.lax.dynamic_slice_in_dim(cache[name], src, 1, axis=1)
        out[name] = jax.lax.dynamic_update_slice_in_dim(
            cache[name], blk, dst, axis=1)
    return out


def gather_block(cache, idx):
    """Read physical block `idx` out of every entry of the pool — the
    device half of KV-block export for disaggregated prefill/decode
    serving. Returns a dict of [L, block_size, H, Dh] payload rows (and
    [L, block_size, H] scale rows for an int8 pool — iterating the
    cache dict means scales always travel with their payload, exactly
    like `copy_block`). `idx` may be a traced scalar, so one jit serves
    every block a prefill engine ever exports; the cache is NOT donated
    (the pool must survive the read)."""
    return {name: jax.lax.dynamic_index_in_dim(
                cache[name], idx, axis=1, keepdims=False)
            for name in cache}


def scatter_block(cache, block, idx):
    """Write one exported block's rows (the dict `gather_block`
    returned, re-hosted on the importing engine) onto physical block
    `idx` of this pool — the device half of KV-block import. Payload
    and scale entries land through the same index, so an int8 pool's
    quantized rows re-install byte-identical and the decode engine's
    attention dequantizes exactly what the prefill engine wrote. `idx`
    may be a traced scalar; donate the cache at jit time so imports
    update the pool in place."""
    return {name: jax.lax.dynamic_update_slice_in_dim(
                cache[name], block[name][:, None], idx, axis=1)
            for name in cache}


def _scatter_kv(lc, k, v, widx):
    """Write `k`/`v` [N, H, Dh] (activation dtype) into one layer's pool
    slice `lc` at flat indices ``widx [N]`` (out-of-bounds rows drop —
    the padded-tail / past-table convention every paged writer shares).
    An int8 pool (``"k_scale" in lc`` — a static check) quantizes at the
    write: payload rows and their (position, head) scale cells scatter
    through the SAME indices, so single-token appends, chunked prefill
    and W-token verify all land byte-identical int8 for identical f32
    inputs (`ops.quant`'s determinism contract). Returns the layer's new
    cache dict."""
    nb, bs, nh, hd = lc["k"].shape
    kf = lc["k"].reshape(nb * bs, nh, hd)
    vf = lc["v"].reshape(nb * bs, nh, hd)
    if "k_scale" in lc:
        from ray_tpu.ops import quant
        qk, ks = quant.quantize_rows(k)
        qv, vs = quant.quantize_rows(v)
        return {
            "k": kf.at[widx].set(qk, mode="drop").reshape(
                nb, bs, nh, hd),
            "v": vf.at[widx].set(qv, mode="drop").reshape(
                nb, bs, nh, hd),
            "k_scale": lc["k_scale"].reshape(nb * bs, nh)
                .at[widx].set(ks, mode="drop").reshape(nb, bs, nh),
            "v_scale": lc["v_scale"].reshape(nb * bs, nh)
                .at[widx].set(vs, mode="drop").reshape(nb, bs, nh),
        }
    return {
        "k": kf.at[widx].set(k.astype(kf.dtype), mode="drop").reshape(
            nb, bs, nh, hd),
        "v": vf.at[widx].set(v.astype(vf.dtype), mode="drop").reshape(
            nb, bs, nh, hd),
    }


def prefill_paged(params, tokens, cache, cfg: GPTConfig,
                  mesh: Mesh | None = None, *, block_table, start,
                  length=None):
    """One chunk of paged prefill for a single sequence: ``tokens
    [1, C]`` (right-padded to the chunk bucket C) are processed at
    absolute positions ``start .. start + length - 1``; their K/V are
    scattered into the block pool through ``block_table [max_blocks]``
    i32, and the returned logits ``[1, vocab]`` f32 are the chunk's last
    *real* position (``start + length - 1``) — the engine samples the
    request's first token from the final chunk's logits and ignores the
    rest.

    Attention is causal over the WHOLE prefix: each chunk token attends
    to every cached position written by earlier chunks (or shared via
    the radix tree) plus the causal part of its own chunk — gathered
    from the pool through the same block table it writes. `start`,
    `length` and the table are traced, so prefill compiles once per
    chunk bucket, ever.

    Attention routes through
    `ops.decode_attention.paged_prefill_attention`
    (`cfg.prefill_attn_impl`): the "jax" path is the dense gather+einsum
    this function used to inline, bit-identical; "pallas" (or "auto" on
    TPU) runs the fused kernel whose chunk scores never round-trip HBM.
    An int8 pool (`cfg.kv_dtype="int8"`) quantizes K/V inside the
    scatter and the attention op dequantizes blockwise inside."""
    check_quant_cfg(cfg)
    from ray_tpu.ops.decode_attention import paged_prefill_attention
    b, c = tokens.shape
    if b != 1:
        raise ValueError(f"paged prefill wants tokens [1, C], got "
                         f"batch {b}")
    nb, bs = cache["k"].shape[1], cache["k"].shape[2]
    if start is None:
        raise ValueError("prefill_paged needs start=")
    adt = cfg.activation_dtype()
    pet = (jnp.float32 if cfg.matmul_out == "float32" else adt)
    nh, hd = cfg.n_heads, cfg.head_dim
    start = jnp.asarray(start, jnp.int32)
    length = jnp.asarray(c if length is None else length, jnp.int32)
    table = jnp.asarray(block_table, jnp.int32)

    offs = jnp.arange(c, dtype=jnp.int32)
    positions = start + offs
    valid = offs < length
    # Physical flat write indices; padded tail rows scatter out of
    # bounds and are dropped, so chunk garbage never lands in a block.
    widx = jnp.where(valid, table[positions // bs] * bs + positions % bs,
                     nb * bs)

    x = params["embed"].astype(adt)[tokens[0]]
    x = x + params["pos_embed"].astype(adt)[positions]      # [C, D]

    def body(x, layer):
        lp, lc = layer                  # lc["k"/"v"]: [nb, bs, H, Dh]
        h = _rms_norm(x, lp["ln1_scale"].astype(adt))
        q = jnp.einsum("td,dh->th", h, _w(lp, "wq", adt),
                       preferred_element_type=pet).astype(adt)
        k = jnp.einsum("td,dh->th", h, _w(lp, "wk", adt),
                       preferred_element_type=pet).astype(adt)
        v = jnp.einsum("td,dh->th", h, _w(lp, "wv", adt),
                       preferred_element_type=pet).astype(adt)
        q = q.reshape(c, nh, hd)
        lc = _scatter_kv(lc, k.reshape(c, nh, hd),
                         v.reshape(c, nh, hd), widx)
        att = paged_prefill_attention(
            q, lc["k"], lc["v"], table, start,
            k_scale=lc.get("k_scale"), v_scale=lc.get("v_scale"),
            impl=cfg.prefill_attn_impl).reshape(c, nh * hd)
        att = jnp.einsum("th,hd->td", att, _w(lp, "wo", adt),
                         preferred_element_type=pet).astype(adt)
        x = x + att
        h = _rms_norm(x, lp["ln2_scale"].astype(adt))
        up = jnp.einsum("td,df->tf", h, _w(lp, "w_up", adt),
                        preferred_element_type=pet).astype(adt)
        gate = jnp.einsum("td,df->tf", h, _w(lp, "w_gate", adt),
                          preferred_element_type=pet).astype(adt)
        ff = jax.nn.silu(gate) * up
        down = jnp.einsum("tf,fd->td", ff, _w(lp, "w_down", adt),
                          preferred_element_type=pet).astype(adt)
        return x + down, lc

    x, cache = jax.lax.scan(body, x, (params["layers"], cache))
    x = _rms_norm(x, params["final_ln_scale"].astype(adt))
    last = jnp.take_along_axis(x, (length - 1)[None, None], axis=0)
    logits = jnp.einsum("td,vd->tv", last, params["embed"].astype(adt),
                        preferred_element_type=jnp.float32)
    return logits, cache


def decode_step_paged(params, tokens, cache, pos, tables,
                      cfg: GPTConfig, mesh: Mesh | None = None):
    """One autoregressive step for every slot through the paged cache:
    ``tokens [B]`` at positions ``pos [B]``, each slot's blocks named by
    ``tables [B, max_blocks]`` i32. Writes each token's K/V at its
    logical position's block/offset and attends over logical positions
    ``<= pos`` via `ops.decode_attention.paged_decode_attention`.
    Returns ``(logits [B, vocab] f32, cache)``.

    Shapes are static (B slots, fixed pool, fixed table width), so the
    engine's jitted wrapper still compiles exactly once; idle rows
    should point their table at the trash block (0) and any position —
    their writes collide harmlessly there and nobody reads the output.

    An int8 pool (`cfg.kv_dtype="int8"`) quantizes the appended K/V row
    (payload + per-head scale cell through the same drop-mode scatter)
    and the attention kernel dequantizes per block in VMEM."""
    check_quant_cfg(cfg)
    from ray_tpu.ops.decode_attention import paged_decode_attention
    adt = cfg.activation_dtype()
    pet = (jnp.float32 if cfg.matmul_out == "float32" else adt)
    b = tokens.shape[0]
    nb, bs = cache["k"].shape[1], cache["k"].shape[2]
    mb = tables.shape[1]
    nh, hd = cfg.n_heads, cfg.head_dim
    pos = pos.astype(jnp.int32)
    tables = tables.astype(jnp.int32)
    # Positions past the table's reach (speculative draft steps can run a
    # few past max_len) must DROP, not clamp — a clamped index would land
    # the write inside the slot's own last block and corrupt real data.
    blk = jnp.take_along_axis(
        tables, jnp.minimum(pos // bs, mb - 1)[:, None], axis=1)[:, 0]
    widx = jnp.where(pos < mb * bs, blk * bs + pos % bs,
                     nb * bs)                    # [B] flat write index
    x = params["embed"].astype(adt)[tokens]
    x = x + params["pos_embed"].astype(adt)[
        jnp.minimum(pos, cfg.max_seq_len - 1)]

    def body(x, layer):
        lp, lc = layer                  # lc["k"/"v"]: [nb, bs, H, Dh]
        h = _rms_norm(x, lp["ln1_scale"].astype(adt))
        q = jnp.einsum("bd,dh->bh", h, _w(lp, "wq", adt),
                       preferred_element_type=pet).astype(adt)
        k = jnp.einsum("bd,dh->bh", h, _w(lp, "wk", adt),
                       preferred_element_type=pet).astype(adt)
        v = jnp.einsum("bd,dh->bh", h, _w(lp, "wv", adt),
                       preferred_element_type=pet).astype(adt)
        q = q.reshape(b, nh, hd)
        lc = _scatter_kv(lc, k.reshape(b, nh, hd),
                         v.reshape(b, nh, hd), widx)
        att = paged_decode_attention(q, lc["k"], lc["v"], tables, pos,
                                     k_scale=lc.get("k_scale"),
                                     v_scale=lc.get("v_scale"),
                                     impl=cfg.decode_attn_impl)
        att = jnp.einsum("bh,hd->bd", att.reshape(b, nh * hd),
                         _w(lp, "wo", adt),
                         preferred_element_type=pet).astype(adt)
        x = x + att
        h = _rms_norm(x, lp["ln2_scale"].astype(adt))
        up = jnp.einsum("bd,df->bf", h, _w(lp, "w_up", adt),
                        preferred_element_type=pet).astype(adt)
        gate = jnp.einsum("bd,df->bf", h, _w(lp, "w_gate", adt),
                          preferred_element_type=pet).astype(adt)
        ff = jax.nn.silu(gate) * up
        down = jnp.einsum("bf,fd->bd", ff, _w(lp, "w_down", adt),
                          preferred_element_type=pet).astype(adt)
        return x + down, lc

    x, cache = jax.lax.scan(body, x, (params["layers"], cache))
    x = _rms_norm(x, params["final_ln_scale"].astype(adt))
    logits = jnp.einsum("bd,vd->bv", x, params["embed"].astype(adt),
                        preferred_element_type=jnp.float32)
    return logits, cache


def verify_step_paged(params, tokens, cache, pos, tables,
                      cfg: GPTConfig, mesh: Mesh | None = None):
    """Batched W-token verify forward for speculative decoding: ``tokens
    [B, W]`` — column 0 is each slot's current token, columns 1..W-1 a
    speculated continuation — where row b's token j sits at logical
    position ``pos[b] + j``. Every token's K/V is written to its
    block/offset first, then all W tokens attend in one shot through
    `ops.decode_attention.paged_verify_attention` (token j sees positions
    ``<= pos[b] + j``, i.e. the real prefix plus drafts 0..j-1 — the same
    numbers W sequential `decode_step_paged` calls would produce).
    Returns ``(logits [B, W, vocab] f32, cache)``: logits[:, j] is the
    target model's next-token distribution *after* accepting drafts
    1..j, which is exactly what the engine's in-jit accept needs.

    Rejected drafts need no device-side cleanup: their K/V sit at
    positions > the rolled-back ``pos``, which the position mask hides
    and which the next (sequential) writes overwrite before any read —
    ``pos`` is the authoritative tail. Positions that run past the table
    (tail of a near-max_len slot) drop their writes instead of clamping,
    so a slot can never corrupt its own last block. Shapes are static
    (B slots, fixed W), so the engine's verify jit compiles exactly
    once.

    An int8 pool (`cfg.kv_dtype="int8"`) runs verify quantized:
    quantize-then-dequantize is a pure function of the written values
    (`ops.quant`), so a draft row's dequantized K/V is byte-identical
    to what the sequential decode append would have produced — verify
    stays bit-identical to W sequential steps, quantized or not."""
    check_quant_cfg(cfg)
    from ray_tpu.ops.decode_attention import paged_verify_attention
    adt = cfg.activation_dtype()
    pet = (jnp.float32 if cfg.matmul_out == "float32" else adt)
    b, w = tokens.shape
    nb, bs = cache["k"].shape[1], cache["k"].shape[2]
    mb = tables.shape[1]
    nh, hd = cfg.n_heads, cfg.head_dim
    pos = pos.astype(jnp.int32)
    tables = tables.astype(jnp.int32)
    positions = pos[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :]
    blk = jnp.take_along_axis(tables, jnp.minimum(positions // bs,
                                                  mb - 1), axis=1)
    widx = jnp.where(positions < mb * bs,
                     blk * bs + positions % bs,
                     nb * bs).reshape(-1)         # [B*W] flat, drop OOB
    x = params["embed"].astype(adt)[tokens]
    x = x + params["pos_embed"].astype(adt)[
        jnp.minimum(positions, cfg.max_seq_len - 1)]

    def body(x, layer):
        lp, lc = layer                  # lc["k"/"v"]: [nb, bs, H, Dh]
        h = _rms_norm(x, lp["ln1_scale"].astype(adt))
        q = jnp.einsum("bwd,dh->bwh", h, _w(lp, "wq", adt),
                       preferred_element_type=pet).astype(adt)
        k = jnp.einsum("bwd,dh->bwh", h, _w(lp, "wk", adt),
                       preferred_element_type=pet).astype(adt)
        v = jnp.einsum("bwd,dh->bwh", h, _w(lp, "wv", adt),
                       preferred_element_type=pet).astype(adt)
        q = q.reshape(b, w, nh, hd)
        lc = _scatter_kv(lc, k.reshape(b * w, nh, hd),
                         v.reshape(b * w, nh, hd), widx)
        att = paged_verify_attention(q, lc["k"], lc["v"], tables, pos,
                                     k_scale=lc.get("k_scale"),
                                     v_scale=lc.get("v_scale"),
                                     impl=cfg.decode_attn_impl)
        att = jnp.einsum("bwh,hd->bwd", att.reshape(b, w, nh * hd),
                         _w(lp, "wo", adt),
                         preferred_element_type=pet).astype(adt)
        x = x + att
        h = _rms_norm(x, lp["ln2_scale"].astype(adt))
        up = jnp.einsum("bwd,df->bwf", h, _w(lp, "w_up", adt),
                        preferred_element_type=pet).astype(adt)
        gate = jnp.einsum("bwd,df->bwf", h, _w(lp, "w_gate", adt),
                          preferred_element_type=pet).astype(adt)
        ff = jax.nn.silu(gate) * up
        down = jnp.einsum("bwf,fd->bwd", ff, _w(lp, "w_down", adt),
                          preferred_element_type=pet).astype(adt)
        return x + down, lc

    x, cache = jax.lax.scan(body, x, (params["layers"], cache))
    x = _rms_norm(x, params["final_ln_scale"].astype(adt))
    logits = jnp.einsum("bwd,vd->bwv", x, params["embed"].astype(adt),
                        preferred_element_type=jnp.float32)
    return logits, cache


def num_params(params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
