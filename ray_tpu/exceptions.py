"""Public exception types.

Counterpart of `python/ray/exceptions.py` in the reference: `TaskError`
mirrors `RayTaskError` (user exception captured with traceback and re-raised
on `get`), `ActorDiedError`/`WorkerCrashedError` mirror the process-failure
errors, `ObjectLostError` the object-availability errors.
"""


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at the `get` callsite.

    Carries the remote traceback text so users see where the failure happened,
    like the reference's RayTaskError (exceptions.py) which wraps `cause`.
    """

    def __init__(self, exc_type_name: str, message: str, remote_traceback: str,
                 cause: BaseException | None = None):
        self.exc_type_name = exc_type_name
        self.message = message
        self.remote_traceback = remote_traceback
        self.cause = cause
        super().__init__(
            f"{exc_type_name}: {message}\n\n"
            f"--- remote traceback ---\n{remote_traceback}")

    def __reduce__(self):
        # Exception's default reduce would replay only the formatted message;
        # rebuild from the real fields. `cause` may itself be unpicklable —
        # the worker's serialize fallback handles that case.
        return (TaskError, (self.exc_type_name, self.message,
                            self.remote_traceback, self.cause))

    def as_instanceof_cause(self):
        """Return an exception that is also an instance of the original type,
        so `except OriginalError:` works across the process boundary."""
        if self.cause is not None and isinstance(self.cause, Exception):
            cls = type(self.cause)
            try:
                derived = type(
                    "TaskError_" + cls.__name__, (TaskError, cls), {})
                err = derived.__new__(derived)
                TaskError.__init__(err, self.exc_type_name, self.message,
                                   self.remote_traceback, self.cause)
                return err
            except TypeError:
                pass
        return self


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class ActorDiedError(RayTpuError):
    """The actor is dead (crashed, killed, or out of restarts)."""


class ActorUnavailableError(RayTpuError):
    """The actor is temporarily unavailable (restarting)."""


class ObjectLostError(RayTpuError):
    """An object's value could not be found in the store."""


class ObjectFreedError(ObjectLostError):
    """The object was freed by reference counting before this access —
    usually a ref that reached the node only after its last holder was
    accounted released (reference: ObjectFreedError in exceptions.py)."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """`get(..., timeout=)` expired before the object was ready."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled before/while running."""


class OverloadedError(RayTpuError):
    """The serving layer shed this request under overload (queue bound
    or block-pool high-water mark) instead of queueing it unboundedly.
    Back off and retry later — the HTTP proxy maps it to 429."""


class RuntimeEnvSetupError(RayTpuError):
    """Preparing a task/actor runtime environment failed."""


class PlacementGroupError(RayTpuError):
    """Placement group creation or lookup failed."""


class SchedulingError(RayTpuError):
    """The task can never be scheduled (e.g. hard node affinity to a dead
    or unknown node)."""
