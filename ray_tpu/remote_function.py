"""@remote functions.

Counterpart of the reference's `python/ray/remote_function.py`
(`RemoteFunction`, `_remote` :245): wraps a user callable, carries default
task options, and turns `.remote(...)` calls into TaskSpec submissions.
"""

from __future__ import annotations

import hashlib
import functools

import cloudpickle

from ray_tpu._private import ids, protocol, serialization
from ray_tpu._private.constants import (
    DEFAULT_TASK_NUM_CPUS,
    INLINE_OBJECT_MAX_BYTES,
)
from ray_tpu._private.worker import ObjectRef, get_client


def _resources_from_options(o: dict, default_cpus: float) -> dict:
    res = dict(o.get("resources") or {})
    num_cpus = o.get("num_cpus")
    num_tpus = o.get("num_tpus", o.get("num_gpus"))  # num_gpus accepted as
    # an alias to ease porting reference-API code onto TPU resources.
    res["CPU"] = float(default_cpus if num_cpus is None else num_cpus)
    if num_tpus:
        res["TPU"] = float(num_tpus)
    mem = o.get("memory")
    if mem:
        res["memory"] = float(mem)
    return res


def encode_strategy(strategy):
    """Flatten a scheduling-strategy object into the TaskSpec side channel
    the cluster scheduler reads (node.py _pick_node): "SPREAD" or
    {"node_id": ..., "soft": ...} for NodeAffinitySchedulingStrategy."""
    if isinstance(strategy, str):
        return strategy
    if hasattr(strategy, "node_id"):
        return {"node_id": strategy.node_id,
                "soft": bool(getattr(strategy, "soft", False))}
    return None


def _encode_args(args, kwargs):
    """Top-level ObjectRefs become ("ref", id); other values are serialized
    inline, spilling to the object store above the inline cap (the reference
    promotes >100KB args to plasma in `_raylet.pyx` submit_task)."""
    def enc(v):
        if isinstance(v, ObjectRef):
            return ("ref", v._id)
        blob = serialization.dumps(v)
        if len(blob) > INLINE_OBJECT_MAX_BYTES:
            # Reuse the envelope we just built instead of re-serializing.
            return ("ref", get_client().put_serialized(blob))
        return ("v", blob)
    return [enc(a) for a in args], {k: enc(v) for k, v in kwargs.items()}


class RemoteFunction:
    def __init__(self, function, options: dict | None = None):
        self._function = function
        self._options = dict(options or {})
        functools.update_wrapper(self, function)
        self._pickled: bytes | None = None
        self._function_id: str | None = None

    def _materialize(self):
        if self._pickled is None:
            self._pickled = cloudpickle.dumps(self._function, protocol=5)
            self._function_id = ("fn_" +
                                 hashlib.sha1(self._pickled).hexdigest()[:16])
        return self._pickled, self._function_id

    def options(self, **opts) -> "RemoteFunction":
        new = RemoteFunction(self._function, {**self._options, **opts})
        new._pickled, new._function_id = self._materialize()
        return new

    def __call__(self, *a, **kw):
        raise TypeError(
            f"remote function {self._function.__name__} cannot be called "
            f"directly; use .remote()")

    def bind(self, *args, **kwargs):
        """Lazy DAG node instead of immediate submission (reference:
        `dag/function_node.py`); run with `.execute()` or ray_tpu.workflow."""
        from ray_tpu.dag import FunctionNode
        return FunctionNode(self, args, kwargs)

    def remote(self, *args, **kwargs):
        blob, function_id = self._materialize()
        o = self._options
        num_returns = int(o.get("num_returns", 1))
        task_id = ids.new_task_id()
        return_ids = [ids.new_object_id() for _ in range(num_returns)]
        enc_args, enc_kwargs = _encode_args(args, kwargs)
        pg_id = None
        strategy_enc = None
        strategy = o.get("scheduling_strategy")
        if strategy is not None and hasattr(strategy, "placement_group"):
            pg_id = strategy.placement_group.id
        elif strategy is not None:
            strategy_enc = encode_strategy(strategy)
        spec = protocol.TaskSpec(
            task_id=task_id,
            function_id=function_id,
            function_blob=blob,
            function_desc=getattr(self._function, "__qualname__",
                                  str(self._function)),
            args=enc_args,
            kwargs=enc_kwargs,
            num_returns=num_returns,
            return_ids=return_ids,
            resources=_resources_from_options(o, DEFAULT_TASK_NUM_CPUS),
            max_retries=int(o.get("max_retries", 0)),
            retry_exceptions=bool(o.get("retry_exceptions", False)),
            runtime_env=o.get("runtime_env"),
            scheduling_strategy=strategy_enc,
            placement_group_id=pg_id,
            name=o.get("name") or getattr(self._function, "__name__", ""),
        )
        get_client().submit(spec)
        refs = [ObjectRef(oid) for oid in return_ids]
        return refs[0] if num_returns == 1 else refs
