"""Gradient-boosted decision trees over the worker-group spine.

Counterpart of the reference's `train/xgboost/xgboost_trainer.py` and
`train/lightgbm/lightgbm_trainer.py`: distributed boosting where each
worker holds a data shard and per-node gradient histograms are
allreduced so every worker grows the IDENTICAL tree (exactly rabit's
histogram-sync scheme, minus rabit — the rendezvous is this framework's
own collective group).

Three trainers:

- `GBDTTrainer` — the native implementation (`_HistGBDT`, pure numpy):
  histogram splits, logistic or squared-error loss, shrinkage,
  lambda-regularized leaf weights. Deterministic: an N-worker fit
  produces bit-identical trees to a single-process fit on the
  concatenated data, which the tests assert. This is the path that
  works on a bare image.
- `XGBoostTrainer` / `LightGBMTrainer` — thin adapters that fit the
  real libraries when installed (single-node multi-thread v1; their
  C-level distributed modes need their own comm setup) and raise a
  clear ImportError otherwise. They share the dataset/session/
  checkpoint plumbing with GBDTTrainer.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.trainer import JaxTrainer, Result


# ---------------------------------------------------------------------------
# native histogram GBDT
# ---------------------------------------------------------------------------

class _Tree:
    __slots__ = ("feature", "threshold", "left", "right", "value")

    def __init__(self):
        # arrays indexed by node id; leaves have feature == -1
        self.feature: list = []
        self.threshold: list = []
        self.left: list = []
        self.right: list = []
        self.value: list = []

    def add_node(self):
        self.feature.append(-1)
        self.threshold.append(0.0)
        self.left.append(-1)
        self.right.append(-1)
        self.value.append(0.0)
        return len(self.feature) - 1

    def predict(self, X: np.ndarray) -> np.ndarray:
        out = np.zeros(len(X))
        feature = np.asarray(self.feature)
        threshold = np.asarray(self.threshold)
        left = np.asarray(self.left)
        right = np.asarray(self.right)
        value = np.asarray(self.value)
        node = np.zeros(len(X), np.int64)
        # depth-bounded trees: iterate until every row is at a leaf
        for _ in range(64):
            f = feature[node]
            live = f >= 0
            if not live.any():
                break
            go_left = np.where(
                live, X[np.arange(len(X)), np.maximum(f, 0)]
                <= threshold[node], False)
            node = np.where(live,
                            np.where(go_left, left[node], right[node]),
                            node)
        return value[node]


class _HistGBDT:
    """Histogram gradient boosting with a pluggable histogram allreduce.

    All split decisions are taken on ALLREDUCED (grad, hess) histograms,
    so every rank grows the same tree from different shards — the core
    invariant of distributed xgboost (`approx`/`hist` tree method)."""

    def __init__(self, objective: str = "squared_error",
                 n_estimators: int = 50, max_depth: int = 3,
                 learning_rate: float = 0.3, n_bins: int = 64,
                 reg_lambda: float = 1.0, min_child_weight: float = 1e-3):
        self.objective = objective
        self.n_estimators = n_estimators
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_bins = n_bins
        self.reg_lambda = reg_lambda
        self.min_child_weight = min_child_weight
        self.trees: list[_Tree] = []
        self.base_score = 0.0
        self.bin_edges: np.ndarray | None = None

    # -- loss ----------------------------------------------------------

    def _grad_hess(self, y, pred):
        if self.objective == "binary:logistic":
            p = 1.0 / (1.0 + np.exp(-pred))
            return p - y, np.maximum(p * (1.0 - p), 1e-12)
        return pred - y, np.ones_like(y)          # squared error

    # -- fitting -------------------------------------------------------

    def fit(self, X: np.ndarray, y: np.ndarray, allreduce=None,
            eval_cb=None):
        """`allreduce(arr) -> arr` sums float64 arrays across ranks
        (None = single process). `eval_cb(round, model)` runs after each
        boosting round (the session.report seam)."""
        X = np.asarray(X, np.float64)
        y = np.asarray(y, np.float64)
        # `allreduce((arr, op))` with op in {"sum", "min", "max"}
        ar = allreduce or (lambda payload: np.asarray(payload[0]))

        # global uniform bins from allreduced min/max (the approximate-
        # quantile sketch of xgboost's approx mode, simplified: uniform
        # bins are deterministic and rank-agnostic, which the
        # multi-worker == single-process parity contract needs)
        local_min = X.min(axis=0) if len(X) else np.full(
            X.shape[1], np.inf)
        local_max = X.max(axis=0) if len(X) else np.full(
            X.shape[1], -np.inf)
        gmin = ar((local_min, "min"))
        gmax = ar((local_max, "max"))
        n_feat = X.shape[1]
        span = np.where(gmax > gmin, gmax - gmin, 1.0)
        self.bin_edges = gmin[None, :] + span[None, :] * (
            np.arange(1, self.n_bins)[:, None] / self.n_bins)
        binned = np.empty_like(X, dtype=np.int32)
        for f in range(n_feat):
            binned[:, f] = np.searchsorted(
                self.bin_edges[:, f], X[:, f], side="right")

        # base score: global mean (sum trick)
        tot = ar((np.asarray([y.sum(), float(len(y))]), "sum"))
        self.base_score = float(tot[0] / max(tot[1], 1.0))
        if self.objective == "binary:logistic":
            p = np.clip(self.base_score, 1e-6, 1 - 1e-6)
            self.base_score = float(np.log(p / (1 - p)))
        pred = np.full(len(y), self.base_score)

        for r in range(self.n_estimators):
            g, h = self._grad_hess(y, pred)
            tree = _Tree()
            root = tree.add_node()
            # node id -> boolean row mask on THIS shard
            frontier = [(root, np.ones(len(y), bool), 0)]
            while frontier:
                node, mask, depth = frontier.pop()
                gh = self._node_hist(binned, g, h, mask, n_feat)
                gh = ar((gh, "sum"))
                gsum, hsum = gh[0].sum(axis=1)[0], gh[1].sum(axis=1)[0]
                leaf_val = -gsum / (hsum + self.reg_lambda)
                tree.value[node] = leaf_val * self.learning_rate
                if depth >= self.max_depth:
                    continue
                feat, thr_bin, gain = self._best_split(gh)
                if feat < 0 or gain <= 1e-12:
                    continue
                tree.feature[node] = feat
                tree.threshold[node] = float(
                    self.bin_edges[thr_bin, feat]
                    if thr_bin < self.n_bins - 1 else np.inf)
                go_left = binned[:, feat] <= thr_bin
                lmask = mask & go_left
                rmask = mask & ~go_left
                tree.left[node] = tree.add_node()
                tree.right[node] = tree.add_node()
                frontier.append((tree.left[node], lmask, depth + 1))
                frontier.append((tree.right[node], rmask, depth + 1))
            self.trees.append(tree)
            pred += tree.predict(np.asarray(X))
            if eval_cb is not None:
                eval_cb(r, self)
        return self

    def _node_hist(self, binned, g, h, mask, n_feat):
        """(2, n_feat, n_bins) grad/hess histogram of this node's rows
        on THIS shard — the only thing that crosses ranks."""
        out = np.zeros((2, n_feat, self.n_bins))
        gm, hm = g[mask], h[mask]
        bm = binned[mask]
        for f in range(n_feat):
            out[0, f] = np.bincount(bm[:, f], weights=gm,
                                    minlength=self.n_bins)
            out[1, f] = np.bincount(bm[:, f], weights=hm,
                                    minlength=self.n_bins)
        return out

    def _best_split(self, gh):
        """xgboost gain over the cumulative histogram, all features at
        once."""
        G, H = gh[0], gh[1]                       # [n_feat, n_bins]
        Gl = np.cumsum(G, axis=1)[:, :-1]         # left of each edge
        Hl = np.cumsum(H, axis=1)[:, :-1]
        Gt, Ht = G.sum(axis=1, keepdims=True), H.sum(axis=1,
                                                     keepdims=True)
        Gr, Hr = Gt - Gl, Ht - Hl
        lam = self.reg_lambda
        gain = (Gl ** 2 / (Hl + lam) + Gr ** 2 / (Hr + lam)
                - Gt ** 2 / (Ht + lam))
        ok = (Hl > self.min_child_weight) & (Hr > self.min_child_weight)
        gain = np.where(ok, gain, -np.inf)
        flat = int(np.argmax(gain))
        feat, thr = divmod(flat, gain.shape[1])
        best = gain[feat, thr]
        if not np.isfinite(best) or best <= 0:
            return -1, -1, 0.0
        return feat, thr, float(best)

    # -- inference -----------------------------------------------------

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        X = np.asarray(X, np.float64)
        out = np.full(len(X), self.base_score)
        for t in self.trees:
            out += t.predict(X)
        return out

    def predict(self, X: np.ndarray) -> np.ndarray:
        raw = self.predict_raw(X)
        if self.objective == "binary:logistic":
            return (raw > 0).astype(np.int64)
        return raw

    def predict_proba(self, X: np.ndarray) -> np.ndarray:
        raw = self.predict_raw(X)
        return 1.0 / (1.0 + np.exp(-raw))


# ---------------------------------------------------------------------------
# trainers over the worker-group spine
# ---------------------------------------------------------------------------

def _rows_to_xy(rows, label_column):
    feats = sorted(k for k in rows[0] if k != label_column)
    y = np.asarray([r[label_column] for r in rows], np.float64)
    X = np.column_stack([
        np.asarray([r[k] for r in rows], np.float64) for k in feats])
    return X, y, feats


def _gbdt_train_loop(config: dict):
    """Runs on every worker: shard in, allreduced histograms, identical
    model out (rank 0 checkpoints it)."""
    from ray_tpu.train import session
    from ray_tpu.util.collective import CollectiveGroup

    rows = session.get_dataset_shard("train").take_all()
    X, y, feats = _rows_to_xy(rows, config["label_column"])
    world = session.get_world_size()
    rank = session.get_world_rank()
    if world > 1:
        group = CollectiveGroup(config["group_name"], world, rank)

        def ar(payload):
            arr, op = payload
            return np.asarray(group.allreduce(np.asarray(arr), op=op))
    else:
        def ar(payload):
            return np.asarray(payload[0])

    model = _HistGBDT(**config["params"])

    def eval_cb(rnd, m):
        if rnd % config.get("report_every", 10) == 0 or \
                rnd == m.n_estimators - 1:
            session.report({"round": rnd})

    model.fit(X, y, allreduce=ar, eval_cb=eval_cb)
    pred = model.predict(X)
    if config["params"].get("objective") == "binary:logistic":
        local = np.asarray([(pred == y).sum(), float(len(y))])
        agg = ar((local, "sum"))
        metric = {"train_accuracy": float(agg[0] / max(agg[1], 1.0))}
    else:
        local = np.asarray([((pred - y) ** 2).sum(), float(len(y))])
        agg = ar((local, "sum"))
        metric = {"train_rmse": float(np.sqrt(agg[0] / max(agg[1], 1.0)))}
    ckpt = None
    if rank == 0:
        ckpt = Checkpoint.from_dict(
            {"model": model, "feature_columns": feats})
    session.report({**metric, "done": True}, checkpoint=ckpt)


class GBDTTrainer(JaxTrainer):
    """Distributed histogram gradient boosting (native backend).

    Usage matches the reference's GBDT trainers::

        trainer = GBDTTrainer(
            label_column="y", params={"objective": "binary:logistic",
                                      "n_estimators": 30, "max_depth": 3},
            datasets={"train": ds},
            scaling_config=ScalingConfig(num_workers=2))
        result = trainer.fit()
        model = result.checkpoint.to_dict()["model"]
    """

    def __init__(self, *, label_column: str, params: dict | None = None,
                 datasets: dict, scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None):
        import uuid
        cfg = {
            "label_column": label_column,
            "params": dict(params or {}),
            "group_name": f"gbdt_{uuid.uuid4().hex[:8]}",
        }
        super().__init__(
            _gbdt_train_loop, train_loop_config=cfg,
            scaling_config=scaling_config or ScalingConfig(),
            run_config=run_config, datasets=datasets)


def _lib_train_loop(config: dict):
    """XGBoost / LightGBM fit on the worker group (v1: each library's
    own threading parallelizes within the worker; rank 0 fits on its
    shard when world > 1 — callers wanting全-data fits use 1 worker)."""
    from ray_tpu.train import session
    lib = config["lib"]
    rows = session.get_dataset_shard("train").take_all()
    X, y, feats = _rows_to_xy(rows, config["label_column"])
    if lib == "xgboost":
        import xgboost as xgb
        dtrain = xgb.DMatrix(X, label=y, feature_names=feats)
        booster = xgb.train(config["params"], dtrain,
                            num_boost_round=config["num_boost_round"])
        blob = booster.save_raw()
    else:
        import lightgbm as lgb
        train_set = lgb.Dataset(X, label=y)
        booster = lgb.train(config["params"], train_set,
                            num_boost_round=config["num_boost_round"])
        blob = booster.model_to_string()
    ckpt = None
    if session.get_world_rank() == 0:
        ckpt = Checkpoint.from_dict(
            {"model_blob": blob, "lib": lib, "feature_columns": feats})
    session.report({"done": True}, checkpoint=ckpt)


class _LibGBDTTrainer(JaxTrainer):
    _lib = ""

    def __init__(self, *, label_column: str, params: dict | None = None,
                 num_boost_round: int = 10, datasets: dict,
                 scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None):
        import importlib
        try:
            importlib.import_module(self._lib)
        except ImportError as e:
            raise ImportError(
                f"{type(self).__name__} requires the '{self._lib}' "
                f"package, which is not installed in this image; the "
                f"native GBDTTrainer provides distributed boosting "
                f"without it") from e
        cfg = {"label_column": label_column, "params": dict(params or {}),
               "num_boost_round": num_boost_round, "lib": self._lib}
        super().__init__(
            _lib_train_loop, train_loop_config=cfg,
            scaling_config=scaling_config or ScalingConfig(),
            run_config=run_config, datasets=datasets)


class XGBoostTrainer(_LibGBDTTrainer):
    """Reference: `train/xgboost/xgboost_trainer.py`."""
    _lib = "xgboost"


class LightGBMTrainer(_LibGBDTTrainer):
    """Reference: `train/lightgbm/lightgbm_trainer.py`."""
    _lib = "lightgbm"
