"""Worker-side training session.

Counterpart of the reference's `train/_internal/session.py` (report :426 —
user loop in a thread, results handed to the actor's main thread through a
bounded queue + semaphore, :141-149) and the `air/session.py` facade
(report :42, get_checkpoint :96, get_dataset_shard :358).

Same concurrency shape here: `train_loop_per_worker` runs in a daemon
thread inside the TrainWorker actor; `report()` blocks the loop until the
driver has consumed the result (lockstep reporting, so iteration counts
align across workers).
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass

_local = threading.local()


@dataclass
class TrainContext:
    world_size: int
    world_rank: int
    local_rank: int
    node_rank: int
    trial_name: str
    checkpoint: object | None          # ray_tpu.train.Checkpoint | None
    dataset_shards: dict
    result_queue: queue.Queue          # size 1: lockstep with the driver
    consumed: threading.Semaphore
    stop_event: threading.Event
    mesh_spec: object | None = None


def _ctx() -> TrainContext:
    ctx = getattr(_local, "ctx", None)
    if ctx is None:
        raise RuntimeError(
            "ray_tpu.train.session functions may only be called inside "
            "train_loop_per_worker")
    return ctx


def _install(ctx: TrainContext):
    _local.ctx = ctx


def report(metrics: dict, checkpoint=None) -> None:
    """Hand metrics (and optionally a checkpoint) to the trainer. Blocks
    until the driver consumed the previous report (reference: semaphore in
    session.py:288) so all workers step in lockstep."""
    ctx = _ctx()
    if ctx.stop_event.is_set():
        raise SystemExit(0)   # driver asked the loop to wind down
    ctx.result_queue.put({"metrics": dict(metrics),
                          "checkpoint": checkpoint})
    ctx.consumed.acquire()


def get_checkpoint():
    """The checkpoint to resume from, if the trainer restored one."""
    return _ctx().checkpoint


def get_world_size() -> int:
    return _ctx().world_size


def get_world_rank() -> int:
    return _ctx().world_rank


def get_local_rank() -> int:
    return _ctx().local_rank


def get_node_rank() -> int:
    return _ctx().node_rank


def get_trial_name() -> str:
    return _ctx().trial_name


def get_dataset_shard(name: str = "train"):
    """This worker's shard of a dataset passed to the trainer (reference:
    session.get_dataset_shard backed by Data streaming_split)."""
    return _ctx().dataset_shards.get(name)


def get_mesh_spec():
    """The ScalingConfig's MeshSpec (TPU-native extension)."""
    return _ctx().mesh_spec
