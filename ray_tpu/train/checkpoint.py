"""Checkpoint artifact.

Counterpart of the reference's `air/checkpoint.py:66` (`Checkpoint` —
interconvertible dict / directory / URI :449-735) and
`train/torch/torch_checkpoint.py`. TPU-native storage: pytrees of jax/numpy
arrays are written with orbax (`PyTreeCheckpointer`), everything else with
pickle, so sharded params round-trip losslessly and restore can reshard
onto a different mesh.
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
import threading

import jax
import numpy as np

_ORBAX_SUBDIR = "pytree"
_PICKLE_FILE = "data.pkl"
_counter_lock = threading.Lock()
_counter = 0


def _next_tmpdir() -> str:
    global _counter
    with _counter_lock:
        _counter += 1
        n = _counter
    d = os.path.join(tempfile.gettempdir(),
                     f"ray_tpu_ckpt_{os.getpid()}_{n}")
    os.makedirs(d, exist_ok=True)
    return d


def _is_array_tree(value) -> bool:
    leaves = jax.tree.leaves(value)
    return bool(leaves) and all(
        isinstance(l, (jax.Array, np.ndarray)) for l in leaves)


class Checkpoint:
    """A directory-backed checkpoint. Construct with `from_dict` /
    `from_directory`; read with `to_dict` / `to_directory` / `as_directory`.
    """

    def __init__(self, path: str):
        self.path = path

    # -- constructors -------------------------------------------------------

    @classmethod
    def from_dict(cls, data: dict) -> "Checkpoint":
        d = _next_tmpdir()
        arrays = {k: v for k, v in data.items() if _is_array_tree(v)}
        rest = {k: v for k, v in data.items() if k not in arrays}
        if arrays:
            import orbax.checkpoint as ocp
            ckptr = ocp.PyTreeCheckpointer()
            host_arrays = jax.tree.map(np.asarray, arrays)
            ckptr.save(os.path.join(d, _ORBAX_SUBDIR), host_arrays)
        with open(os.path.join(d, _PICKLE_FILE), "wb") as f:
            pickle.dump(rest, f, protocol=5)
        return cls(d)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    # -- accessors ----------------------------------------------------------

    def to_dict(self) -> dict:
        out = {}
        orbax_path = os.path.join(self.path, _ORBAX_SUBDIR)
        if os.path.isdir(orbax_path):
            import orbax.checkpoint as ocp
            out.update(ocp.PyTreeCheckpointer().restore(orbax_path))
        pkl = os.path.join(self.path, _PICKLE_FILE)
        if os.path.exists(pkl):
            with open(pkl, "rb") as f:
                out.update(pickle.load(f))
        return out

    def to_directory(self, path: str) -> str:
        if os.path.abspath(path) != os.path.abspath(self.path):
            shutil.copytree(self.path, path, dirs_exist_ok=True)
        return path

    def as_directory(self) -> str:
        return self.path

    def __repr__(self):
        return f"Checkpoint({self.path})"
