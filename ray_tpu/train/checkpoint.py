"""Checkpoint artifact.

Counterpart of the reference's `air/checkpoint.py:66` (`Checkpoint` —
interconvertible dict / directory / URI :449-735) and
`train/torch/torch_checkpoint.py`. TPU-native storage: pytrees of jax/numpy
arrays are written with orbax (`PyTreeCheckpointer`), everything else with
pickle, so sharded params round-trip losslessly and restore can reshard
onto a different mesh.

Dict checkpoints are held in memory (host numpy snapshots) until persisted:
no tmpdir per report() (which leaked disk for the life of the run) and no
same-host assumption when a worker ships a checkpoint to the driver.
"""

from __future__ import annotations

import atexit
import contextlib
import os
import pickle
import shutil
import tempfile
import threading
import uuid

import numpy as np

_ORBAX_SUBDIR = "pytree"
_PICKLE_FILE = "data.pkl"
_counter_lock = threading.Lock()
_counter = 0
_tmpdirs: list[str] = []


class CheckpointError(RuntimeError):
    """A checkpoint is missing, partial, or corrupt."""


def fsync_dir(path: str) -> None:
    """fsync a directory so its entries (a just-renamed checkpoint)
    survive power loss."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


@contextlib.contextmanager
def atomic_dir(path: str):
    """Write a directory atomically: yields a sibling temp dir to fill;
    on clean exit the temp dir is fsynced and renamed into place (any
    previous `path` is replaced). On error — or a crash at ANY point —
    `path` is never a half-written directory: readers see the old
    content, the new content, or nothing, so a crashed writer can never
    leave a readable partial checkpoint. The `train/ft.py` commit path
    and `Checkpoint.to_directory` both go through here."""
    path = os.path.abspath(path)
    parent = os.path.dirname(path)
    os.makedirs(parent, exist_ok=True)
    tag = f"{os.getpid()}-{uuid.uuid4().hex[:6]}"
    tmp = os.path.join(parent, f".{os.path.basename(path)}.tmp-{tag}")
    os.makedirs(tmp)
    try:
        yield tmp
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    fsync_dir(tmp)
    if os.path.lexists(path):
        # move the old version aside first: os.replace can't atomically
        # swap non-empty directories, and a crash in this window leaves
        # `path` absent (detectable), never partial
        old = os.path.join(parent, f".{os.path.basename(path)}.old-{tag}")
        if os.path.isdir(path):
            os.replace(path, old)
            shutil.rmtree(old, ignore_errors=True)
        else:
            os.unlink(path)
    os.replace(tmp, path)
    fsync_dir(parent)


def _next_tmpdir() -> str:
    global _counter
    with _counter_lock:
        _counter += 1
        n = _counter
    d = os.path.join(tempfile.gettempdir(),
                     f"ray_tpu_ckpt_{os.getpid()}_{n}")
    os.makedirs(d, exist_ok=True)
    _tmpdirs.append(d)
    return d


@atexit.register
def _cleanup_tmpdirs():
    for d in _tmpdirs:
        shutil.rmtree(d, ignore_errors=True)


def _is_array_tree(value) -> bool:
    # jax imports lazily: Checkpoint is used by tune/experiment metadata
    # paths that must stay JAX-free at import time (package docstring
    # promise in ray_tpu/__init__.py).
    import jax
    leaves = jax.tree.leaves(value)
    return bool(leaves) and all(
        isinstance(l, (jax.Array, np.ndarray)) for l in leaves)


def _tree_to_numpy(value):
    import jax
    return jax.tree.map(np.asarray, value)


class Checkpoint:
    """A dict- or directory-backed checkpoint. Construct with `from_dict` /
    `from_directory`; read with `to_dict` / `to_directory` / `as_directory`.
    """

    def __init__(self, path: str | None = None, *, _data: dict | None = None):
        self.path = path
        self._data = _data

    # -- constructors -------------------------------------------------------

    @classmethod
    def from_dict(cls, data: dict) -> "Checkpoint":
        # Snapshot arrays to host numpy now: detaches from device buffers
        # (donation-safe) and makes the object picklable across processes.
        snap = {
            k: (_tree_to_numpy(v) if _is_array_tree(v) else v)
            for k, v in data.items()
        }
        return cls(_data=snap)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    # -- accessors ----------------------------------------------------------

    def to_dict(self) -> dict:
        if self._data is not None:
            return dict(self._data)
        out = {}
        orbax_path = os.path.join(self.path, _ORBAX_SUBDIR)
        if os.path.isdir(orbax_path):
            import orbax.checkpoint as ocp
            out.update(ocp.PyTreeCheckpointer().restore(orbax_path))
        pkl = os.path.join(self.path, _PICKLE_FILE)
        if os.path.exists(pkl):
            with open(pkl, "rb") as f:
                out.update(pickle.load(f))
        return out

    def to_directory(self, path: str) -> str:
        # atomic_dir: a crash mid-write leaves no readable half-written
        # checkpoint dir for from_directory to load
        if self._data is not None:
            arrays = {k: v for k, v in self._data.items()
                      if _is_array_tree(v)}
            rest = {k: v for k, v in self._data.items() if k not in arrays}
            with atomic_dir(path) as tmp:
                if arrays:
                    import orbax.checkpoint as ocp
                    ocp.PyTreeCheckpointer().save(
                        os.path.join(tmp, _ORBAX_SUBDIR), arrays)
                with open(os.path.join(tmp, _PICKLE_FILE), "wb") as f:
                    pickle.dump(rest, f, protocol=5)
        elif os.path.abspath(path) != os.path.abspath(self.path):
            with atomic_dir(path) as tmp:
                shutil.copytree(self.path, tmp, dirs_exist_ok=True)
        return path

    def as_directory(self) -> str:
        if self._data is not None:
            # Materialize once; the dir lives until process exit.
            self.path = self.to_directory(_next_tmpdir())
            self._data = None
        return self.path

    # -- remote storage (reference: air/checkpoint.py:707/:735
    # to_uri/from_uri over remote_storage.py) -------------------------------

    def to_uri(self, uri: str) -> str:
        """Upload this checkpoint through the URI-keyed storage seam
        (ray_tpu.util.storage; mem:// fake or a registered gs:// etc.).
        The upload is COMMITTED: data files go first and a checksummed
        commit manifest lands last, so an interrupted upload is
        distinguishable from a complete one (from_uri refuses it)."""
        from ray_tpu.util import storage
        storage.upload_dir_committed(self.as_directory(), uri)
        return uri

    @classmethod
    def from_uri(cls, uri: str) -> "Checkpoint":
        """Download a COMMITTED checkpoint. Raises CheckpointError if the
        URI holds nothing, an interrupted (uncommitted) upload, or bytes
        that fail the commit manifest's checksums — never silently
        restores an empty/partial dict."""
        from ray_tpu.util import storage
        local = storage.staging_dir(uri)
        try:
            storage.download_dir_committed(uri, local)
        except storage.UncommittedError as e:
            raise CheckpointError(
                f"no restorable checkpoint at {uri!r}: {e}") from None
        return cls(local)

    def __repr__(self):
        kind = "dict" if self._data is not None else self.path
        return f"Checkpoint({kind})"
