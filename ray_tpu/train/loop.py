"""Overlapped training loop: host→device prefetch, fused multi-step
dispatch, async metrics.

The jitted step (train/spmd.py) is fast; what stalls real training is
everything AROUND it: waiting on host→device transfer of the next batch,
re-entering Python once per step to dispatch, and pulling metrics to the
host after every step. The Podracer "sebulba" split (arXiv:2104.06272)
wins TPU throughput by overlapping the host data feed with device compute
and batching many steps per dispatch; this module is that loop for the
SPMD trainers:

  * `DevicePrefetcher` — keeps `depth` sharded `device_put` transfers in
    flight ahead of the consumer, so DMA of batch N+1 rides under compute
    of step N.
  * `fuse_steps` / `TrainLoop(unroll=u)` — `lax.scan`s u steps into one
    jitted dispatch with state donation: one Python round-trip and one
    XLA launch per u steps.
  * `MetricsRing` — device-side metric handles ride in a ring and are
    fetched to host at most every `interval` steps, always from a
    dispatch that is already `lag` dispatches old, so no step ever blocks
    on a host sync.

`ray_tpu.data.Dataset.iter_device_batches` bridges `iter_batches` into a
`DevicePrefetcher`, and `bench.py` streams fresh host batches through the
whole thing.
"""

from __future__ import annotations

import collections
import itertools
import time
from typing import Any, Callable, Iterable, Iterator

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ray_tpu.parallel.sharding import logical_to_spec
from ray_tpu.util import telemetry as _telemetry

# Host-fetch seam: the ONLY place this module moves device values to the
# host. Tests monkeypatch it to assert the no-per-step-sync property.
_device_get = jax.device_get


def make_placer(mesh: Mesh, rules: dict | None = None,
                stacked: bool = False) -> Callable[[Any], Any]:
    """Host-batch placement fn: leaves go to the mesh sharded over the
    data-like axes on their leading dim (batch→data/fsdp), trailing dims
    replicated. stacked=True expects a leading unroll/group axis ahead of
    the batch dim (kept unsharded — it is the scan axis of a fused
    multi-step dispatch)."""
    spec = logical_to_spec(("batch",), rules, mesh)
    lead = [None] if stacked else []

    def place(tree):
        def put(a):
            dims = lead + list(spec)
            full = PartitionSpec(*(dims + [None] * (a.ndim - len(dims))))
            return jax.device_put(a, NamedSharding(mesh, full))
        return jax.tree.map(put, tree)
    return place


class DevicePrefetcher:
    """Double-buffered host→device prefetcher (flax `prefetch_to_device`
    idiom, sharding-aware).

    Keeps `depth` transfers in flight: `device_put` of batch N+depth is
    issued before batch N is consumed, and JAX transfers are async, so
    host→device DMA overlaps device compute. Every yielded batch is a
    FRESH device allocation — a yielded buffer is never re-filled or
    re-yielded, so a consumer that donates batch buffers into its step
    can never alias a transfer still in flight (donation-safe rotation);
    rotation is the deque of in-flight batches, bounded at `depth`.

    group=g stacks g host batches leaf-wise (leading [g, ...] axis)
    before placing — the input shape of a fused multi-step dispatch
    (`TrainLoop(unroll=g)`). A trailing ragged group is dropped and
    counted in `skipped_ragged` (it would change the compiled dispatch
    shape), so silently shortened epochs are observable.

    A host-iterator exception is never masked as end-of-stream: batches
    already transferred are still delivered in order, then the original
    exception is re-raised (and keeps re-raising — a failed feed must
    not look like a clean epoch boundary to a retrying consumer).
    """

    def __init__(self, host_iter: Iterable, place: Callable[[Any], Any],
                 *, depth: int = 2, group: int = 1):
        self._host = iter(host_iter)
        self._place = place
        self._depth = max(1, int(depth))
        self._group = max(1, int(group))
        self._buf: collections.deque = collections.deque()
        self._err: BaseException | None = None
        self._exhausted = False
        self.issued = 0         # transfers dispatched (observability)
        self.skipped_ragged = 0  # host batches dropped in a ragged tail

    def _next_host_batch(self):
        if self._group == 1:
            return next(self._host)
        parts = list(itertools.islice(self._host, self._group))
        if len(parts) < self._group:
            self.skipped_ragged += len(parts)
            raise StopIteration
        return jax.tree.map(lambda *xs: np.stack(xs), *parts)

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        while (not self._exhausted and self._err is None
                and len(self._buf) < self._depth):
            try:
                self._buf.append(self._place(self._next_host_batch()))
                self.issued += 1
            except StopIteration:
                self._exhausted = True
            except Exception as e:
                self._err = e
        if self._buf:
            return self._buf.popleft()
        if self._err is not None:
            raise self._err
        raise StopIteration


class MetricsRing:
    """Device-side metrics ring with bounded, lagged host fetches.

    `push` stores the device pytree a dispatch returned (no sync);
    entries are fetched to host at most every `interval` steps, and only
    once they are at least `lag` dispatches old — by then the device has
    long finished computing them (the loop has dispatched past them), so
    the `device_get` returns without stalling the device queue. `drain`
    fetches everything left (the one deliberate end-of-run sync).
    """

    def __init__(self, interval: int = 10, lag: int = 2):
        self.interval = max(1, int(interval))
        self.lag = max(0, int(lag))
        self._pending: collections.deque = collections.deque()
        self.history: list = []
        self.fetches = 0        # host syncs performed (tests assert this)
        self._steps_pushed = 0
        self._last_sync = 0

    def push(self, metrics, count: int = 1) -> None:
        """Store one dispatch's device metrics (`count` = steps in the
        dispatch; leaves carry a leading [count] axis when count > 1)."""
        self._pending.append((count, metrics))
        self._steps_pushed += count
        if (self._steps_pushed - self._last_sync >= self.interval
                and len(self._pending) > self.lag):
            self._sync(keep=self.lag)
            self._last_sync = self._steps_pushed

    def _sync(self, keep: int) -> None:
        """ONE host fetch covering every pending entry older than the
        newest `keep` dispatches."""
        take = len(self._pending) - keep
        if take <= 0:
            return
        items = [self._pending.popleft() for _ in range(take)]
        # graftlint: disable-next-line=R001 intentional lagged fetch: fires at most every `interval` pushed steps and only for entries >= `lag` dispatches old, so the device queue is never drained behind the live dispatch
        hosts = _device_get([m for _, m in items])
        self.fetches += 1
        for (count, _), host in zip(items, hosts):
            if count == 1:
                self.history.append(host)
            else:
                self.history.extend(
                    jax.tree.map(lambda a, i=i: a[i], host)
                    for i in range(count))

    def drain(self) -> list:
        self._sync(keep=0)
        # Reset the cadence counters so a ring reused across runs starts
        # the next run's interval from zero instead of inheriting stale
        # push counts (which either fired a fetch on the first push or
        # deferred one for a whole extra interval).
        self._steps_pushed = 0
        self._last_sync = 0
        return self.history


def fuse_steps(step_fn: Callable, unroll: int,
               donate: bool = True,
               on_trace: Callable[[], None] | None = None) -> Callable:
    """One jitted dispatch running `unroll` chained steps via lax.scan.

    step_fn: (state, batch) -> (state, metrics); jitted is fine (the
    inner pjit inlines under the outer trace). The fused call takes
    batch leaves stacked [unroll, ...] and returns metrics stacked the
    same way. State is donated across the dispatch, so param/opt
    buffers update in place exactly as in the single-step path.

    on_trace (if given) is called once per python trace of the fused
    dispatch — the compile-once counter seam the retrace sentinel
    watches, same idiom as the engine's `decode_traces`.
    """
    unroll = int(unroll)
    if unroll < 1:
        raise ValueError(f"unroll must be >= 1, got {unroll}")

    def multi(state, stacked):
        if on_trace is not None:
            on_trace()
        return jax.lax.scan(step_fn, state, stacked)

    kwargs = {"donate_argnums": (0,)} if donate else {}
    return jax.jit(multi, **kwargs)


class TrainLoop:
    """Overlap-aware driver around a (state, batch) -> (state, metrics)
    step.

    Builds its dispatch once (so repeated `run` calls — warmup then the
    timed region — hit the same jit cache): the step itself for
    unroll=1, `fuse_steps(step_fn, unroll)` otherwise. Metrics go
    through a `MetricsRing` (host fetch at most every
    `metrics_interval` steps, `metrics_lag` dispatches behind); `run`
    returns the drained per-step host metrics, so the only blocking
    sync is at the very end of each run.
    """

    def __init__(self, step_fn: Callable, *, unroll: int = 1,
                 metrics_interval: int = 10, metrics_lag: int = 2,
                 donate: bool = True, checkpointer=None,
                 publisher: Callable | None = None,
                 flops_per_step: float | None = None):
        self.unroll = max(1, int(unroll))
        self.metrics_interval = metrics_interval
        self.metrics_lag = metrics_lag
        # Compile-once accounting for the fused dispatch (engine idiom:
        # the counter increments inside the traced fn, once per trace).
        # For unroll=1 the dispatch is the caller's step_fn — its jit
        # cache isn't ours to instrument, so the watch is unroll>1 only.
        self.dispatch_traces = 0

        def _count_trace():
            self.dispatch_traces += 1

        self._dispatch = (step_fn if self.unroll == 1
                          else fuse_steps(step_fn, self.unroll, donate,
                                          on_trace=_count_trace))
        self.last_ring: MetricsRing | None = None
        # Step-time breakdown of the last run (host-side perf_counter
        # timers only — no device syncs beyond the ones already there),
        # MFU/goodput derived from it, and the retrace sentinel.
        self.last_breakdown: dict = {}
        self.flops_per_step = flops_per_step
        self.last_mfu = 0.0
        self.last_goodput = 0.0
        self.name = _telemetry.next_name("train")
        self.sentinel = _telemetry.RetraceSentinel(self.name)
        if self.unroll > 1:
            self.sentinel.watch("dispatch",
                                lambda: self.dispatch_traces, cap=1,
                                registered=True)
        _telemetry.register_stats_source(self.name, self, kind="train")
        # Optional train/ft.AsyncCheckpointer (any object with
        # maybe_snapshot(state, step) + flush()). Mutable attribute so a
        # compiled loop can toggle checkpointing between runs without
        # rebuilding (and re-tracing) the fused dispatch.
        self.checkpointer = checkpointer
        # Optional weight publisher `publisher(state, step)` — the RL
        # flywheel's seam (rl.FlywheelLoop wires it to
        # InferenceEngine.update_params). Called at the same
        # donation-safety point as the checkpointer: after a dispatch
        # returns and BEFORE the next dispatch donates the state's
        # buffers, so a publisher that device-copies (update_params
        # does) never races the training step. Mutable for the same
        # reason as `checkpointer`.
        self.publisher = publisher

    def run(self, state, device_batches: Iterable,
            num_steps: int | None = None, *, start_step: int = 0):
        """Drive steps until `num_steps` TOTAL steps are reached (or the
        batch iterator ends). `device_batches` yields one pytree per
        DISPATCH: leaves [B, ...] for unroll=1, [unroll, B, ...]
        otherwise — exactly what `DevicePrefetcher(group=unroll)`
        produces. Returns (state, per-step host metrics list).

        start_step seeds the global step counter for elastic resume
        (ft.restore_resharded): the caller fast-forwards the host
        iterator past the first `start_step` batches and the loop picks
        up checkpoint cadence from there, so `num_steps` keeps meaning
        "train through step N" across kills and restarts."""
        ring = MetricsRing(self.metrics_interval, self.metrics_lag)
        self.last_ring = ring
        ckpt = self.checkpointer
        done = int(start_step)
        # Host-side step-time breakdown: perf_counter around each host
        # activity of the loop. These time where the HOST thread waits
        # (the overlap design's whole point is keeping these small) and
        # add no device syncs — the no-host-sync tests monkeypatch
        # `_device_get` and still see only the ring's lagged fetches.
        pc = time.perf_counter
        prefetch_s = dispatch_s = metrics_s = 0.0
        checkpoint_s = publish_s = 0.0
        t_run = pc()
        it = iter(device_batches)
        while True:
            t0 = pc()
            try:
                batch = next(it)
            except StopIteration:
                prefetch_s += pc() - t0
                break
            t1 = pc()
            state, metrics = self._dispatch(state, batch)
            t2 = pc()
            ring.push(metrics, count=self.unroll)
            t3 = pc()
            done += self.unroll
            # Snapshot/publish BEFORE the next dispatch donates these
            # buffers: both hooks device-copy what they keep, which is
            # the donation-safety seam (ft.AsyncCheckpointer docstring;
            # engine.update_params copies into its own buffers).
            if ckpt is not None:
                ckpt.maybe_snapshot(state, done)
            t4 = pc()
            if self.publisher is not None:
                self.publisher(state, done)
            t5 = pc()
            prefetch_s += t1 - t0
            dispatch_s += t2 - t1
            metrics_s += t3 - t2
            checkpoint_s += t4 - t3
            publish_s += t5 - t4
            if self.unroll > 1:
                self.sentinel.check()
            if num_steps is not None and done >= num_steps:
                break
        if ckpt is not None:
            t0 = pc()
            ckpt.flush()
            checkpoint_s += pc() - t0
        t0 = pc()
        out = ring.drain()
        metrics_s += pc() - t0
        total_s = pc() - t_run
        steps_run = done - int(start_step)
        denom = max(total_s, 1e-12)
        self.last_breakdown = {
            "steps": steps_run,
            "total_s": total_s,
            "prefetch_s": prefetch_s,
            "dispatch_s": dispatch_s,
            "metrics_s": metrics_s,
            "checkpoint_s": checkpoint_s,
            "publish_s": publish_s,
            "prefetch_share": prefetch_s / denom,
            "dispatch_share": dispatch_s / denom,
            "metrics_share": metrics_s / denom,
            "checkpoint_share": checkpoint_s / denom,
            "publish_share": publish_s / denom,
        }
        # Host goodput: fraction of wall time the host spends inside
        # device dispatch (i.e. not stalled on data, checkpoint or
        # metrics plumbing). MFU needs the model's flop estimate.
        self.last_goodput = dispatch_s / denom
        if self.flops_per_step and steps_run:
            self.last_mfu = _telemetry.mfu(
                self.flops_per_step * steps_run / denom)
        return state, out

    def stats(self) -> dict:
        """Telemetry-bridge stats dict (util.telemetry republishes these
        as train_* gauges at every /metrics scrape): the last run's
        step-time breakdown plus MFU/goodput and the fused-dispatch
        compile-once accounting."""
        return {
            "dispatch_traces": self.dispatch_traces,
            "retraces_unexpected": self.sentinel.retraces_unexpected,
            "unroll": self.unroll,
            "mfu": self.last_mfu,
            "goodput": self.last_goodput,
            **self.last_breakdown,
        }


def run_steps(step_fn: Callable, state, device_batches: Iterable,
              *, num_steps: int | None = None, unroll: int = 1,
              metrics_interval: int = 10, metrics_lag: int = 2):
    """One-shot convenience over `TrainLoop` (build + run). Prefer
    holding a `TrainLoop` when calling more than once — each `run_steps`
    call with unroll > 1 builds (and re-compiles) its own fused
    dispatch."""
    loop = TrainLoop(step_fn, unroll=unroll,
                     metrics_interval=metrics_interval,
                     metrics_lag=metrics_lag)
    return loop.run(state, device_batches, num_steps=num_steps)
