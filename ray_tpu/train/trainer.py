"""JaxTrainer — the Train-equivalent's DataParallelTrainer.

Reference call stack being replaced (SURVEY.md §3.3): `TorchTrainer.fit` ->
Tune trial -> BackendExecutor -> WorkerGroup -> torch DDP. Differences by
design:

- Runs standalone (no mandatory Tune coupling — SURVEY.md §7.2 M6 calls the
  reference's Train->Tune indirection accidental complexity). The Tune-equiv
  wraps *this*, not vice versa.
- Rendezvous is `jax.distributed.initialize` + a Mesh over all workers'
  devices; gradients sync as `psum` inside the user's jitted step, not via
  a DDP wrapper.
- One worker == one host process (JAX is SPMD per process over all local
  chips), not one device.
"""

from __future__ import annotations

import logging
import os
import shutil
import time
from dataclasses import dataclass, field

import ray_tpu
from ray_tpu.actor import wait_for_actor_ready
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.worker_group import make_worker_group
from ray_tpu.util.placement_group import (
    placement_group,
    remove_placement_group,
)

logger = logging.getLogger("ray_tpu.train")


class TrainingFailedError(RuntimeError):
    pass


@dataclass
class Result:
    """Counterpart of `air/result.py` Result."""
    metrics: dict = field(default_factory=dict)
    checkpoint: Checkpoint | None = None
    error: str | None = None
    metrics_history: list = field(default_factory=list)
    path: str | None = None

    @property
    def best_checkpoints(self):
        return [(self.checkpoint, self.metrics)] if self.checkpoint else []


class JaxTrainer:
    """Distributed JAX training over a worker group.

    train_loop_per_worker(config) runs on every worker; inside it, use
    `ray_tpu.train.session` (report / get_checkpoint / get_dataset_shard /
    get_mesh_spec) exactly like the reference's session API.
    """

    def __init__(self,
                 train_loop_per_worker,
                 *,
                 train_loop_config: dict | None = None,
                 scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None,
                 datasets: dict | None = None,
                 resume_from_checkpoint: Checkpoint | None = None):
        self.train_loop = train_loop_per_worker
        self.config = dict(train_loop_config or {})
        self.scaling = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = dict(datasets or {})
        self.resume_checkpoint = resume_from_checkpoint
        # When running as a Tune trial, the controller's gang reservation
        # is handed down here (bundle 0 = trial executor, 1..N = our
        # workers) — we fill it instead of creating a second group.
        self._external_pg = None

    # ------------------------------------------------------------------

    def _make_shards(self, rank: int, world: int) -> dict:
        shards = {}
        for name, ds in self.datasets.items():
            if hasattr(ds, "streaming_split_shard"):
                shards[name] = ds.streaming_split_shard(rank, world)
            elif hasattr(ds, "split"):
                shards[name] = ds.split(world)[rank]
            else:
                shards[name] = ds
        return shards

    def _create_workers(self, trial_name: str):
        sc = self.scaling
        res = sc.worker_resources()
        if self._external_pg is not None:
            workers = make_worker_group(
                sc.num_workers, res, trial_name,
                placement_group=self._external_pg, bundle_offset=1,
                env_vars={})
            return workers, None        # not ours to remove
        pg = placement_group([dict(res) for _ in range(sc.num_workers)],
                             strategy=sc.placement_strategy)
        workers = make_worker_group(sc.num_workers, res, trial_name,
                                    placement_group=pg, env_vars={})
        return workers, pg

    # subclass seam: which TrainWorker method performs the collective
    # rendezvous, and whether a 1-worker group still needs one (torch DDP
    # requires an initialized process group even at world_size=1)
    _rendezvous_method = "setup_distributed"
    _always_rendezvous = False

    def _setup_workers(self, workers, checkpoint):
        sc = self.scaling
        for w in workers:
            wait_for_actor_ready(w, timeout=180)
        if sc.num_workers > 1 or self._always_rendezvous:
            # Rendezvous address probed on worker 0's host, not the driver.
            coordinator = ray_tpu.get(
                workers[0].get_coordinator_address.remote(), timeout=60)
            ray_tpu.get([
                getattr(w, self._rendezvous_method).remote(
                    coordinator, sc.num_workers, i)
                for i, w in enumerate(workers)], timeout=300)
        ray_tpu.get([
            w.start_training.remote(
                self.train_loop, self.config,
                checkpoint=checkpoint,
                dataset_shards=self._make_shards(i, sc.num_workers),
                mesh_spec=sc.mesh)
            for i, w in enumerate(workers)], timeout=300)

    def _teardown(self, workers, pg):
        for w in workers:
            try:
                w.shutdown_loop.remote()
                ray_tpu.kill(w)
            except Exception:
                pass
        if pg is not None:
            try:
                remove_placement_group(pg)
            except Exception:
                pass

    def _persist_checkpoint(self, ckpt, storage: str, iteration: int,
                            kept: list):
        from ray_tpu.util import storage as storage_mod
        name = f"checkpoint_{iteration:06d}"
        if storage_mod.is_uri(storage):
            # write locally (staging), then push through the URI-keyed
            # backend; on a pod the run dir isn't a shared filesystem
            # (reference: Checkpoint.to_uri + remote_storage.py)
            local_root = storage_mod.staging_dir(storage)
            dest = os.path.join(local_root, name)
            ckpt.to_directory(dest)
            uri = storage_mod.uri_join(storage, name)
            try:
                storage_mod.upload_dir_committed(dest, uri)
            except Exception:
                # transient remote-storage failure must not kill the
                # run: the local checkpoint is intact (same policy as
                # the Tune sync path, tune/experiment.py)
                logger.exception("checkpoint upload to %s failed", uri)
        else:
            dest = os.path.join(storage, name)
            ckpt.to_directory(dest)
            uri = None
        kept.append((dest, uri))
        limit = self.run_config.checkpoint_config.num_to_keep
        while limit and len(kept) > limit:
            old_dest, old_uri = kept.pop(0)
            shutil.rmtree(old_dest, ignore_errors=True)
            if old_uri is not None:
                try:
                    storage_mod.delete(old_uri)
                except Exception:
                    logger.exception("remote checkpoint delete failed "
                                     "(%s)", old_uri)
        return Checkpoint(dest)

    # ------------------------------------------------------------------

    def fit(self) -> Result:
        from ray_tpu.util import storage as storage_mod
        trial_name = self.run_config.name or f"train_{int(time.time())}"
        storage = self.run_config.resolved_storage_path()
        if not storage_mod.is_uri(storage):
            os.makedirs(storage, exist_ok=True)
        max_failures = self.run_config.failure_config.max_failures
        failures = 0
        latest_ckpt = self.resume_checkpoint
        history: list = []
        kept: list = []

        while True:
            workers, pg = None, None
            error = None
            try:
                # Creation/setup failures (actor-ready timeout, rendezvous
                # errors) must hit the same teardown + FailureConfig path as
                # mid-training failures, not leak the placement group.
                workers, pg = self._create_workers(trial_name)
                self._setup_workers(workers, latest_ckpt)
                while True:
                    results = ray_tpu.get(
                        [w.next_result.remote() for w in workers])
                    errs = [r["error"] for r in results if "error" in r]
                    if errs:
                        error = errs[0]
                        break
                    if any(r.get("done") for r in results):
                        break
                    head = results[0]
                    metrics = head["metrics"]
                    metrics["_iteration"] = len(history)
                    history.append(metrics)
                    if head.get("checkpoint") is not None:
                        latest_ckpt = self._persist_checkpoint(
                            head["checkpoint"], storage, len(history), kept)
            except (ray_tpu.exceptions.RayTpuError, TimeoutError) as e:
                error = f"worker group failed: {e!r}"
            finally:
                if workers is not None:
                    self._teardown(workers, pg)
                elif pg is not None:
                    remove_placement_group(pg)

            if error is None:
                return Result(
                    metrics=history[-1] if history else {},
                    checkpoint=latest_ckpt,
                    metrics_history=history,
                    path=storage)
            failures += 1
            if max_failures != -1 and failures > max_failures:
                return Result(
                    metrics=history[-1] if history else {},
                    checkpoint=latest_ckpt,
                    error=error,
                    metrics_history=history,
                    path=storage)
            logger.warning(
                "training failed (attempt %d/%s), restarting from last "
                "checkpoint: %s", failures, max_failures, error[-500:])
