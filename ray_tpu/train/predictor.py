"""Predictors + batch inference over datasets.

Counterpart of the reference's predictor stack: `Predictor`
(`train/predictor.py`), the torch/tf predictors
(`train/torch/torch_predictor.py`, `_internal/dl_predictor.py`), and
`BatchPredictor` (`train/batch_predictor.py`) which maps a
checkpoint-loaded model over a Dataset with an autoscaling actor pool —
the GPU/TPU batch-inference path (`ActorPoolMapOperator`,
`data/_internal/execution/operators/actor_pool_map_operator.py:34`).

TPU-first shape: a JaxPredictor owns one jitted apply function; batches
arrive as numpy, ride device_put once, and results come back as numpy.
Model state loads once per actor (the whole point of the actor-pool
path), so weights transfer per-actor, not per-batch.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from ray_tpu.train.checkpoint import Checkpoint


class Predictor:
    """Base: subclass with `_predict_numpy` (reference: Predictor)."""

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, **kwargs) -> "Predictor":
        raise NotImplementedError

    def predict(self, data, **kwargs):
        if isinstance(data, dict):
            return self._predict_numpy(data, **kwargs)
        arr = np.asarray(data)
        return self._predict_numpy({"__value__": arr}, **kwargs)

    def _predict_numpy(self, batch: Dict[str, np.ndarray], **kwargs):
        raise NotImplementedError


class JaxPredictor(Predictor):
    """Wraps (apply_fn, params): apply_fn(params, batch_array) -> output.

    `input_column` picks the feature column of dict batches ("__value__"
    for plain-array datasets); output lands in `output_column`.
    """

    def __init__(self, apply_fn: Callable, params: Any,
                 input_column: str = "__value__",
                 output_column: str = "predictions",
                 jit: bool = True):
        import jax
        self._apply = jax.jit(apply_fn) if jit else apply_fn
        self._params = params
        self.input_column = input_column
        self.output_column = output_column

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, *,
                        apply_fn: Callable, **kwargs) -> "JaxPredictor":
        state = checkpoint.to_dict()
        params = state.get("params", state)
        return cls(apply_fn, params, **kwargs)

    def _predict_numpy(self, batch: Dict[str, np.ndarray], **kwargs):
        import jax.numpy as jnp
        col = self.input_column if self.input_column in batch \
            else next(iter(batch))
        out = self._apply(self._params, jnp.asarray(batch[col]))
        result = dict(batch)
        result[self.output_column] = np.asarray(out)
        return result


class BatchPredictor:
    """Map a checkpoint-loaded predictor over a Dataset
    (reference: BatchPredictor.predict)."""

    def __init__(self, checkpoint: Checkpoint, predictor_cls,
                 **predictor_kwargs):
        self._checkpoint = checkpoint
        self._predictor_cls = predictor_cls
        self._predictor_kwargs = predictor_kwargs

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, predictor_cls,
                        **kwargs) -> "BatchPredictor":
        return cls(checkpoint, predictor_cls, **kwargs)

    def predict(self, dataset, *, batch_size: int = 1024,
                num_tpus_per_actor: float = 0,
                min_actors: int = 1, max_actors: Optional[int] = None,
                keep_columns: Optional[list] = None):
        """-> Dataset with the prediction column appended. The predictor
        loads once per pool actor; batches stream through the actor pool
        (the reference's ActorPoolMapOperator path)."""
        from ray_tpu.data.dataset import ActorPoolStrategy

        checkpoint = self._checkpoint
        predictor_cls = self._predictor_cls
        predictor_kwargs = self._predictor_kwargs
        keep = keep_columns

        class _PredictUDF:
            def __init__(self):
                self.predictor = predictor_cls.from_checkpoint(
                    checkpoint, **predictor_kwargs)

            def __call__(self, batch):
                out = self.predictor._predict_numpy(batch)
                if keep is not None:
                    out = {k: v for k, v in out.items()
                           if k in keep or
                           k == self.predictor.output_column}
                return out

        pool = ActorPoolStrategy(
            min_size=min_actors, max_size=max_actors or max(min_actors, 2))
        return dataset.map_batches(
            _PredictUDF, batch_size=batch_size, compute=pool,
            num_tpus=num_tpus_per_actor or None)
