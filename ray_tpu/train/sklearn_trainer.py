"""SklearnTrainer — fit a scikit-learn estimator on a cluster worker.

Counterpart of the reference's `train/sklearn/sklearn_trainer.py`: the
estimator trains in ONE remote worker (sklearn is not data-parallel;
`n_jobs` threads parallelize inside it), datasets materialize from
ray_tpu.data, and the fitted estimator comes back as a dict checkpoint.
"""

from __future__ import annotations

import time

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.trainer import Result


def _fit_remote(estimator, datasets: dict, label_column: str,
                score: bool):
    import numpy as np

    # materialize ON the worker (the driver never holds the rows)
    blocks = {name: ds.take_all() if hasattr(ds, "take_all") else ds
              for name, ds in datasets.items()}
    # ONE canonical feature order shared by every split — per-split
    # dict insertion order could silently misalign train vs valid
    feats = sorted(k for k in blocks["train"][0] if k != label_column)

    def to_xy(rows):
        y = np.asarray([r[label_column] for r in rows])
        x = np.column_stack([
            np.asarray([r[k] for r in rows]) for k in feats])
        return x, y

    x, y = to_xy(blocks["train"])
    t0 = time.time()
    estimator.fit(x, y)
    metrics = {"fit_time_s": time.time() - t0}
    if score:
        metrics["train_score"] = float(estimator.score(x, y))
    if "valid" in blocks:
        xv, yv = to_xy(blocks["valid"])
        metrics["valid_score"] = float(estimator.score(xv, yv))
    return estimator, metrics


class SklearnTrainer:
    def __init__(self, estimator, *, label_column: str,
                 datasets: dict,
                 scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None,
                 score: bool = True):
        self.estimator = estimator
        self.label_column = label_column
        self.datasets = dict(datasets)
        self.scaling = scaling_config or ScalingConfig()
        if self.scaling.num_workers > 1:
            raise ValueError(
                "SklearnTrainer fits on ONE worker (sklearn is not "
                "data-parallel; use n_jobs inside the estimator and "
                "CPU in resources_per_worker)")
        self.run_config = run_config or RunConfig()
        self.score = score

    def fit(self) -> Result:
        import ray_tpu
        res = self.scaling.worker_resources()
        fit = ray_tpu.remote(
            num_cpus=res.get("CPU", 1.0))(_fit_remote)
        try:
            est, metrics = ray_tpu.get(
                fit.remote(self.estimator, self.datasets,
                           self.label_column, self.score),
                timeout=3600)
        except Exception as e:   # surface the worker traceback
            return Result(error=repr(e))
        return Result(metrics=metrics,
                      checkpoint=Checkpoint.from_dict({"estimator": est}),
                      metrics_history=[metrics])
