"""Train worker actors.

Counterpart of the reference's `train/_internal/worker_group.py:100`
(WorkerGroup of plain `ray.remote` actors) + `backend_executor.py:45`
(start :104, start_training :342) + the torch rendezvous
(`train/torch/config.py:70-121`) — whose TPU-native replacement is
`jax.distributed.initialize(coordinator, num_processes, process_id)`
followed by mesh construction (SURVEY.md §2.3).
"""

from __future__ import annotations

import os
import queue
import threading
import traceback

import ray_tpu
from ray_tpu.train import session as session_mod


class TrainWorker:
    """Actor hosting one training process. The user loop runs in a daemon
    thread; the actor thread serves `next_result` (reference pattern:
    session.py:81)."""

    def __init__(self, rank: int, world_size: int, trial_name: str):
        self.rank = rank
        self.world_size = world_size
        self.trial_name = trial_name
        self.thread: threading.Thread | None = None
        self.ctx: session_mod.TrainContext | None = None
        self.error: str | None = None
        self.finished = False

    def get_coordinator_address(self) -> str:
        """Pick the rendezvous address ON THIS WORKER's host (rank 0) — the
        reference does the same on the rank-0 torch worker
        (`train/torch/config.py:113`); probing on the driver would hand out
        a port only valid when driver and worker 0 share a machine."""
        import socket
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        host = os.environ.get("RAY_TPU_NODE_IP") or socket.gethostbyname(
            socket.gethostname())
        return f"{host}:{port}"

    def setup_distributed(self, coordinator: str, num_processes: int,
                          process_id: int):
        """TPU-native rendezvous (replaces dist.init_process_group)."""
        import jax
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id)
        return jax.device_count()

    def setup_torch_distributed(self, coordinator: str,
                                num_processes: int, process_id: int):
        """torch.distributed rendezvous over gloo (the reference's
        `_setup_torch_process_group`, train/torch/config.py:70-113;
        gloo because these workers are CPU hosts — TPU compute runs
        through the JAX backend instead of NCCL)."""
        import torch.distributed as dist
        dist.init_process_group(
            "gloo", init_method=f"tcp://{coordinator}",
            world_size=num_processes, rank=process_id)
        return dist.get_world_size()

    def setup_tf_config(self, coordinator: str, num_processes: int,
                        process_id: int):
        """Render TF_CONFIG for MultiWorkerMirroredStrategy (the
        reference's `train/tensorflow/config.py:21` _setup_tensorflow_
        environment): the coordinator's host gets port+1+rank per rank
        so every worker lists the same cluster spec. Must run BEFORE
        any tensorflow import in the training loop.

        v1 scope: SINGLE-HOST worker groups — the spec lists every rank
        on the coordinator's host, so a rank on another machine could
        never bind its own entry. Multi-host needs a per-worker address
        gather (the reference collects each worker's own ip:port);
        detect and refuse rather than fail inside TF's gRPC server."""
        import json
        import os
        import socket
        host, port = coordinator.rsplit(":", 1)
        own = {socket.gethostbyname(socket.gethostname()),
               os.environ.get("RAY_TPU_NODE_IP"),
               "127.0.0.1", "localhost"}
        if host not in own:
            raise NotImplementedError(
                f"TensorflowTrainer v1 supports single-host worker "
                f"groups only (rank {process_id} cannot bind an address "
                f"on coordinator host {host}); use JaxTrainer for "
                "multi-host TPU training")
        my_port = int(port) + 1 + process_id
        # fail with a CLEAR error if our assigned port is taken (the
        # +1..+N ports are derived, not reserved) instead of dying
        # inside TF's gRPC server with address-in-use
        probe = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            probe.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            probe.bind((host if host != "localhost" else "127.0.0.1",
                        my_port))
        except OSError as e:
            raise RuntimeError(
                f"TF_CONFIG port {my_port} for rank {process_id} is "
                f"already in use ({e}); another service or concurrent "
                "TF trial holds it — rerun to get a fresh port range")
        finally:
            probe.close()
        workers = [f"{host}:{int(port) + 1 + i}"
                   for i in range(num_processes)]
        os.environ["TF_CONFIG"] = json.dumps({
            "cluster": {"worker": workers},
            "task": {"type": "worker", "index": process_id},
        })
        return num_processes

    def device_info(self):
        import jax
        return {"backend": jax.default_backend(),
                "local": jax.local_device_count(),
                "global": jax.device_count()}

    def start_training(self, train_loop, config: dict,
                       checkpoint=None, dataset_shards: dict | None = None,
                       mesh_spec=None):
        self.ctx = session_mod.TrainContext(
            world_size=self.world_size,
            world_rank=self.rank,
            local_rank=0,
            node_rank=self.rank,
            trial_name=self.trial_name,
            checkpoint=checkpoint,
            dataset_shards=dataset_shards or {},
            result_queue=queue.Queue(maxsize=1),
            consumed=threading.Semaphore(0),
            stop_event=threading.Event(),
        )
        self.ctx.mesh_spec = mesh_spec

        import inspect
        try:
            takes_config = bool(
                inspect.signature(train_loop).parameters)
        except (TypeError, ValueError):
            takes_config = True

        def run():
            session_mod._install(self.ctx)
            try:
                if takes_config:
                    train_loop(config)
                else:
                    train_loop()
                self.finished = True
            except SystemExit:
                self.finished = True
            except BaseException:
                self.error = traceback.format_exc()
            finally:
                # Sentinel unblocks the driver's pending next_result.
                self.ctx.result_queue.put(None)

        self.thread = threading.Thread(target=run, daemon=True,
                                       name="train-loop")
        self.thread.start()
        return True

    def next_result(self):
        """Blocks until the train loop reports, finishes, or errors.
        Returns {"metrics":..., "checkpoint":...} | {"done": True} |
        {"error": traceback_str}."""
        item = self.ctx.result_queue.get()
        if item is None:
            if self.error:
                return {"error": self.error}
            return {"done": True}
        # Let the loop proceed with its next step while the driver digests
        # this one (bounded pipelining, queue size 1).
        self.ctx.consumed.release()
        return item

    def shutdown_loop(self):
        if self.ctx is not None:
            self.ctx.stop_event.set()
            self.ctx.consumed.release()
        return True


def make_worker_group(num_workers: int, resources: dict, trial_name: str,
                      placement_group=None, env_vars: dict | None = None,
                      bundle_offset: int = 0):
    """Spawn the actor group (one placement-group bundle per worker).
    `bundle_offset` skips leading bundles when the group is placed inside
    a larger reservation (a Tune trial's PG, whose bundle 0 is the trial
    executor)."""
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy,
    )
    opts = dict(resources or {})
    num_cpus = opts.pop("CPU", 1.0)
    num_tpus = opts.pop("TPU", 0.0)
    cls = ray_tpu.remote(TrainWorker)
    workers = []
    for rank in range(num_workers):
        o = dict(num_cpus=num_cpus, resources=opts,
                 runtime_env={"env_vars": dict(env_vars or {})})
        if num_tpus:
            o["num_tpus"] = num_tpus
        if placement_group is not None:
            o["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                placement_group=placement_group,
                placement_group_bundle_index=rank + bundle_offset)
        workers.append(cls.options(**o).remote(
            rank, num_workers, trial_name))
    return workers
