"""TensorflowTrainer — distributed TF over the worker-group spine.

Counterpart of the reference's `train/tensorflow/tensorflow_trainer.py`
+ `train/tensorflow/config.py` (TF_CONFIG rendezvous): the worker
group, session API, checkpointing, and FailureConfig restarts are
IDENTICAL to JaxTrainer — the only difference is the rendezvous, which
renders TF_CONFIG (cluster spec + task index) into each worker's env
before the training loop runs, so a
`tf.distribute.MultiWorkerMirroredStrategy()` built inside the loop
discovers its peers (tested for real: the MWMS gradient-sync regression
in tests/test_train.py). Construction raises a clear ImportError when
tensorflow is absent, same gating as the GBDT library adapters.
"""

from __future__ import annotations

from ray_tpu.train.trainer import JaxTrainer


class TensorflowTrainer(JaxTrainer):
    _rendezvous_method = "setup_tf_config"
    _always_rendezvous = True     # TF_CONFIG is needed even at world=1

    def __init__(self, *args, **kwargs):
        import importlib.util
        if importlib.util.find_spec("tensorflow") is None:
            # find_spec, not import: gating must not load hundreds of
            # MB of TF into the driver (only workers use it)
            raise ImportError(
                "TensorflowTrainer requires the 'tensorflow' package; "
                "on TPU use JaxTrainer (the native path) instead")
        super().__init__(*args, **kwargs)


def prepare_dataset_shard(dataset):
    """Reference-parity passthrough (`train/tensorflow/train_loop_utils
    .py` prepare_dataset_shard): with TF_CONFIG sharding, the dataset
    shard needs no further transformation here."""
    return dataset
