"""Elastic fault-tolerant training: async sharded checkpointing + resume.

A training job must survive losing a host without losing the run
(ROADMAP item 4; Ray arXiv:1712.05889 makes recovery a property of the
runtime, not the application). Two pieces:

  * `AsyncCheckpointer` — every `every` steps, takes a DONATION-SAFE
    device-side copy of the full TrainState (a jitted `jnp.copy` of
    every leaf, dispatched asynchronously like any other step — the
    training loop donates its state buffers into the next dispatch, so
    the copy is the only thing that may outlive the step). A background
    writer thread then moves each copy to host and commits it to disk,
    so the device→host fetch rides under later steps' compute exactly
    like `MetricsRing`'s lagged metric fetches (train/loop.py): no
    training step ever blocks on a host sync. In-flight snapshots are
    bounded (`max_in_flight`), so HBM/host memory stays flat no matter
    how slow the filesystem is — when the bound is hit the *snapshot*
    (not the step) waits for the writer.

  * Atomic commit — shards, a pickled tree skeleton, and a manifest
    carrying per-shard sha256 checksums + the PartitionSpec each leaf
    was saved under are written into a temp dir; the manifest is
    fsynced and the directory renamed into place LAST
    (train/checkpoint.py `atomic_dir`). A writer killed at any point
    leaves either a previous committed checkpoint or an ignorable temp
    dir — never a readable half-checkpoint.

  * `restore_resharded` — re-forms training state on a mesh that may
    have a DIFFERENT device count: mesh axis names are stable across
    scale changes (parallel/mesh.py keeps size-1 axes), so each leaf's
    recorded PartitionSpec re-applies to the new mesh after
    `sharding.valid_spec_for` re-validation (axes that vanished or no
    longer divide degrade to replication). `TrainLoop.run(...,
    start_step=k)` with a `fast_forward`ed data iterator then resumes
    the trajectory bit-identically (same device count) from the
    restored step.

The chaos proof lives in tests/test_chaos.py: a trainer host is
SIGKILLed mid-run, the job resumes from the last committed step — at
the same or a smaller device count — and the post-resume loss
trajectory matches an unkilled run.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import queue
import re
import shutil
import threading
from typing import Any, Iterable, Iterator

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding

from ray_tpu.parallel.sharding import (
    spec_from_json,
    spec_to_json,
    valid_spec_for,
)
from ray_tpu.train.checkpoint import CheckpointError, atomic_dir
from ray_tpu.util import tracing as _tracing

MANIFEST = "manifest.json"
_SKELETON = "skeleton.pkl"
_FORMAT = "ray_tpu_ft_v1"
_STEP_RE = re.compile(r"^step_(\d{8})$")

# Host-fetch seam (same contract as train/loop.py:_device_get): the ONLY
# place this module moves device values to the host. Tests monkeypatch it
# to prove snapshotting adds no per-step sync on the training thread.
_device_get = jax.device_get


class _ShardRef:
    """Placeholder leaf in the pickled tree skeleton: `index` names the
    shard file holding the real array."""

    def __init__(self, index: int):
        self.index = index


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _leaf_spec(leaf) -> list:
    sharding = getattr(leaf, "sharding", None)
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return [None] * getattr(leaf, "ndim", 0)
    entries = spec_to_json(spec)
    entries += [None] * (leaf.ndim - len(entries))
    return entries


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # bfloat16 & friends aren't np builtins
        return np.dtype(getattr(ml_dtypes, name))


def _write_file(path: str, data: bytes) -> None:
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


def write_checkpoint(root: str, step: int, host_tree: Any,
                     specs: list[list]) -> str:
    """Commit one host-side TrainState snapshot under
    `root/step_{step:08d}` atomically (temp dir -> fsynced manifest ->
    rename). `specs` holds one JSON-ready PartitionSpec per flattened
    leaf, in tree-flatten order."""
    leaves, treedef = jax.tree_util.tree_flatten(host_tree)
    if len(specs) != len(leaves):
        raise ValueError(f"{len(specs)} specs for {len(leaves)} leaves")
    skeleton = jax.tree_util.tree_unflatten(
        treedef, [_ShardRef(i) for i in range(len(leaves))])
    dest = os.path.join(root, f"step_{step:08d}")
    with atomic_dir(dest) as tmp:
        shards = []
        for i, leaf in enumerate(leaves):
            arr = np.asarray(leaf)
            data = np.ascontiguousarray(arr).tobytes()
            name = f"shard_{i:05d}.bin"
            _write_file(os.path.join(tmp, name), data)
            shards.append({
                "file": name,
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
                "spec": specs[i],
                "sha256": _sha256(data),
            })
        skel = pickle.dumps(skeleton, protocol=5)
        _write_file(os.path.join(tmp, _SKELETON), skel)
        manifest = json.dumps({
            "format": _FORMAT,
            "step": int(step),
            "shards": shards,
            "skeleton": {"file": _SKELETON, "sha256": _sha256(skel)},
        }, indent=1).encode()
        _write_file(os.path.join(tmp, MANIFEST), manifest)
    return dest


def load_manifest(path: str) -> dict:
    """Read + sanity-check a committed checkpoint dir's manifest."""
    mf = os.path.join(path, MANIFEST)
    if not os.path.isfile(mf):
        raise CheckpointError(
            f"{path!r} holds no committed checkpoint (no {MANIFEST} — "
            f"a crashed writer's partial dir is never committed)")
    try:
        with open(mf, "rb") as f:
            manifest = json.load(f)
    except ValueError as e:
        raise CheckpointError(f"unreadable manifest in {path!r}: {e}") \
            from None
    if manifest.get("format") != _FORMAT:
        raise CheckpointError(
            f"{path!r}: unknown checkpoint format "
            f"{manifest.get('format')!r}")
    return manifest


def validate_checkpoint(path: str) -> dict:
    """Verify every shard (and the skeleton) against the manifest's
    checksums. Returns the manifest; raises CheckpointError on any
    mismatch or missing file."""
    manifest = load_manifest(path)
    entries = list(manifest["shards"])
    entries.append(manifest["skeleton"])
    for entry in entries:
        full = os.path.join(path, entry["file"])
        try:
            with open(full, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            raise CheckpointError(
                f"{path!r}: shard {entry['file']!r} listed in the "
                f"manifest is missing") from None
        if _sha256(data) != entry["sha256"]:
            raise CheckpointError(
                f"{path!r}: checksum mismatch on {entry['file']!r} "
                f"(torn write or corruption)")
    return manifest


def committed_steps(root: str) -> list[tuple[int, str]]:
    """(step, dir) for every COMMITTED checkpoint under `root`,
    ascending. Temp/partial dirs (no manifest, unparseable) are
    ignored. Accepts a local path or a storage URI."""
    from ray_tpu.util import storage
    if storage.is_uri(root):
        steps = {}
        for rel in storage.list_prefix(root):
            head, _, tail = rel.partition("/")
            m = _STEP_RE.match(head)
            if m and tail == storage.COMMIT_FILE:
                steps[int(m.group(1))] = storage.uri_join(root, head)
        return sorted(steps.items())
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        m = _STEP_RE.match(name)
        full = os.path.join(root, name)
        if m and os.path.isfile(os.path.join(full, MANIFEST)):
            out.append((int(m.group(1)), full))
    return sorted(out)


def latest_checkpoint(root: str) -> str | None:
    """Newest committed checkpoint dir (or URI) under `root`, else
    None."""
    steps = committed_steps(root)
    return steps[-1][1] if steps else None


def restore_resharded(source: str, mesh: Mesh, *, validate: bool = True
                      ) -> tuple[Any, int]:
    """Restore a committed checkpoint onto `mesh`, resharding every leaf
    via its recorded PartitionSpec — `mesh` may have a different device
    count than the mesh the checkpoint was written from (elastic
    resume). Returns (state, step).

    `source` is a committed checkpoint dir, a root holding step_* dirs
    (the newest committed one is used), or a storage URI of either.
    """
    from ray_tpu.util import storage
    if storage.is_uri(source):
        uri = source
        if not storage.is_committed(uri):
            latest = latest_checkpoint(uri)
            if latest is None:
                raise CheckpointError(
                    f"no committed checkpoint under {uri!r}")
            uri = latest
        local = storage.staging_dir(uri)
        try:
            storage.download_dir_committed(uri, local)
        except storage.UncommittedError as e:
            raise CheckpointError(str(e)) from None
        source = local
    if not os.path.isfile(os.path.join(source, MANIFEST)):
        latest = latest_checkpoint(source)
        if latest is None:
            raise CheckpointError(
                f"no committed checkpoint under {source!r}")
        source = latest
    manifest = validate_checkpoint(source) if validate \
        else load_manifest(source)
    with open(os.path.join(source, manifest["skeleton"]["file"]),
              "rb") as f:
        skeleton = pickle.load(f)
    shards = manifest["shards"]

    def materialize(ref: _ShardRef):
        entry = shards[ref.index]
        with open(os.path.join(source, entry["file"]), "rb") as f:
            arr = np.frombuffer(f.read(), dtype=_np_dtype(entry["dtype"]))
        arr = arr.reshape(entry["shape"])
        spec = valid_spec_for(mesh, spec_from_json(entry["spec"]),
                              arr.shape)
        return jax.device_put(arr, NamedSharding(mesh, spec))

    state = jax.tree.map(
        materialize, skeleton,
        is_leaf=lambda x: isinstance(x, _ShardRef))
    return state, int(manifest["step"])


def fast_forward(host_iter: Iterable, n: int) -> Iterator:
    """Skip the first `n` host batches — positions a deterministic data
    stream at the restored step so the resumed trajectory replays the
    exact batches the lost run would have seen."""
    it = iter(host_iter)
    for _ in range(int(n)):
        next(it)
    return it


class AsyncCheckpointer:
    """Asynchronous sharded checkpointer for TrainLoop (train/loop.py).

    `maybe_snapshot(state, step)` is called once per dispatch; every
    `every` steps it enqueues a device-side copy of the state (jitted
    `jnp.copy` per leaf — donation-safe: the loop is free to donate the
    original buffers into the next step) plus each leaf's PartitionSpec,
    and returns immediately. A daemon writer thread fetches the copy to
    host (`_device_get`, off the training thread) and commits it under
    `root/step_{NNNNNNNN}` via `write_checkpoint`'s atomic temp-dir →
    fsynced-manifest → rename protocol. With `uri=` set, each committed
    dir is additionally mirrored through util/storage's commit-marker
    upload.

    At most `max_in_flight` snapshots exist between device and disk;
    a slower filesystem back-pressures `maybe_snapshot` (counted in
    `stalls`), never memory. `keep` bounds committed checkpoints on
    disk, oldest pruned first. Writer errors surface on the training
    thread at the next `maybe_snapshot`/`flush`.
    """

    def __init__(self, root: str, *, every: int = 100,
                 max_in_flight: int = 2, keep: int = 2,
                 uri: str | None = None):
        from ray_tpu.util import storage
        if storage.is_uri(root) and uri is None:
            uri, root = root, storage.staging_dir(root)
        self.root = root
        self.uri = uri
        self.every = max(1, int(every))
        self.max_in_flight = max(1, int(max_in_flight))
        self.keep = max(1, int(keep))
        os.makedirs(root, exist_ok=True)
        self._copy = jax.jit(lambda t: jax.tree.map(jnp.copy, t))
        self._queue: queue.Queue = queue.Queue(maxsize=self.max_in_flight)
        self._error: BaseException | None = None
        self._last_snap_step: int | None = None
        self._closed = False
        # observability counters
        self.snapshots = 0      # device copies enqueued
        self.commits = 0        # checkpoints committed to disk
        self.stalls = 0         # times the in-flight bound back-pressured
        # When tracing is on, the writer thread's spans should nest
        # under whatever span was active when the checkpointer was
        # built (threads don't inherit the submitter's span otherwise).
        self._trace_ctx = _tracing.capture_context()
        self._writer = threading.Thread(target=self._writer_loop,
                                        daemon=True,
                                        name="ft-checkpoint-writer")
        self._writer.start()

    # -- training-thread API ------------------------------------------------

    def maybe_snapshot(self, state, step: int, *,
                       force: bool = False) -> bool:
        """Snapshot if `step` is `every` past the last snapshot (or
        `force`). Never blocks on a device→host sync; blocks only when
        `max_in_flight` snapshots are already pending (memory bound)."""
        self._raise_pending()
        if self._closed:
            raise RuntimeError("AsyncCheckpointer is closed")
        last = self._last_snap_step
        if not force and last is not None and step - last < self.every:
            return False
        if not force and last is None and step < self.every:
            return False
        snap = self._copy(state)            # async device-side copy
        specs = [_leaf_spec(l) for l in jax.tree_util.tree_leaves(snap)]
        if self._queue.full():
            self.stalls += 1
        # graftlint: disable-next-line=R001 bounded backpressure: blocks only when max_in_flight snapshots are pending (counted in `stalls`) — the memory bound IS the contract, not an accidental sync
        self._queue.put((int(step), snap, specs))
        self._last_snap_step = int(step)
        self.snapshots += 1
        return True

    def flush(self) -> None:
        """Block until every enqueued snapshot is committed (the one
        deliberate end-of-run sync, mirroring MetricsRing.drain)."""
        # graftlint: disable-next-line=R001 the one deliberate end-of-run barrier, mirroring MetricsRing.drain — callers invoke it after the timed region
        self._queue.join()
        self._raise_pending()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._queue.put(None)
        self._writer.join()
        self._raise_pending()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- writer thread ------------------------------------------------------

    def _writer_loop(self):
        _tracing.attach_context(self._trace_ctx)
        while True:
            item = self._queue.get()
            try:
                if item is None:
                    return
                step, snap, specs = item
                with _tracing.span("ft.checkpoint_commit",
                                   {"step": step, "root": self.root}):
                    host = _device_get(snap)  # off the training thread
                    del snap
                    dest = write_checkpoint(self.root, step, host, specs)
                    self.commits += 1
                    if self.uri is not None:
                        from ray_tpu.util import storage
                        storage.upload_dir_committed(
                            dest, storage.uri_join(
                                self.uri, os.path.basename(dest)))
                    self._prune()
            except BaseException as e:       # surfaced on train thread
                self._error = e
            finally:
                self._queue.task_done()

    def _prune(self):
        from ray_tpu.util import storage
        steps = committed_steps(self.root)
        excess = steps[:-self.keep] if len(steps) > self.keep else []
        for step, path in excess:
            shutil.rmtree(path, ignore_errors=True)
            if self.uri is not None:
                try:
                    storage.delete(storage.uri_join(
                        self.uri, os.path.basename(path)))
                except Exception:
                    pass

    def _raise_pending(self):
        if self._error is not None:
            err, self._error = self._error, None
            raise CheckpointError(
                f"async checkpoint writer failed: {err!r}") from err

    # -- validation ---------------------------------------------------------

    def check_invariants(self) -> None:
        """Validator wired into tests (chaos suite + units): in-flight
        bound respected, every committed checkpoint's shards match its
        manifest checksums, steps strictly increasing, no swallowed
        writer error."""
        assert self._queue.qsize() <= self.max_in_flight, \
            f"{self._queue.qsize()} in flight > bound {self.max_in_flight}"
        steps = committed_steps(self.root)
        assert len(steps) <= self.keep, \
            f"{len(steps)} committed > keep={self.keep}"
        last = None
        for step, path in steps:
            validate_checkpoint(path)       # raises on any mismatch
            assert last is None or step > last, \
                f"non-monotonic committed steps under {self.root!r}"
            last = step
        self._raise_pending()
