"""SPMD train-state/step factory — the jit-compiled training hot path.

The reference's equivalent seam is `prepare_model` wrapping torch modules in
DDP/FSDP (`train/torch/train_loop_utils.py:75-101`) plus NCCL process-group
setup (`train/torch/config.py:113`). TPU-native, the whole thing collapses
into shardings: parameters/optimizer state carry NamedShardings derived from
logical axes, the batch shards over the data-like mesh axes, and jit inserts
every collective (gradient psum, FSDP all-gather/reduce-scatter, TP
collectives) from the sharding lattice. There is no wrapper object.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ray_tpu.parallel.sharding import (
    logical_to_spec,
    replicated,
    tree_shardings,
)


@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: Any


def default_optimizer(learning_rate: float = 3e-4,
                      weight_decay: float = 0.1,
                      warmup_steps: int = 100,
                      total_steps: int = 10_000,
                      b1: float = 0.9, b2: float = 0.95,
                      grad_clip: float = 1.0) -> optax.GradientTransformation:
    """AdamW + cosine schedule + global-norm clip — the standard LLM recipe."""
    sched = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1))
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(sched, b1=b1, b2=b2, weight_decay=weight_decay),
    )


def create_sharded_state(init_fn: Callable[[jax.Array], Any],
                         param_logical_axes,
                         mesh: Mesh,
                         rng,
                         optimizer: optax.GradientTransformation,
                         rules: dict | None = None) -> tuple[TrainState, Any]:
    """Initialize params + optimizer state directly into their shardings.

    Params are materialized *sharded* (jit with out_shardings), so a model
    too big for one device's HBM never exists unsharded anywhere. Optimizer
    moments inherit the param shardings through XLA propagation
    (zeros_like preserves sharding).
    """
    param_shardings = tree_shardings(mesh, param_logical_axes, rules)
    params = jax.jit(init_fn, out_shardings=param_shardings)(rng)
    opt_state = jax.jit(optimizer.init)(params)
    step = jax.device_put(jnp.zeros((), jnp.int32), replicated(mesh))
    return TrainState(params, opt_state, step), param_shardings


def make_train_step(loss_fn: Callable,
                    optimizer: optax.GradientTransformation,
                    mesh: Mesh,
                    donate: bool = True,
                    accum: int = 1,
                    rules: dict | None = None,
                    jit: bool = True):
    """Build the jitted (state, batch) -> (state, metrics) step.

    loss_fn(params, batch) -> scalar loss. The batch is a pytree of global
    arrays sharded over the data-like axes; gradient synchronization is
    implicit (jit sees replicated params + sharded batch and inserts the
    reduce). Donation reuses param/opt-state HBM buffers in place.

    accum=k splits the batch's leading axis into k microbatches and
    `lax.scan`s value_and_grad over them, keeping a running f32 mean of
    loss and grads, then applies ONE optimizer update — peak activation
    memory is that of a single microbatch, so effective batch sizes grow
    k-fold beyond what fits in HBM at once. Each microbatch keeps the
    batch sharding over the data-like mesh axes (the leading k axis is
    the scan axis, unsharded). accum=k matches accum=1 on the same batch
    up to summation-order float error (~1e-6 f32); with a padding mask
    the per-microbatch normalization means exact parity only holds when
    mask counts are equal across microbatches.
    """
    accum = int(accum)
    if accum < 1:
        raise ValueError(f"accum must be >= 1, got {accum}")
    micro_spec = logical_to_spec(("batch",), rules, mesh)

    def split_micro(batch):
        def rs(a):
            if a.shape[0] % accum:
                raise ValueError(
                    f"batch dim {a.shape[0]} not divisible by "
                    f"accum={accum}")
            a = a.reshape(accum, a.shape[0] // accum, *a.shape[1:])
            spec = PartitionSpec(
                None, *(list(micro_spec) + [None] * (a.ndim - 2)))
            return jax.lax.with_sharding_constraint(
                a, NamedSharding(mesh, spec))
        return jax.tree.map(rs, batch)

    def value_and_mean_grad(params, batch):
        if accum == 1:
            return jax.value_and_grad(loss_fn)(params, batch)

        def micro_step(carry, mb):
            i, loss_mean, gmean = carry
            loss, g = jax.value_and_grad(loss_fn)(params, mb)
            # running mean in f32 regardless of param/grad dtype: the
            # k-th increment is (x_k - mean)/k, so bf16 grads never
            # accumulate in their own (3-bit-mantissa-per-step) dtype
            inv = 1.0 / (i + 1.0)
            loss_mean = loss_mean + (loss.astype(jnp.float32)
                                     - loss_mean) * inv
            gmean = jax.tree.map(
                lambda m, x: m + (x.astype(jnp.float32) - m) * inv,
                gmean, g)
            return (i + 1.0, loss_mean, gmean), None

        zeros = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        (_, loss, gmean), _ = jax.lax.scan(
            micro_step, (jnp.zeros(()), jnp.zeros(()), zeros),
            split_micro(batch))
        grads = jax.tree.map(lambda g, p: g.astype(p.dtype),
                             gmean, params)
        return loss, grads

    def step(state: TrainState, batch):
        loss, grads = value_and_mean_grad(state.params, batch)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        new_state = TrainState(params, opt_state, state.step + 1)
        return new_state, {"loss": loss, "grad_norm": gnorm,
                           "step": new_state.step}

    if not jit:
        return step
    kwargs = {"donate_argnums": (0,)} if donate else {}
    return jax.jit(step, **kwargs)


# ---------------------------------------------------------------------------
# GPT-specific assembly (the flagship train path used by bench / graft entry)
# ---------------------------------------------------------------------------

def softmax_xent(logits, targets):
    """Dense cross entropy: ``gather - logsumexp`` touches the [B, T, V]
    logits twice instead of log_softmax's materialize-then-gather (the
    logits tensor is the biggest array in an LM step — at GPT-2 bench
    shape it is 1.6 GB f32, so every avoided pass is ~2 ms of HBM).
    cfg.loss_impl="fused" (ops/fused_xent.py) goes further and never
    materializes the logits at all — gpt_loss_fn routes between the
    two."""
    logits = logits.astype(jnp.float32)   # no-op for f32; bf16 logits
    #                                       upcast before the logsumexp
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jax.scipy.special.logsumexp(logits, axis=-1) - tgt


def gpt_loss_fn(params, batch, cfg, mesh: Mesh | None = None):
    """Cross entropy over pre-shifted inputs/targets [B, T].

    Unlike `models.gpt.loss_fn` (which slices tokens[:, :-1] and breaks
    seq-axis divisibility), inputs/targets are shifted on the host so the
    in-graph T stays divisible by the `seq` mesh axis for ring attention.
    """
    from ray_tpu.models import gpt

    if gpt.check_loss_impl(cfg) == "fused":
        from ray_tpu.ops.fused_xent import fused_softmax_xent
        x = gpt.forward_features(params, batch["inputs"], cfg, mesh)
        nll = fused_softmax_xent(
            x, params["embed"].astype(cfg.activation_dtype()),
            batch["targets"], vocab_chunk=cfg.loss_chunk, mesh=mesh)
    else:
        logits = gpt.forward(params, batch["inputs"], cfg, mesh)
        nll = softmax_xent(logits, batch["targets"])
    mask = batch.get("mask")
    if mask is not None:
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)


def make_gpt_trainer(cfg, mesh: Mesh, rng=None,
                     optimizer: optax.GradientTransformation | None = None,
                     rules: dict | None = None, accum: int = 1,
                     init_state: bool = True):
    """One-call assembly: sharded state + jitted step + batch sharding.

    Returns (state, step_fn, batch_sharding_fn). batch_sharding_fn places a
    host batch {"inputs","targets"} [B,T] onto the mesh sharded
    (batch→data/fsdp, length→seq). accum=k makes the step accumulate
    gradients over k microbatches (see make_train_step).

    init_state=False skips parameter/optimizer initialization and returns
    state=None — the elastic-resume path (train/ft.restore_resharded)
    already holds the state and shouldn't pay to materialize one it is
    about to throw away.
    """
    from ray_tpu.models import gpt

    return _make_lm_trainer(
        lambda key: gpt.init_params(key, cfg), gpt.param_logical_axes(cfg),
        partial(gpt_loss_fn, cfg=cfg, mesh=mesh), mesh, rng, optimizer,
        rules, accum=accum, init_state=init_state)


def moe_loss_fn(params, batch, cfg, mesh: Mesh | None = None):
    """MoE counterpart of gpt_loss_fn (pre-shifted inputs/targets, same
    optional padding mask) adding the router load-balance auxiliary loss."""
    from ray_tpu.models import moe

    logits, aux = moe.forward(params, batch["inputs"], cfg, mesh)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(
        logp, batch["targets"][..., None], axis=-1)[..., 0]
    mask = batch.get("mask")
    if mask is not None:
        ce = -jnp.sum(ll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    else:
        ce = -jnp.mean(ll)
    return ce + cfg.aux_loss_coeff * aux


def _make_lm_trainer(init_fn, logical_axes, loss_fn, mesh: Mesh, rng,
                     optimizer, rules, accum: int = 1,
                     init_state: bool = True):
    """Shared assembly behind make_gpt_trainer / make_moe_trainer."""
    rng = jax.random.key(0) if rng is None else rng
    optimizer = optimizer or default_optimizer()
    state = None
    if init_state:
        state, _ = create_sharded_state(
            init_fn, logical_axes, mesh, rng, optimizer, rules)
    step_fn = make_train_step(loss_fn, optimizer, mesh, accum=accum,
                              rules=rules)

    tok_spec = logical_to_spec(("batch", "length"), rules, mesh)
    tok_sharding = NamedSharding(mesh, tok_spec)

    def shard_tokens(batch):
        return jax.tree.map(
            lambda a: jax.device_put(a, tok_sharding), batch)

    return state, step_fn, shard_tokens


def make_gpt_pipeline_trainer(cfg, mesh: Mesh, num_microbatches: int = 2,
                              rng=None,
                              optimizer: optax.GradientTransformation | None
                              = None,
                              rules: dict | None = None):
    """GPipe-staged GPT trainer: the layer stack splits into
    mesh["pipe"] contiguous stages, activations stream between neighbor
    stages via ppermute (parallel/pipeline.py), combinable with the data
    axis (each pipe rank streams its own data shard). The reference has no
    pipeline parallelism at all (SURVEY.md §2.4); this is the TPU-native
    member of the same trainer family as make_gpt_trainer."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu.models import gpt
    from ray_tpu.parallel.pipeline import pipeline_apply

    s_count = max(mesh.shape.get("pipe", 1), 1)
    if cfg.n_layers % s_count:
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible by pipe={s_count}")
    per = cfg.n_layers // s_count

    def loss_fn(params, batch):
        adt = cfg.activation_dtype()
        tokens = batch["inputs"]
        t = tokens.shape[1]
        x = params["embed"].astype(adt)[tokens]
        x = x + params["pos_embed"].astype(adt)[:t][None]
        per_stage = [
            jax.tree.map(lambda p: p[i * per:(i + 1) * per],
                         params["layers"])
            for i in range(s_count)
        ]

        def stage_fn(sp, xm):
            def body(h, lp):
                # mesh=None: attention stays local to the stage shard (no
                # nested seq-axis collectives inside the pipe shard_map)
                return gpt._block(h, lp, cfg, None), None
            out, _ = jax.lax.scan(body, xm, sp)
            return out

        x = pipeline_apply(stage_fn, per_stage, x, mesh=mesh,
                           num_microbatches=num_microbatches,
                           batch_spec=P(None, ("data", "fsdp")))
        x = gpt._rms_norm(x, params["final_ln_scale"].astype(adt))
        logits = jnp.einsum("btd,vd->btv", x, params["embed"].astype(adt),
                            preferred_element_type=jnp.float32)
        return jnp.mean(softmax_xent(logits, batch["targets"]))

    return _make_lm_trainer(
        lambda key: gpt.init_params(key, cfg), gpt.param_logical_axes(cfg),
        loss_fn, mesh, rng, optimizer, rules)


def make_moe_trainer(cfg, mesh: Mesh, rng=None,
                     optimizer: optax.GradientTransformation | None = None,
                     rules: dict | None = None, accum: int = 1,
                     init_state: bool = True):
    """MoE assembly: expert weights shard over the mesh's `expert` axis,
    so the dispatch/combine einsums lower to all-to-alls over ICI."""
    from ray_tpu.models import moe

    return _make_lm_trainer(
        lambda key: moe.init_params(key, cfg), moe.param_logical_axes(cfg),
        partial(moe_loss_fn, cfg=cfg, mesh=mesh), mesh, rng, optimizer,
        rules, accum=accum, init_state=init_state)


def train_flops_per_token(cfg, seq_len: int) -> float:
    """Approximate model FLOPs per trained token (fwd+bwd ≈ 3x fwd), for
    MFU reporting."""
    d, f, L = cfg.d_model, cfg.d_ff, cfg.n_layers
    h = cfg.n_heads * cfg.head_dim
    matmuls = 2 * (3 * d * h + h * d + 3 * d * f)      # qkv+o+glu-mlp
    attn = 2 * 2 * seq_len * h                         # scores + p@v
    embed = 2 * d * cfg.vocab_size                     # logits matmul
    return 3.0 * (L * (matmuls + attn) + embed)        # fwd + 2x bwd
