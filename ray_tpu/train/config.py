"""Train configuration dataclasses.

Counterparts of the reference's `air/config.py` (ScalingConfig :91,
RunConfig :704, FailureConfig :523, CheckpointConfig :574) with TPU-native
extensions: `ScalingConfig.mesh` carries the full parallelism layout
(MeshSpec) instead of just a worker count, because on TPU the partitioning
IS the configuration.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


@dataclass
class ScalingConfig:
    """How many worker processes and what each one sees.

    num_workers: processes (one per TPU host in multi-host deployments;
    the reference's worker == one GPU, ours == one host of chips, because
    JAX is SPMD per process).
    """
    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: dict = field(default_factory=dict)
    placement_strategy: str = "STRICT_PACK"
    # TPU-native: logical mesh over ALL workers' devices; None = pure DP.
    mesh: object | None = None      # ray_tpu.parallel.MeshSpec

    def worker_resources(self) -> dict:
        res = dict(self.resources_per_worker)
        res.setdefault("CPU", 1.0)
        if self.use_tpu and "TPU" not in res:
            res["TPU"] = 1.0
        return res


@dataclass
class CheckpointConfig:
    num_to_keep: int | None = None          # None = keep all
    checkpoint_score_attribute: str | None = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0


@dataclass
class FailureConfig:
    max_failures: int = 0                   # -1 = unlimited restarts


@dataclass
class RunConfig:
    name: str | None = None
    storage_path: str | None = None
    checkpoint_config: CheckpointConfig = field(
        default_factory=CheckpointConfig)
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    verbose: int = 1
    # Experiment callbacks (tune/loggers.py Json/CSV/TensorBoard logger
    # callbacks, or any object with on_trial_start/result/complete/error
    # and on_experiment_start/end hooks — reference: tune/callback.py).
    callbacks: list = field(default_factory=list)

    def resolved_storage_path(self) -> str:
        base = self.storage_path or os.path.expanduser("~/ray_tpu_results")
        name = self.name or "train_run"
        from ray_tpu.util import storage
        if storage.is_uri(base):
            # remote experiment root (reference: RunConfig.storage_path
            # accepts s3://... URIs; air/_internal/remote_storage.py)
            return storage.uri_join(base, name)
        return os.path.join(base, name)
