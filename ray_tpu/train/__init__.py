"""ray_tpu.train — distributed training (Train-equivalent).

Reference surface covered (SURVEY.md §2.5): trainer + config dataclasses +
session API + checkpointing; the torch/NCCL backend seam
(`train/torch/config.py:113`) is replaced by `jax.distributed.initialize`
+ mesh SPMD.
"""

from ray_tpu.train import ft, loop, session
from ray_tpu.train.checkpoint import Checkpoint, CheckpointError
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.predictor import BatchPredictor, JaxPredictor, Predictor
from ray_tpu.train.trainer import JaxTrainer, Result, TrainingFailedError
from ray_tpu.train.torch_trainer import TorchTrainer
from ray_tpu.train.sklearn_trainer import SklearnTrainer
from ray_tpu.train.gbdt import GBDTTrainer, LightGBMTrainer, XGBoostTrainer
from ray_tpu.train.tf_trainer import TensorflowTrainer

# Session facade re-exports (reference: ray.air.session / ray.train.*)
report = session.report
get_checkpoint = session.get_checkpoint
get_dataset_shard = session.get_dataset_shard
get_world_size = session.get_world_size
get_world_rank = session.get_world_rank
get_mesh_spec = session.get_mesh_spec

__all__ = [
    "JaxTrainer", "TorchTrainer", "SklearnTrainer", "GBDTTrainer",
    "XGBoostTrainer", "LightGBMTrainer", "TensorflowTrainer", "Result",
    "TrainingFailedError", "Checkpoint", "CheckpointError", "ft",
    "Predictor", "JaxPredictor", "BatchPredictor",
    "ScalingConfig", "RunConfig", "CheckpointConfig", "FailureConfig",
    "session", "report", "get_checkpoint", "get_dataset_shard",
    "get_world_size", "get_world_rank", "get_mesh_spec", "loop",
]

from ray_tpu._private.usage_stats import record_library_usage as _rlu
_rlu("train")
del _rlu
