"""TorchTrainer — distributed PyTorch over the same worker-group spine.

Counterpart of the reference's `train/torch/torch_trainer.py` +
`train/torch/config.py` (rank-0 rendezvous, `dist.init_process_group`)
+ `train_loop_utils.py:75` (`prepare_model` DDP wrap): the worker group,
session API (report/get_checkpoint/get_dataset_shard), checkpointing,
and FailureConfig restarts are IDENTICAL to JaxTrainer — only the
collective rendezvous differs (torch gloo instead of
`jax.distributed.initialize`). gloo because these workers are CPU
hosts: on this framework TPU compute belongs to the JAX path, and
TorchTrainer covers torch-native workloads (data prep models,
CPU fine-tunes, reference-parity training loops).
"""

from __future__ import annotations

from ray_tpu.train.trainer import JaxTrainer


class TorchTrainer(JaxTrainer):
    _rendezvous_method = "setup_torch_distributed"
    _always_rendezvous = True     # DDP needs a process group at world=1


def prepare_model(model):
    """Wrap for data-parallel gradient sync when world_size > 1
    (reference: train_loop_utils.py:75 prepare_model -> DDP)."""
    import torch.distributed as dist
    from torch.nn.parallel import DistributedDataParallel
    if dist.is_available() and dist.is_initialized() \
            and dist.get_world_size() > 1:
        return DistributedDataParallel(model)
    return model


def prepare_data_loader(loader):
    """Shard a DataLoader across workers with a DistributedSampler
    (reference: train_loop_utils.py:116). The rebuilt loader keeps the
    original's worker/pinning/seeding settings; loaders built with a
    custom batch_sampler can't be re-sharded this way and are
    rejected."""
    import torch.distributed as dist
    from torch.utils.data import (DataLoader, DistributedSampler,
                                  SequentialSampler)
    if not (dist.is_available() and dist.is_initialized()
            and dist.get_world_size() > 1):
        return loader
    if loader.batch_size is None:
        raise ValueError(
            "prepare_data_loader cannot re-shard a DataLoader built "
            "with a custom batch_sampler; construct it with batch_size "
            "and let the sampler be replaced")
    # shuffle unless the ORIGINAL loader was sequential — a sequential
    # eval loader must stay in-order, while any randomized sampler
    # (RandomSampler, WeightedRandomSampler, custom) keeps shuffling
    # (reference: train_loop_utils.py:408-410 `not SequentialSampler`)
    shuffle = not isinstance(loader.sampler, SequentialSampler)
    sampler = DistributedSampler(loader.dataset, shuffle=shuffle)
    kwargs = dict(
        batch_size=loader.batch_size, sampler=sampler,
        num_workers=loader.num_workers, collate_fn=loader.collate_fn,
        pin_memory=loader.pin_memory, drop_last=loader.drop_last,
        timeout=loader.timeout, worker_init_fn=loader.worker_init_fn,
        generator=loader.generator)
    if loader.num_workers > 0:
        kwargs["persistent_workers"] = loader.persistent_workers
        if loader.prefetch_factor is not None:
            kwargs["prefetch_factor"] = loader.prefetch_factor
    return DataLoader(loader.dataset, **kwargs)
