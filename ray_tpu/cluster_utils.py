"""One-host multi-daemon cluster fixture.

Counterpart of the reference's `python/ray/cluster_utils.py:99` `Cluster`:
N HostDaemons (each with its own object store, worker pool, and — faked —
resources) on one machine, sharing the head's cluster store. Resource
shapes are just scheduler numbers, so a laptop can simulate a multi-host
TPU pod the same way the reference fakes `num_gpus=8` nodes; this is the
load-bearing fixture for multi-node scheduling, placement-strategy, object
-transfer, and chaos tests.
"""

from __future__ import annotations

import ray_tpu
from ray_tpu._private.worker import get_client


class Cluster:
    """Start a head session plus `initial_nodes` extra daemon nodes.

    Usage::

        cluster = Cluster(head_resources={"CPU": 2})
        n1 = cluster.add_node({"CPU": 2, "accel": 1})
        ...
        cluster.shutdown()
    """

    def __init__(self, head_resources: dict | None = None,
                 num_tpus: int = 0, **init_kwargs):
        res = dict(head_resources or {})
        num_cpus = res.pop("CPU", None)
        self.client = ray_tpu.init(
            num_cpus=int(num_cpus) if num_cpus is not None else None,
            num_tpus=num_tpus, resources=res, **init_kwargs)
        self.node_ids: list[str] = []

    @classmethod
    def attach(cls) -> "Cluster":
        """Wrap the already-initialized session (shared test fixtures)."""
        c = cls.__new__(cls)
        c.client = get_client()
        c.node_ids = []
        return c

    def add_node(self, resources: dict | None = None,
                 num_tpus: int = 0) -> str:
        """Spawn one HostDaemon with the given (fake) resource shape and
        block until it registers with the head."""
        node_id = get_client().control(
            "add_node", {"resources": resources or {},
                         "num_tpus": num_tpus})
        self.node_ids.append(node_id)
        return node_id

    def kill_node(self, node_id: str, force: bool = True) -> bool:
        """SIGKILL the daemon (chaos path): its workers die with it and the
        head's failure handling kicks in, exactly like losing a host."""
        return get_client().control(
            "kill_node", {"node_id": node_id, "force": force})

    def list_nodes(self):
        return get_client().control("list_nodes")

    def shutdown(self):
        ray_tpu.shutdown()
