/* Dashboard SPA — hash-routed pages over the JSON API.
 *
 * Page families mirror the reference's React client
 * (dashboard/client/src/pages/): overview, cluster (nodes/resources),
 * jobs (+submit/logs), actors, tasks (+state filters), serve, logs,
 * metrics (client-side timeseries polled from /api/metrics_snapshot).
 * No build step: one file, fetch + DOM.
 */
"use strict";

const $main = document.getElementById("main");
const REFRESH_MS = 3000;
let timer = null;

const fmt = {
  num(x) {
    if (x === null || x === undefined) return "–";
    if (typeof x !== "number") return String(x);
    if (Number.isInteger(x)) return x.toLocaleString();
    return x.toFixed(2);
  },
  bytes(x) {
    if (x === null || x === undefined) return "–";
    const u = ["B", "KB", "MB", "GB", "TB"];
    let i = 0;
    while (x >= 1024 && i < u.length - 1) { x /= 1024; i++; }
    return x.toFixed(i ? 1 : 0) + " " + u[i];
  },
  ts(t) {
    if (!t) return "–";
    return new Date(t * 1000).toLocaleTimeString();
  },
  ago(t) {
    if (!t) return "–";
    const s = Math.max(0, Date.now() / 1000 - t);
    if (s < 60) return s.toFixed(0) + "s ago";
    if (s < 3600) return (s / 60).toFixed(0) + "m ago";
    return (s / 3600).toFixed(1) + "h ago";
  },
  esc(s) {
    // includes ' — escaped values land inside single-quoted inline
    // onclick handlers (stopJob('${id}') etc.), where a bare quote
    // breaks out of the attribute
    return String(s ?? "").replace(/[&<>"']/g,
      c => ({"&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;",
             "'": "&#39;"}[c]));
  },
};

async function api(path) {
  const r = await fetch(path);
  if (!r.ok) throw new Error(path + " -> " + r.status);
  const ct = r.headers.get("Content-Type") || "";
  return ct.includes("json") ? r.json() : r.text();
}

function stateBadge(s) {
  return `<span class="state ${fmt.esc(s)}">${fmt.esc(s)}</span>`;
}

function table(cols, rows, renderRow) {
  if (!rows || !rows.length)
    return `<p class="dim">nothing here yet</p>`;
  return `<table><thead><tr>${
    cols.map(c => `<th>${c}</th>`).join("")
  }</tr></thead><tbody>${rows.map(renderRow).join("")}</tbody></table>`;
}

function bar(frac) {
  const pct = Math.min(100, Math.max(0, frac * 100));
  return `<span class="bar"><i style="width:${pct}%"></i></span>`;
}

/* --------------------------------------------------------------- pages */

const pages = {};

pages.overview = async () => {
  const [nodes, summary, jobs, actors] = await Promise.all([
    api("/api/nodes"), api("/api/summary"), api("/api/jobs"),
    api("/api/actors"),
  ]);
  const alive = nodes.filter(n => (n.state || n.status) !== "DEAD").length;
  const states = {};
  for (const row of Object.values(summary || {})) {
    for (const [st, n] of Object.entries(row.states || row)) {
      if (typeof n === "number") states[st] = (states[st] || 0) + n;
    }
  }
  const running = states.RUNNING || 0, pending =
    (states.PENDING || 0) + (states.QUEUED || 0);
  return `
  <h2>Overview</h2>
  <div class="cards">
    <div class="card"><div class="big">${alive}</div>
      <div class="label">alive nodes</div></div>
    <div class="card"><div class="big">${actors.length}</div>
      <div class="label">actors</div></div>
    <div class="card"><div class="big">${running}</div>
      <div class="label">running tasks</div></div>
    <div class="card"><div class="big">${pending}</div>
      <div class="label">queued tasks</div></div>
    <div class="card"><div class="big">${jobs.length}</div>
      <div class="label">jobs</div></div>
  </div>
  <h3>Recent jobs</h3>
  ${table(["job", "status", "entrypoint", "submitted"],
          jobs.slice(-8).reverse(), j => `<tr>
    <td><span class="linklike" onclick="location.hash='#/jobs/${
      fmt.esc(j.job_id || j.submission_id)}'">${
      fmt.esc(j.job_id || j.submission_id)}</span></td>
    <td>${stateBadge(j.status)}</td>
    <td>${fmt.esc(j.entrypoint)}</td>
    <td>${fmt.ago(j.submitted_at || j.start_time)}</td></tr>`)}`;
};

pages.cluster = async () => {
  const nodes = await api("/api/nodes");
  return `
  <h2>Cluster</h2>
  ${table(["node", "state", "address", "CPU", "TPU", "memory",
           "object store"],
          nodes, n => {
    const res = n.resources || n.resources_total || {};
    const avail = n.available || n.resources_available || {};
    const cpu = res.CPU || 0, cpuA = avail.CPU ?? cpu;
    const tpu = res.TPU || 0, tpuA = avail.TPU ?? tpu;
    return `<tr>
      <td>${fmt.esc(n.node_id)}</td>
      <td>${stateBadge(n.state || n.status || "ALIVE")}</td>
      <td>${fmt.esc(n.address || n.node_ip || "local")}</td>
      <td>${fmt.num(cpu - cpuA)}/${fmt.num(cpu)} ${
        bar(cpu ? (cpu - cpuA) / cpu : 0)}</td>
      <td>${fmt.num(tpu - tpuA)}/${fmt.num(tpu)}</td>
      <td>${fmt.bytes(n.memory_used)} / ${fmt.bytes(n.memory_total)}</td>
      <td>${fmt.bytes(n.object_store_used)} / ${
        fmt.bytes(n.object_store_total)}</td></tr>`;
  })}`;
};

pages.jobs = async (sub) => {
  if (sub) return jobDetail(sub);
  const jobs = await api("/api/jobs");
  return `
  <h2>Jobs</h2>
  <form class="inline" onsubmit="return submitJob(this)">
    <input type="text" name="entrypoint"
           placeholder="entrypoint, e.g. python my_script.py">
    <button>Submit</button>
  </form>
  <h3>All jobs</h3>
  ${table(["job", "status", "entrypoint", "submitted", ""],
          jobs.slice().reverse(), j => {
    const id = fmt.esc(j.job_id || j.submission_id);
    return `<tr>
    <td><span class="linklike" onclick="location.hash='#/jobs/${id}'">${
      id}</span></td>
    <td>${stateBadge(j.status)}</td>
    <td>${fmt.esc(j.entrypoint)}</td>
    <td>${fmt.ago(j.submitted_at || j.start_time)}</td>
    <td>${j.status === "RUNNING"
      ? `<span class="linklike" onclick="stopJob('${id}')">stop</span>`
      : ""}</td></tr>`;
  })}`;
};

async function jobDetail(jobId) {
  const [info, logs] = await Promise.all([
    api("/api/jobs/" + jobId),
    api("/api/jobs/" + jobId + "/logs").catch(() => "(no logs)"),
  ]);
  return `
  <h2>Job ${fmt.esc(jobId)} ${stateBadge(info.status)}</h2>
  <p class="dim">${fmt.esc(info.entrypoint || "")}</p>
  <h3>Logs</h3>
  <pre class="logbox">${fmt.esc(logs)}</pre>
  <p><a class="btn" href="#/jobs">back</a></p>`;
}

window.submitJob = (form) => {
  const entrypoint = form.entrypoint.value.trim();
  if (entrypoint) {
    fetch("/api/jobs", {
      method: "POST", headers: {"Content-Type": "application/json"},
      body: JSON.stringify({entrypoint}),
    }).then(render);
  }
  return false;
};
window.stopJob = (id) => {
  fetch(`/api/jobs/${id}/stop`, {method: "POST"}).then(render);
};

pages.actors = async () => {
  const actors = await api("/api/actors");
  return `
  <h2>Actors</h2>
  ${table(["actor", "class", "state", "node", "pid", "restarts", "name"],
          actors, a => `<tr>
    <td>${fmt.esc(a.actor_id)}</td>
    <td>${fmt.esc(a.class_name)}</td>
    <td>${stateBadge(a.state)}</td>
    <td>${fmt.esc(a.node_id || "head")}</td>
    <td>${fmt.esc(a.pid ?? "–")}</td>
    <td>${fmt.num(a.num_restarts || 0)}</td>
    <td>${fmt.esc(a.name || "")}</td></tr>`)}`;
};

let taskFilter = "ALL";
window.setTaskFilter = (s) => { taskFilter = s; render(); };

pages.tasks = async () => {
  const [tasks, summary] = await Promise.all([
    api("/api/tasks"), api("/api/summary")]);
  const states = [...new Set(tasks.map(t => t.state))].sort();
  const shown = tasks.filter(
    t => taskFilter === "ALL" || t.state === taskFilter).slice(-500);
  const sumRows = Object.entries(summary || {});
  return `
  <h2>Tasks</h2>
  <h3>Summary (by function)</h3>
  ${table(["function", "states"], sumRows, ([name, row]) => {
    const st = row.states || row;
    return `<tr><td>${fmt.esc(name)}</td><td>${
      Object.entries(st).map(([k, v]) =>
        `${stateBadge(k)} ${v}`).join(" &nbsp; ")}</td></tr>`;
  })}
  <h3>Tasks</h3>
  <div class="filters">
    ${["ALL", ...states].map(s =>
      `<button class="${taskFilter === s ? "on" : ""}"
        onclick="setTaskFilter('${s}')">${s}</button>`).join("")}
  </div>
  ${table(["task", "function", "state", "node", "attempts"],
          shown.reverse(), t => `<tr>
    <td>${fmt.esc(t.task_id)}</td>
    <td>${fmt.esc(t.func_or_class_name || t.name)}</td>
    <td>${stateBadge(t.state)}</td>
    <td>${fmt.esc(t.node_id || "–")}</td>
    <td>${fmt.num(t.attempt_number || 0)}</td></tr>`)}`;
};

pages.serve = async () => {
  const apps = await api("/api/serve/applications");
  const entries = Object.entries(apps.applications || apps || {});
  if (!entries.length)
    return `<h2>Serve</h2><p class="dim">no applications deployed</p>`;
  let html = `<h2>Serve</h2>`;
  for (const [name, app] of entries) {
    const deps = Object.entries(app.deployments || {});
    html += `<h3>${fmt.esc(name)} ${stateBadge(app.status || "?")}</h3>
    ${table(["deployment", "status", "replicas", "route"],
            deps, ([dn, d]) => `<tr>
      <td>${fmt.esc(dn)}</td>
      <td>${stateBadge(d.status || "?")}</td>
      <td>${fmt.num(d.num_replicas ?? (d.replicas || []).length)}</td>
      <td>${fmt.esc(d.route_prefix || app.route_prefix || "")}</td>
      </tr>`)}`;
  }
  return html;
};

let logSource = null;
window.setLogSource = (s) => { logSource = s; render(); };

pages.logs = async () => {
  const sources = await api("/api/logs");
  const list = Array.isArray(sources) ? sources
    : (sources.sources || Object.keys(sources));
  let tail = "";
  if (logSource) {
    tail = await api("/api/logs/" + logSource + "?lines=300")
      .catch(e => "error: " + e);
  }
  return `
  <h2>Logs</h2>
  <div class="filters">
    ${list.map(s => `<button class="${logSource === s ? "on" : ""}"
       onclick="setLogSource('${fmt.esc(s)}')">${fmt.esc(s)}</button>`)
      .join("")}
  </div>
  ${logSource
    ? `<h3>${fmt.esc(logSource)}</h3>
       <pre class="logbox">${fmt.esc(tail)}</pre>`
    : `<p class="dim">pick a source</p>`}`;
};

/* metrics: poll gauge snapshots client-side into ring buffers and draw
 * sparkline charts (the reference embeds Grafana; this is self-serve) */
const series = {};   // name -> [{t, v}]
const SERIES_CAP = 120;

function pushSample(name, v) {
  const s = series[name] || (series[name] = []);
  s.push({t: Date.now(), v});
  if (s.length > SERIES_CAP) s.shift();
}

async function pollMetrics() {
  try {
    const snap = await api("/api/metrics_snapshot");
    for (const [k, v] of Object.entries(snap || {})) {
      if (typeof v === "number") pushSample(k, v);
    }
    document.getElementById("health").classList.add("ok");
  } catch (e) {
    document.getElementById("health").classList.remove("ok");
  }
}

function drawChart(canvas, pts) {
  const ctx = canvas.getContext("2d");
  const W = canvas.width, H = canvas.height;
  ctx.clearRect(0, 0, W, H);
  if (pts.length < 2) return;
  const vs = pts.map(p => p.v);
  const lo = Math.min(...vs), hi = Math.max(...vs);
  const span = hi - lo || 1;
  ctx.strokeStyle = "#4da3ff";
  ctx.lineWidth = 1.5;
  ctx.beginPath();
  pts.forEach((p, i) => {
    const x = (i / (pts.length - 1)) * (W - 8) + 4;
    const y = H - 6 - ((p.v - lo) / span) * (H - 14);
    i ? ctx.lineTo(x, y) : ctx.moveTo(x, y);
  });
  ctx.stroke();
  ctx.fillStyle = "#8494a6";
  ctx.font = "10px monospace";
  ctx.fillText(hi.toFixed(1), 4, 10);
  ctx.fillText(lo.toFixed(1), 4, H - 2);
}

pages.metrics = async () => {
  await pollMetrics();
  const names = Object.keys(series).sort();
  setTimeout(() => {
    for (const n of names) {
      const c = document.getElementById("c_" + n);
      if (c) drawChart(c, series[n]);
    }
  }, 0);
  return `
  <h2>Metrics</h2>
  <p class="dim">sampled every ${REFRESH_MS / 1000}s from
     /api/metrics_snapshot · raw: <a class="linklike"
     href="/metrics" target="_blank">/metrics</a> · trace:
     <a class="linklike" href="/api/timeline" target="_blank">
     /api/timeline</a></p>
  <div class="row">
    ${names.map(n => `<div class="chart-card">
      <div class="t">${fmt.esc(n)} = ${
        fmt.num(series[n][series[n].length - 1].v)}</div>
      <canvas id="c_${fmt.esc(n)}" width="280" height="80"></canvas>
    </div>`).join("") || `<p class="dim">no gauges yet</p>`}
  </div>`;
};

/* --------------------------------------------------------------- router */

function route() {
  const hash = location.hash.replace(/^#\//, "") || "overview";
  const [page, sub] = hash.split("/");
  return {page: pages[page] ? page : "overview", sub};
}

async function render() {
  const {page, sub} = route();
  document.querySelectorAll("#nav a").forEach(a =>
    a.classList.toggle("active", a.dataset.page === page));
  try {
    $main.innerHTML = await pages[page](sub);
    document.getElementById("health").classList.add("ok");
  } catch (e) {
    $main.innerHTML = `<p class="err">error: ${fmt.esc(e.message)}</p>`;
    document.getElementById("health").classList.remove("ok");
  }
}

function loop() {
  clearInterval(timer);
  timer = setInterval(() => {
    pollMetrics();
    render();
  }, REFRESH_MS);
}

window.addEventListener("hashchange", render);
render();
loop();
