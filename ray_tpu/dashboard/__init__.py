"""Dashboard: HTTP endpoints over the session's state, metrics, and jobs.

Counterpart of the reference's dashboard head (`dashboard/head.py:81`) and
its REST modules (`dashboard/modules/{node,actor,job,metrics,state,...}`).
The reference ships a React SPA; here the surface is the JSON/Prometheus
API those frontends consume — the part tooling depends on:

  GET /healthz                      liveness
  GET /api/nodes|tasks|actors|workers|objects|placement_groups
  GET /api/summary                  task counts by name/state
  GET /api/jobs                     job table
  POST /api/jobs                    {"entrypoint": ...} -> {"job_id": ...}
  GET /api/jobs/<id>                job info
  GET /api/jobs/<id>/logs           captured stdout/stderr
  GET /api/logs                     log sources (head + every node)
  GET /api/logs/<source>?lines=N    tail of one process's output
  GET /metrics                      Prometheus text exposition
  GET /api/timeline                 chrome://tracing events (task events
                                    merged with engine request spans and
                                    application tracing spans)
  GET /api/telemetry                flight-recorder / retrace-sentinel /
                                    tracing health summary

Runs as a daemon thread in the driver process (the driver embeds the
node, so handlers read NodeServer state through the same control verbs the
CLI uses). Start with ray_tpu.init(dashboard_port=...) or
start_dashboard().
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

_server: Optional[ThreadingHTTPServer] = None

_LIST_ROUTES = {
    "nodes": "list_nodes",
    "tasks": "list_tasks",
    "actors": "list_actors",
    "workers": "list_workers",
    "objects": "list_objects",
    "placement_groups": "list_placement_groups",
}


def _jsonable(value):
    """Tuple-keyed metric series etc. -> JSON-safe structures."""
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    return value


class _Handler(BaseHTTPRequestHandler):
    control = None   # injected

    def log_message(self, *a):   # no stderr spam
        pass

    _STATIC_TYPES = {".html": "text/html", ".js": "text/javascript",
                     ".css": "text/css", ".svg": "image/svg+xml"}

    def _static(self, name: str):
        import os as _os
        root = _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                             "static")
        path = _os.path.normpath(_os.path.join(root, name))
        if not path.startswith(root + _os.sep) or not _os.path.isfile(path):
            return self._send(404, {"error": f"no asset {name!r}"})
        ext = _os.path.splitext(path)[1]
        with open(path, "r") as f:
            return self._send(200, f.read(),
                              self._STATIC_TYPES.get(ext, "text/plain"))

    def _send(self, code: int, body, content_type="application/json"):
        data = (json.dumps(_jsonable(body)).encode()
                if content_type == "application/json"
                else body.encode())
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        try:
            path = self.path.split("?")[0].rstrip("/")
            if path == "":
                # SPA shell (reference: dashboard/client/src — re-done
                # as a no-build vanilla-JS app in static/)
                return self._static("index.html")
            if path.startswith("/static/"):
                return self._static(path[len("/static/"):])
            if path == "/healthz":
                return self._send(200, {"status": "ok"})
            if path == "/metrics":
                from ray_tpu.util import metrics as _metrics
                text = _metrics.render_prometheus(
                    type(self).control("get_metrics"))
                return self._send(200, text, "text/plain; version=0.0.4")
            if path == "/api/summary":
                return self._send(200, type(self).control("summarize_tasks"))
            if path == "/api/metrics_snapshot":
                # gauge sample for the UI's client-side timeseries
                return self._send(
                    200, type(self).control("dashboard_snapshot"))
            if path == "/api/timeline":
                return self._send(200, type(self).control("timeline"))
            if path == "/api/telemetry":
                from ray_tpu.util import telemetry as _telemetry
                return self._send(200, _telemetry.summary())
            if path == "/api/jobs":
                return self._send(200, type(self).control("job_list"))
            if path == "/api/serve/applications":
                # reference: dashboard/modules/serve/ GET status
                from ray_tpu import serve as _serve
                return self._send(200, _serve.status())
            if path == "/api/stack":
                # on-demand profiling (reference: reporter profile
                # endpoints); ?worker=<id> filters
                from urllib.parse import parse_qs, urlparse
                q = parse_qs(urlparse(self.path).query)
                wid = q.get("worker", [None])[0]
                return self._send(200, type(self).control(
                    "stack", {"worker_id": wid, "timeout": 5.0}))
            if path == "/api/logs":
                return self._send(200, type(self).control("list_logs"))
            if path.startswith("/api/logs/"):
                # /api/logs/<source>?lines=N  (source may contain '/':
                # daemon-shipped entries are "<node_id>/<proc>")
                from urllib.parse import parse_qs, urlparse
                u = urlparse(self.path)
                source = u.path[len("/api/logs/"):].rstrip("/")
                n = int(parse_qs(u.query).get("lines", ["200"])[0])
                lines = type(self).control(
                    "get_log", {"source": source, "lines": n})
                return self._send(200, "\n".join(lines) + "\n",
                                  "text/plain")
            if path.startswith("/api/jobs/"):
                parts = path.split("/")
                job_id = parts[3]
                if len(parts) > 4 and parts[4] == "logs":
                    return self._send(
                        200, type(self).control("job_logs", job_id),
                        "text/plain")
                return self._send(200, type(self).control("job_status",
                                                          job_id))
            if path.startswith("/api/"):
                kind = path[len("/api/"):]
                method = _LIST_ROUTES.get(kind)
                if method:
                    return self._send(200, type(self).control(method))
            return self._send(404, {"error": f"no route {path}"})
        except Exception as e:
            return self._send(500, {"error": repr(e)})

    def do_POST(self):
        try:
            path = self.path.rstrip("/")
            length = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(length) or b"{}")
            if path == "/api/jobs":
                job_id = type(self).control("job_submit", {
                    "entrypoint": body["entrypoint"],
                    "job_id": body.get("job_id"),
                    "runtime_env": body.get("runtime_env"),
                    "metadata": body.get("metadata")})
                return self._send(200, {"job_id": job_id})
            if path.startswith("/api/jobs/") and path.endswith("/stop"):
                job_id = path.split("/")[3]
                return self._send(
                    200, {"stopped": type(self).control("job_stop", job_id)})
            if path == "/api/serve/applications":
                # declarative apply (reference: serve REST deploy,
                # dashboard/modules/serve/); body = schema.py config
                from ray_tpu import serve as _serve
                return self._send(200, _serve.apply_config(body))
            return self._send(404, {"error": f"no route {path}"})
        except Exception as e:
            return self._send(500, {"error": repr(e)})


def start_dashboard(port: int = 8265, host: str | None = None) -> int:
    """Start (or return) the dashboard server; returns the bound port."""
    global _server
    if host is None:
        from ray_tpu._private.constants import DASHBOARD_BIND_HOST
        host = DASHBOARD_BIND_HOST
    if _server is not None:
        return _server.server_address[1]
    from ray_tpu._private import worker as _worker
    handler = type("BoundHandler", (_Handler,),
                   {"control": staticmethod(_worker.get_client().control)})
    _server = ThreadingHTTPServer((host, port), handler)
    threading.Thread(target=_server.serve_forever,
                     name="ray_tpu-dashboard", daemon=True).start()
    return _server.server_address[1]


def stop_dashboard() -> None:
    global _server
    if _server is not None:
        _server.shutdown()
        _server.server_close()
        _server = None
