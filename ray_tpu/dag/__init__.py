"""Lazy task/actor DAGs built with `.bind()`.

Counterpart of the reference's `python/ray/dag/` (`dag_node.py` DAGNode,
`function_node.py`, `class_node.py`, `input_node.py`; ~2.5k LoC): binding
builds an expression tree without executing anything; `execute()` walks it,
submitting each function node as a task and instantiating each class node
as an actor, memoizing shared subtrees so diamond dependencies run once.
Used directly by users and as the substrate for `ray_tpu.workflow`
(durable execution) and serve graph composition.
"""

from __future__ import annotations

from typing import Any, Dict, List

from ray_tpu._private.worker import ObjectRef


class DAGNode:
    """Base: an unexecuted node whose args may contain other DAGNodes."""

    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = args
        self._bound_kwargs = kwargs

    # -- traversal ----------------------------------------------------------

    def _children(self) -> List["DAGNode"]:
        out = []

        def scan(v):
            if isinstance(v, DAGNode):
                out.append(v)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    scan(x)
            elif isinstance(v, dict):
                for x in v.values():
                    scan(x)
        for a in self._bound_args:
            scan(a)
        for a in self._bound_kwargs.values():
            scan(a)
        return out

    def _resolve_args(self, memo: Dict[int, Any], input_value):
        def sub(v):
            if isinstance(v, DAGNode):
                return v._execute_memo(memo, input_value)
            if isinstance(v, list):
                return [sub(x) for x in v]
            if isinstance(v, tuple):
                return tuple(sub(x) for x in v)
            if isinstance(v, dict):
                return {k: sub(x) for k, x in v.items()}
            return v
        args = [sub(a) for a in self._bound_args]
        kwargs = {k: sub(v) for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _execute_memo(self, memo: Dict[int, Any], input_value):
        key = id(self)
        if key not in memo:
            memo[key] = self._execute_impl(memo, input_value)
        return memo[key]

    def _execute_impl(self, memo, input_value):
        raise NotImplementedError

    # -- public -------------------------------------------------------------

    def execute(self, *input_value):
        """Run the DAG. Returns the root's result: an ObjectRef for
        function/method roots, an ActorHandle for class roots."""
        inp = None
        if len(input_value) == 1:
            inp = input_value[0]
        elif input_value:
            inp = tuple(input_value)
        return self._execute_memo({}, inp)


class InputNode(DAGNode):
    """Placeholder for the runtime input passed to `execute()`
    (reference: `input_node.py`). Supports `with InputNode() as x:` and
    attribute/index access on the eventual value."""

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _execute_impl(self, memo, input_value):
        return input_value

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return InputAttributeNode(self, name, kind="attr")

    def __getitem__(self, key):
        return InputAttributeNode(self, key, kind="item")


class InputAttributeNode(DAGNode):
    def __init__(self, parent: DAGNode, key, kind: str):
        super().__init__((parent,), {})
        self._key = key
        self._kind = kind

    def _execute_impl(self, memo, input_value):
        base = self._bound_args[0]._execute_memo(memo, input_value)
        return base[self._key] if self._kind == "item" \
            else getattr(base, self._key)


class FunctionNode(DAGNode):
    """`remote_fn.bind(...)` (reference: `function_node.py`)."""

    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._fn = remote_fn

    def _execute_impl(self, memo, input_value) -> ObjectRef:
        args, kwargs = self._resolve_args(memo, input_value)
        return self._fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    """`ActorCls.bind(...)` — instantiated as an actor on execute
    (reference: `class_node.py`)."""

    def __init__(self, actor_cls, args, kwargs):
        super().__init__(args, kwargs)
        self._cls = actor_cls

    def _execute_impl(self, memo, input_value):
        args, kwargs = self._resolve_args(memo, input_value)
        return self._cls.remote(*args, **kwargs)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _UnboundMethod(self, name)


class _UnboundMethod:
    def __init__(self, class_node: ClassNode, method: str):
        self._class_node = class_node
        self._method = method

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method, args, kwargs)


class ClassMethodNode(DAGNode):
    """`class_node.method.bind(...)`; the actor is shared via the memo, so
    several method nodes on one ClassNode hit one actor instance."""

    def __init__(self, class_node: ClassNode, method: str, args, kwargs):
        super().__init__((class_node,) + tuple(args), kwargs)
        self._method = method

    def _execute_impl(self, memo, input_value) -> ObjectRef:
        resolved, kwargs = self._resolve_args(memo, input_value)
        handle, args = resolved[0], resolved[1:]
        return getattr(handle, self._method).remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    """Bundle several leaves as the DAG output (reference:
    `output_node.py`): execute() -> list of results."""

    def __init__(self, outputs: list):
        super().__init__(tuple(outputs), {})

    def _execute_impl(self, memo, input_value):
        return [n._execute_memo(memo, input_value)
                if isinstance(n, DAGNode) else n for n in self._bound_args]


__all__ = [
    "DAGNode", "InputNode", "InputAttributeNode", "FunctionNode",
    "ClassNode", "ClassMethodNode", "MultiOutputNode",
]
