"""Placement groups (reference: `python/ray/util/placement_group.py`).

On one TPU host a placement group is a resource reservation with per-bundle
accounting. The TPU-specific strategies map ICI topology: STRICT_PACK means
"same ICI domain" per SURVEY.md §7.1; multi-host atomicity (the reference's
2PC, placement_group_resource_manager.h:46-99) arrives with the multi-node
control plane.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ray_tpu._private import worker as _worker
from ray_tpu._private.worker import ObjectRef

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


@dataclass
class PlacementGroup:
    id: str
    bundles: list = field(default_factory=list)
    strategy: str = "PACK"
    bandwidth: float = 0.0

    def ready(self) -> ObjectRef:
        """ObjectRef resolving when the reservation is committed. Creation
        is synchronous on a single node, so this resolves immediately."""
        return _worker.put(True)

    @property
    def bundle_specs(self):
        return list(self.bundles)

    def wait(self, timeout_seconds: float | None = None) -> bool:
        return True


def placement_group(bundles, strategy: str = "PACK", name: str = "",
                    bandwidth: float = 0.0) -> PlacementGroup:
    """`bandwidth` declares the gang's interconnect appetite in GB/s
    (all-reduce-heavy training jobs). Tagged gangs participate in the
    head's per-link contention model: their bundles steer away from ICI/
    DCN link groups that other tagged gangs already load (2207.07817).
    0 (default) keeps legacy placement exactly."""
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"invalid strategy {strategy!r}; "
                         f"one of {VALID_STRATEGIES}")
    if bandwidth < 0:
        raise ValueError("bandwidth must be >= 0")
    norm = []
    for b in bundles:
        if not isinstance(b, dict) or not b:
            raise ValueError("each bundle must be a non-empty dict")
        norm.append({k: float(v) for k, v in b.items()})
    pg_id = _worker.get_client().control(
        "create_pg", {"bundles": norm, "strategy": strategy, "name": name,
                      "bandwidth": float(bandwidth)})
    return PlacementGroup(pg_id, norm, strategy, float(bandwidth))


def remove_placement_group(pg: PlacementGroup) -> None:
    _worker.get_client().control("remove_pg", pg.id)


def get_current_placement_group() -> PlacementGroup | None:
    return None
