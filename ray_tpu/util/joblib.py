"""joblib backend running Parallel() jobs as cluster tasks.

Counterpart of the reference's `ray.util.joblib`
(`util/joblib/__init__.py` register_ray + `ray_backend.py` RayBackend on
top of the multiprocessing-pool shim): after `register_ray_tpu()`,
`with joblib.parallel_backend("ray_tpu"):` routes scikit-learn-style
workloads through the scheduler.
"""

from __future__ import annotations

from joblib._parallel_backends import MultiprocessingBackend
from joblib.parallel import register_parallel_backend


class RayTpuBackend(MultiprocessingBackend):
    """joblib backend whose pool is the cluster-task Pool."""

    supports_timeout = True

    def effective_n_jobs(self, n_jobs):
        import ray_tpu
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        ncpu = int(ray_tpu.cluster_resources().get("CPU", 1))
        if n_jobs is None or n_jobs == -1:
            return ncpu
        return min(abs(n_jobs), ncpu) or 1

    def configure(self, n_jobs=1, parallel=None, prefer=None, require=None,
                  **memmapping_kwargs):
        n_jobs = self.effective_n_jobs(n_jobs)
        from ray_tpu.util.multiprocessing import Pool
        self._pool = Pool(processes=n_jobs)
        self.parallel = parallel
        return n_jobs

    def terminate(self):
        if getattr(self, "_pool", None) is not None:
            self._pool.terminate()
            self._pool = None


def register_ray_tpu() -> None:
    """Make `joblib.parallel_backend("ray_tpu")` available."""
    register_parallel_backend("ray_tpu", RayTpuBackend)
