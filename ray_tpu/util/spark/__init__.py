"""ray_tpu-on-Spark: start a ray_tpu cluster on a Spark cluster's
executors.

Counterpart of the reference's `python/ray/util/spark/`
(`setup_ray_cluster`: the head runs on the Spark driver, and a
long-running background Spark job holds one task per worker node, each
task hosting a ray worker node for the cluster's lifetime).

The shim depends only on the tiny RDD protocol it actually uses —
``spark.sparkContext.parallelize(seq, n).foreachPartition(fn)`` — so the
seam is testable without pyspark (tests drive it with a fake
SparkSession whose "executors" are local threads); a real SparkSession
satisfies the same protocol unchanged.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass
from typing import Optional

import ray_tpu

__all__ = ["setup_ray_cluster", "shutdown_ray_cluster", "RayClusterOnSpark"]

_active: Optional["RayClusterOnSpark"] = None


@dataclass
class RayClusterOnSpark:
    address: str
    num_worker_nodes: int
    _stop_event: threading.Event = None
    _job_thread: threading.Thread = None

    def shutdown(self):
        if self._stop_event is not None:
            self._stop_event.set()      # flag file (shared-fs fast path)
        # head teardown is the cluster-visible signal: daemons lose the
        # head channel, exit after their reconnect window, and the Spark
        # tasks holding the executors return
        ray_tpu.shutdown()
        if self._job_thread is not None:
            self._job_thread.join(timeout=120)


def _worker_partition_fn(head_address: str, authkey_hex: str,
                         num_cpus: int, stop_flag_path: str):
    """Runs INSIDE a Spark task on an executor: host one ray_tpu worker
    node (HostDaemon) for the cluster's lifetime. Returned as a closure
    so pyspark can pickle it to the executor."""

    def fn(_iter):
        import uuid
        env = dict(os.environ)
        env["RAY_TPU_AUTHKEY"] = authkey_hex
        # pid alone collides when two partition tasks share an executor
        node_id = f"spark_{os.getpid()}_{uuid.uuid4().hex[:6]}"
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.daemon",
             head_address, node_id,
             json.dumps({"CPU": float(num_cpus)})],
            env=env)
        try:
            # hold the Spark task (and with it the executor slot) until
            # shutdown. Two signals, because executors usually do NOT
            # share a filesystem with the driver: (1) the stop-flag file
            # (fast path when shared_dir IS shared or same-host), and
            # (2) the daemon process EXITING — shutdown_ray_cluster
            # tears the head down, every daemon loses its head channel
            # and exits after its reconnect window, which releases the
            # executor slot on any topology.
            while not os.path.exists(stop_flag_path):
                if proc.poll() is not None:
                    return iter(())
                time.sleep(1.0)
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except Exception:
                proc.kill()
        return iter(())

    return fn


def setup_ray_cluster(spark, *, num_worker_nodes: int,
                      num_cpus_per_node: int = 1,
                      shared_dir: str = "/tmp",
                      wait_timeout_s: float = 120.0) -> str:
    """Start the head in THIS process (the Spark driver) and one worker
    node per Spark task via a background job. Returns the cluster
    address; call shutdown_ray_cluster() (or .shutdown() on the handle)
    to tear down (reference: util/spark/cluster_init.py
    setup_ray_cluster)."""
    global _active
    if _active is not None:
        raise RuntimeError("a ray-on-spark cluster is already active; "
                           "call shutdown_ray_cluster() first")
    client = ray_tpu.init(num_cpus=0)
    node = client.node
    # TCP address when the head listens cross-host (RAY_TPU_HEAD_PORT /
    # TRANSPORT=tcp — real Spark executors are other machines); the UDS
    # session address otherwise (same-host testing)
    address = node.tcp_address or node._address
    authkey_hex = node._authkey.hex()
    stop_flag = os.path.join(
        shared_dir, f"ray_tpu_spark_stop_{os.getpid()}_{int(time.time())}")

    stop_event = threading.Event()
    fn = _worker_partition_fn(address, authkey_hex, num_cpus_per_node,
                              stop_flag)
    job_error: list = []

    def run_job():
        rdd = spark.sparkContext.parallelize(
            range(num_worker_nodes), num_worker_nodes)
        try:
            rdd.foreachPartition(fn)    # blocks until shutdown
        except Exception as e:          # surfaced by the register wait
            job_error.append(e)

    job = threading.Thread(target=run_job, daemon=True,
                           name="ray_tpu-spark-job")
    job.start()

    def stopper():
        stop_event.wait()
        with open(stop_flag, "w") as f:
            f.write("stop")

    threading.Thread(target=stopper, daemon=True).start()

    # wait for every worker node to register
    alive: list = []
    deadline = time.monotonic() + wait_timeout_s
    while time.monotonic() < deadline:
        if job_error:
            stop_event.set()
            ray_tpu.shutdown()
            raise RuntimeError(
                "the background Spark job failed before the worker "
                "nodes registered") from job_error[0]
        alive = [n for n in client.control("list_nodes")
                 if n.get("node_id", "").startswith("spark_")
                 and n.get("alive", n.get("state") != "DEAD")]
        if len(alive) >= num_worker_nodes:
            break
        time.sleep(0.5)
    else:
        stop_event.set()
        ray_tpu.shutdown()
        raise TimeoutError(
            f"only {len(alive)}/{num_worker_nodes} spark worker nodes "
            "registered"
            + (f" (spark job error: {job_error[0]!r})" if job_error
               else ""))

    _active = RayClusterOnSpark(address, num_worker_nodes,
                                _stop_event=stop_event, _job_thread=job)
    return address


def shutdown_ray_cluster() -> None:
    """Reference: util/spark/cluster_init.py shutdown_ray_cluster."""
    global _active
    if _active is None:
        return
    _active.shutdown()
    _active = None
