"""Hot-path flight recorder: one observability plane over the engine,
trainer, and flywheel telemetry islands.

The serving engine, training loop, and RL flywheel each keep rich
private telemetry (`InferenceEngine.stats()`, `MetricsRing`,
`weight_swap_ms`), none of which reached the plane the core ships — the
`util.metrics` Prometheus registry, `util.tracing` spans, the
dashboard's `/metrics` and `/api/timeline`. This module is the bridge,
built from four pieces:

  * `FlightRecorder` — per-request lifecycle tracing for an engine:
    submit → queue wait → each prefill chunk (prefix-hit/COW annotated)
    → decode → first token → finish/cancel/swap-crossing, recorded as
    `util.tracing`-shaped span dicts in a bounded ring (evictions
    counted, never silent). Sampled per request
    (`RAY_TPU_TELEMETRY_SAMPLE`, default 1.0) and cheap enough to leave
    on: the per-token hook is one dict lookup + an int increment, and an
    unsampled request costs a single failed lookup per hook.
    Distills TTFT / TPOT / queue-wait into `util.metrics` histograms.

  * stats-dict metrics bridge — `register_stats_source(name, obj)`
    holds a weakref to anything with a `stats() -> dict` (engines,
    replicas, train loops, flywheels) and a collect hook
    (`metrics.add_collect_hook`) republishes every numeric stat as a
    Gauge — or, for the monotone keys in `COUNTER_KEYS`, a delta-tracked
    Counter that treats a decrease as `reset_stats()` — tagged by
    source, so the dashboard's `/metrics` serves engine / replica /
    paged-cache / spec-decode / flywheel-staleness series to Prometheus
    with no per-step push anywhere on the hot path.

  * `RetraceSentinel` — runtime watcher over compile-once counters
    (`decode_traces`, `verify_traces`, `swap_traces`, the fused train
    dispatch). Pinned paths carry a hard cap from construction; bucket-
    dependent paths (prefill) are baselined by `arm()` after warmup.
    The moment any watched counter exceeds its allowance the sentinel
    increments `retraces_unexpected` and emits ONE WARN per path — the
    property the compile-once tests pin only at test time, enforced in
    production.

  * `chrome_trace_events()` / `summary()` / `check_invariants()` —
    exports: recorder spans + `util.tracing` spans as chrome://tracing
    events (the node's "timeline" verb merges them with task events into
    one view), a JSON health summary for `/api/telemetry`, and the
    self-test the shared test-session fixture runs at teardown.

Everything here is driver/host-side: no device syncs, no jax import at
module load.
"""

from __future__ import annotations

import collections
import itertools
import logging
import os
import random
import re
import threading
import time
import uuid
import weakref

from ray_tpu.util import metrics as _metrics
from ray_tpu.util import tracing as _tracing

logger = logging.getLogger(__name__)

DEFAULT_SAMPLE = float(os.environ.get("RAY_TPU_TELEMETRY_SAMPLE", "1.0"))
DEFAULT_MAX_SPANS = int(os.environ.get("RAY_TPU_TELEMETRY_MAX_SPANS",
                                       "4096"))
# Per-request chunk-span bound: a pathological prompt chunked a thousand
# times must not make one live trace unbounded.
MAX_CHUNKS_PER_REQUEST = 256

_lock = threading.Lock()
_ids: dict[str, itertools.count] = {}
_recorders: "weakref.WeakSet[FlightRecorder]" = weakref.WeakSet()
_sentinels: "weakref.WeakSet[RetraceSentinel]" = weakref.WeakSet()


def next_name(kind: str) -> str:
    """Process-unique instance name per kind: engine0, engine1, train0…
    Used to tag each source's metric series."""
    with _lock:
        counter = _ids.setdefault(kind, itertools.count())
        return f"{kind}{next(counter)}"


def _now_ns() -> int:
    return time.time_ns()


# ---------------------------------------------------------------------------
# latency histograms (module-level, tagged by source)
# ---------------------------------------------------------------------------

_MS_BOUNDARIES = [0.1, 0.5, 1, 5, 10, 50, 100, 500, 1000, 5000]
_metric_cache: dict[tuple[type, str], "_metrics.Metric"] = {}


def _metric(cls, name: str, desc: str = "", boundaries=None,
            tag_keys=("source",)):
    """Lazily create/reuse one tagged metric; returns None when the name
    is already registered as a conflicting type (the scrape must not
    break because two subsystems picked one name)."""
    key = (cls, name)
    with _lock:
        m = _metric_cache.get(key)
        if m is not None:
            return m
        try:
            if cls is _metrics.Histogram:
                m = cls(name, desc, boundaries=boundaries,
                        tag_keys=tag_keys)
            else:
                m = cls(name, desc, tag_keys=tag_keys)
        except (ValueError, TypeError):
            return None
        _metric_cache[key] = m
        return m


# ---------------------------------------------------------------------------
# flight recorder: per-request engine tracing
# ---------------------------------------------------------------------------

class FlightRecorder:
    """Sampled per-request lifecycle tracer for one engine.

    The engine calls the `on_*` hooks from inside its scheduler (under
    its own lock, so no recorder state races); every hook for an
    unsampled request is one dict miss. Spans use the `util.tracing`
    dict shape (epoch-ns timestamps, so they interleave with task events
    on the merged timeline) and land in a bounded ring on finish —
    `dropped_spans` counts ring evictions so truncation is observable.
    """

    def __init__(self, name: str | None = None, *,
                 sample: float | None = None,
                 max_spans: int | None = None):
        self.name = name or next_name("recorder")
        self.sample = (DEFAULT_SAMPLE if sample is None
                       else max(0.0, min(1.0, float(sample))))
        self.max_spans = max(1, int(DEFAULT_MAX_SPANS if max_spans is None
                                    else max_spans))
        self._spans: collections.deque = collections.deque()
        self._live: dict[int, dict] = {}
        self._rng = random.Random(0x5EED ^ hash(self.name))
        self.dropped_spans = 0
        self.requests_seen = 0
        self.requests_traced = 0
        _recorders.add(self)

    # -- engine hooks (hot path) --------------------------------------

    def on_submit(self, rid: int, prompt_len: int) -> None:
        self.requests_seen += 1
        if self.sample <= 0.0 or (self.sample < 1.0
                                  and self._rng.random() >= self.sample):
            return
        now = _now_ns()
        # Join the distributed trace when the submitting context carries
        # one (proxy → replica → engine: the replica's context flows into
        # this caller thread via contextvars), else start a fresh trace.
        parent = _tracing.capture_context()
        if parent is not None:
            trace_id = parent["trace_id"]
            parent_sid = parent["span_id"]
        else:
            trace_id = uuid.uuid4().hex
            parent_sid = None
        root = self._span("engine.request", trace_id, parent_sid, now,
                          {"rid": rid, "engine": self.name,
                           "prompt_len": int(prompt_len)})
        queue = self._span("queue_wait", trace_id, root["span_id"], now,
                           {"rid": rid})
        self._live[rid] = {"root": root, "queue": queue, "extra": [],
                           "first_ns": None, "tokens": 0}
        self.requests_traced += 1

    def on_admit(self, rid: int, prefix_hit_tokens: int,
                 cow: bool) -> None:
        tr = self._live.get(rid)
        if tr is None:
            return
        now = _now_ns()
        tr["queue"]["end_ns"] = now
        tr["root"]["attributes"].update(
            prefix_hit_tokens=int(prefix_hit_tokens), cow=bool(cow))
        h = _metric(_metrics.Histogram, "engine_queue_wait_ms",
                    "submit -> slot admission, ms",
                    boundaries=_MS_BOUNDARIES)
        if h is not None:
            h.observe((now - tr["queue"]["start_ns"]) / 1e6,
                      tags={"source": self.name})

    def on_prefill_chunk(self, rid: int, tokens: int, bucket: int,
                         dur_s: float) -> None:
        tr = self._live.get(rid)
        if tr is None or len(tr["extra"]) >= MAX_CHUNKS_PER_REQUEST:
            return
        end = _now_ns()
        root = tr["root"]
        s = self._span("prefill_chunk", root["trace_id"],
                       root["span_id"], end - int(dur_s * 1e9),
                       {"rid": rid, "tokens": int(tokens),
                        "bucket": int(bucket)})
        s["end_ns"] = end
        tr["extra"].append(s)

    def on_first_token(self, rid: int, wait_s: float) -> None:
        tr = self._live.get(rid)
        if tr is None:
            return
        tr["first_ns"] = _now_ns()
        tr["extra"].append(self._instant(tr, "first_token", rid))
        h = _metric(_metrics.Histogram, "engine_ttft_ms",
                    "submit -> first token, ms",
                    boundaries=_MS_BOUNDARIES)
        if h is not None:
            h.observe(wait_s * 1e3, tags={"source": self.name})

    def on_token(self, rid: int) -> None:
        tr = self._live.get(rid)
        if tr is not None:
            tr["tokens"] += 1

    def on_swap_crossing(self, rid: int) -> None:
        tr = self._live.get(rid)
        if tr is not None:
            tr["extra"].append(self._instant(tr, "swap_crossing", rid))

    def _on_kv_transfer(self, name: str, metric: str, rid: int,
                        blocks: int, nbytes: int, dur_s: float) -> None:
        """Shared body for the disaggregation transfer hooks: one
        `kv_export`/`kv_import` span under the request root plus a
        tagged latency histogram — `kv_transfer_ms` on the merged
        timeline is the pair's union."""
        h = _metric(_metrics.Histogram, metric,
                    "paged KV block transfer (one handoff side), ms",
                    boundaries=_MS_BOUNDARIES)
        if h is not None:
            h.observe(dur_s * 1e3, tags={"source": self.name})
        tr = self._live.get(rid)
        if tr is None or len(tr["extra"]) >= MAX_CHUNKS_PER_REQUEST:
            return
        end = _now_ns()
        root = tr["root"]
        s = self._span(name, root["trace_id"], root["span_id"],
                       end - int(dur_s * 1e9),
                       {"rid": rid, "blocks": int(blocks),
                        "bytes": int(nbytes)})
        s["end_ns"] = end
        tr["extra"].append(s)

    def on_kv_export(self, rid: int, blocks: int, nbytes: int,
                     dur_s: float) -> None:
        """Prefill-role engine gathered `blocks` KV blocks to host for
        a handoff (device->host side of kv_transfer_ms)."""
        self._on_kv_transfer("kv_export", "engine_kv_export_ms", rid,
                             blocks, nbytes, dur_s)

    def on_kv_import(self, rid: int, blocks: int, nbytes: int,
                     dur_s: float) -> None:
        """Decode-role engine scattered a handoff's blocks into its
        pool (host->device side of kv_transfer_ms)."""
        self._on_kv_transfer("kv_import", "engine_kv_import_ms", rid,
                             blocks, nbytes, dur_s)

    def on_handoff(self, rid: int, dur_s: float) -> None:
        """End-to-end prefill->decode handoff latency (export + wire +
        import), recorded by whichever layer drove the transfer — the
        serve DisaggHandle or an engine-level test harness."""
        h = _metric(_metrics.Histogram, "serve_handoff_ms",
                    "prefill->decode handoff, end to end, ms",
                    boundaries=_MS_BOUNDARIES)
        if h is not None:
            h.observe(dur_s * 1e3, tags={"source": self.name})
        tr = self._live.get(rid)
        if tr is not None:
            tr["extra"].append(self._instant(tr, "handoff", rid))

    def on_finish(self, rid: int, outcome: str) -> None:
        tr = self._live.pop(rid, None)
        if tr is None:
            return
        now = _now_ns()
        root, queue = tr["root"], tr["queue"]
        if queue["end_ns"] is None:     # cancelled while still pending
            queue["end_ns"] = now
        root["end_ns"] = now
        root["attributes"]["outcome"] = outcome
        root["attributes"]["tokens"] = tr["tokens"]
        spans = [root, queue] + tr["extra"]
        first = tr["first_ns"]
        if first is not None:
            dec = self._span("decode", root["trace_id"],
                             root["span_id"], first,
                             {"rid": rid, "tokens": tr["tokens"]})
            dec["end_ns"] = now
            spans.append(dec)
            if tr["tokens"] > 1:
                h = _metric(_metrics.Histogram, "engine_tpot_ms",
                            "inter-token latency after first token, ms",
                            boundaries=_MS_BOUNDARIES)
                if h is not None:
                    h.observe((now - first) / 1e6 / (tr["tokens"] - 1),
                              tags={"source": self.name})
        for s in spans:
            if len(self._spans) >= self.max_spans:
                self._spans.popleft()
                self.dropped_spans += 1
            self._spans.append(s)

    # -- internals ----------------------------------------------------

    def _span(self, name, trace_id, parent, start_ns, attrs) -> dict:
        return {"name": name, "trace_id": trace_id,
                "span_id": uuid.uuid4().hex[:16],
                "parent_span_id": parent, "start_ns": start_ns,
                "end_ns": None, "attributes": attrs, "status": "OK",
                "process": os.getpid()}

    def _instant(self, tr, name, rid) -> dict:
        now = _now_ns()
        root = tr["root"]
        s = self._span(name, root["trace_id"], root["span_id"], now,
                       {"rid": rid})
        s["end_ns"] = now
        return s

    # -- export -------------------------------------------------------

    def get_spans(self) -> list[dict]:
        return list(self._spans)

    def drain_spans(self) -> list[dict]:
        """Atomically pop the ring (worker side of cluster-wide span
        collection: drained spans ride the TaskDone / metrics-flush hop
        to the head's tracing ring). Spans are tagged with this
        recorder's category/lane/process so the head's merged chrome
        view keeps the per-request lanes."""
        out = []
        while True:
            try:
                s = self._spans.popleft()
            except IndexError:
                break
            rid = s["attributes"].get("rid", 0)
            s.setdefault("cat", "request")
            s.setdefault("lane", f"{self.name}/r{rid}")
            s.setdefault("proc", _tracing.process_label())
            out.append(s)
        return out

    def live_requests(self) -> int:
        return len(self._live)

    def chrome_events(self) -> list[dict]:
        """Recorder spans as chrome://tracing events, cat="request" so
        they are distinguishable from task events (cat="task") and
        application spans (cat="span") on the merged timeline. Instant
        markers (first_token / swap_crossing) become "i" events."""
        out = []
        for s in self.get_spans():
            rid = s["attributes"].get("rid", 0)
            base = {"name": s["name"], "cat": "request",
                    "pid": s["process"], "tid": f"{self.name}/r{rid}",
                    "args": s["attributes"]}
            end = s["end_ns"] or _now_ns()
            if end == s["start_ns"]:
                out.append({**base, "ph": "i", "ts": s["start_ns"] / 1e3,
                            "s": "t"})
            else:
                out.append({**base, "ph": "X", "ts": s["start_ns"] / 1e3,
                            "dur": (end - s["start_ns"]) / 1e3})
        return out

    def clear(self) -> None:
        self._spans.clear()
        self.dropped_spans = 0

    def check_invariants(self) -> None:
        assert len(self._spans) <= self.max_spans, \
            f"{self.name}: span ring {len(self._spans)} > cap " \
            f"{self.max_spans}"
        assert 0.0 <= self.sample <= 1.0, self.sample
        assert self.requests_traced <= self.requests_seen
        for tr in self._live.values():
            assert len(tr["extra"]) <= MAX_CHUNKS_PER_REQUEST + 8


# ---------------------------------------------------------------------------
# retrace sentinel
# ---------------------------------------------------------------------------

class RetraceSentinel:
    """Runtime watcher over compile-once trace counters.

    Two watch flavors: a `cap` watch is armed from construction with a
    hard allowance (decode must trace exactly once, ever — caps hold for
    any workload, so the existing compile-once suites run fully watched
    and report zero); a dynamic watch (cap=None) has no allowance until
    `arm()` snapshots its current count as the baseline — the shape for
    bucket-dependent paths like chunked prefill, where "warmed up" is
    workload-defined. `check()` is a handful of int compares, cheap
    enough for every scheduler tick; the first violation per path logs
    ONE WARN and every excess trace increments `retraces_unexpected`.
    """

    def __init__(self, name: str | None = None):
        self.name = name or next_name("sentinel")
        self._watches: dict[str, dict] = {}
        self.retraces_unexpected = 0
        self.armed = False
        self.events: collections.deque = collections.deque(maxlen=64)
        _sentinels.add(self)

    def watch(self, path: str, getter, cap: int | None = None,
              *, registered: bool = False) -> None:
        """`registered=True` asserts `path` is in graftlint's
        compile-once inventory (scopes.RETRACE_WATCHES) — the repo's
        jitted hot paths arm their watches through this, so the static
        R003 registry and the runtime sentinel can never drift apart.
        Ad-hoc/test watches keep the default."""
        if registered:
            from ray_tpu.tools.graftlint import scopes as _scopes
            if path not in _scopes.RETRACE_WATCHES:
                raise ValueError(
                    f"sentinel watch {path!r} is not a registered "
                    "compile-once path — add it to COMPILE_ONCE_JITS in "
                    "ray_tpu/tools/graftlint/scopes.py (R003) so lint "
                    "and runtime agree on the inventory")
        self._watches[path] = {
            "getter": getter,
            "cap": None if cap is None else int(cap),
            "limit": None if cap is None else int(cap),
            "counted": 0, "warned": False}

    def arm(self) -> None:
        """Declare warmup over: baseline every dynamic watch at its
        current count, so any further trace on it is unexpected. Cap
        watches are unaffected (they were armed from construction)."""
        self.armed = True
        for w in self._watches.values():
            if w["cap"] is None:
                try:
                    w["limit"] = int(w["getter"]())
                except Exception:
                    continue
                w["counted"] = w["limit"]

    def check(self) -> int:
        """Compare every watched counter against its allowance; count
        and WARN on new excess traces. Returns newly-counted excess."""
        new = 0
        for path, w in self._watches.items():
            limit = w["limit"]
            if limit is None:
                continue
            try:
                cur = int(w["getter"]())
            except Exception:
                continue
            base = max(limit, w["counted"])
            if cur > base:
                delta = cur - base
                w["counted"] = cur
                self.retraces_unexpected += delta
                new += delta
                self.events.append({
                    "ts": time.time(), "sentinel": self.name,
                    "path": path, "traces": cur, "allowed": limit})
                if not w["warned"]:
                    w["warned"] = True
                    logger.warning(
                        "retrace sentinel [%s]: pinned path %r "
                        "re-traced at runtime (traces=%d, allowed=%d) — "
                        "a compile-once guarantee broke; expect a "
                        "latency spike and check for changing input "
                        "shapes/dtypes", self.name, path, cur, limit)
        if new:
            c = _metric(_metrics.Counter, "retraces_unexpected",
                        "traces of pinned compile-once paths beyond "
                        "their allowance")
            if c is not None:
                c.inc(new, tags={"source": self.name})
        return new

    def watching(self) -> bool:
        return any(w["limit"] is not None
                   for w in self._watches.values())

    def reset(self) -> None:
        self.retraces_unexpected = 0
        self.events.clear()
        for w in self._watches.items():
            pass
        for w in self._watches.values():
            w["counted"] = 0
            w["warned"] = False
            if w["cap"] is None:
                w["limit"] = None
        self.armed = False


# ---------------------------------------------------------------------------
# stats-dict -> metrics bridge
# ---------------------------------------------------------------------------

# Monotone-while-not-reset stats keys published as Counters with delta
# tracking (a decrease means reset_stats(); the post-reset count re-adds
# from zero). Everything else numeric is a Gauge.
COUNTER_KEYS = frozenset({
    "decode_steps", "prefill_tokens", "decode_tokens", "prefill_chunks",
    "prefix_hit_tokens", "cow_copies", "evicted_blocks", "cancelled",
    "swaps", "spec_steps", "total", "snapshots", "commits", "stalls",
    "fetches", "iterations",
    # serve-plane fault tolerance (handle/engine/controller stats)
    "retries", "failovers", "sheds", "watchdog_stalls",
    "breaker_trips", "replicas_restarted", "health_check_failures",
    # task-event recorder (stage-attribution observations)
    "stage_samples",
    # priority/preemption plane (engine + per_class sub-dicts)
    "preemptions", "reprefill_blocks", "aging_promotions",
    "submitted", "completed",
    # disaggregated prefill/decode (engine handoff plane + the proxy's
    # SLO admission verdicts)
    "handoffs", "imports", "handoffs_abandoned",
    "kv_blocks_exported", "kv_blocks_imported",
    "kv_export_bytes", "kv_import_bytes",
    "slo_sheds", "slo_queued",
})

_sources: dict[str, tuple] = {}          # name -> (weakref, kind)
# (name, metric) or (name, metric, class_tag) -> last published count
_last_counts: dict[tuple, float] = {}
_hook_installed = False


def register_stats_source(name: str, obj, kind: str = "engine") -> str:
    """Publish `obj.stats()` into the metrics registry at every scrape/
    flush, as `<kind>_<key>` series tagged source=<name>. Holds only a
    weakref — a garbage-collected source silently drops out (its gauges
    keep their last value for the session). Returns the (possibly
    uniquified) registered name."""
    global _hook_installed
    with _lock:
        final = name
        i = 2
        while final in _sources and _sources[final][0]() is not None \
                and _sources[final][0]() is not obj:
            final = f"{name}-{i}"
            i += 1
        _sources[final] = (weakref.ref(obj), kind)
        if not _hook_installed:
            _metrics.add_collect_hook(_collect)
            _hook_installed = True
    # In a worker process the hook only runs when the flusher snapshots;
    # make sure one is running even if no Metric exists here yet.
    _metrics.ensure_flusher()
    return final


def unregister_stats_source(name: str) -> None:
    with _lock:
        _sources.pop(name, None)
        for key in [k for k in _last_counts if k[0] == name]:
            del _last_counts[key]


def _collect() -> None:
    """The metrics collect hook: refresh every live source's series.
    Runs BEFORE the registry lock (metrics.snapshot contract), so it may
    freely create metrics; a broken source never breaks the scrape."""
    with _lock:
        items = list(_sources.items())
    dead = []
    for name, (ref, kind) in items:
        obj = ref()
        if obj is None:
            dead.append(name)
            continue
        try:
            stats = obj.stats()
        except Exception:
            continue
        if isinstance(stats, dict):
            _publish_stats(kind, name, stats)
    for name in dead:
        unregister_stats_source(name)


def _publish_stats(kind: str, name: str, stats: dict) -> None:
    for key, val in stats.items():
        if isinstance(val, bool) or isinstance(val, str):
            continue
        if isinstance(val, dict):
            # One level of nesting fans out as tagged series: a stats key
            # like ``per_class: {"0": {"sheds": 2, ...}, ...}`` becomes
            # `<kind>_<key>_<metric>{source=..., class="0"}` — the
            # fairness/usage-by-class view without N distinct sources.
            for tag, sub in val.items():
                if not isinstance(sub, dict):
                    continue
                for skey, sval in sub.items():
                    if isinstance(sval, (bool, str)):
                        continue
                    try:
                        num = float(sval)
                    except (TypeError, ValueError):
                        continue
                    _publish_one(name, f"{kind}_{key}_{skey}", skey, num,
                                 {"source": name, "class": str(tag)},
                                 (name, f"{kind}_{key}_{skey}", str(tag)))
            continue
        try:
            num = float(val)
        except (TypeError, ValueError):
            continue
        mname = f"{kind}_{key}"
        _publish_one(name, mname, key, num, {"source": name},
                     (name, mname))


def _publish_one(name: str, mname: str, key: str, num: float,
                 tags: dict, ckey: tuple) -> None:
    """Publish one numeric sample: delta-tracked Counter when `key` is in
    COUNTER_KEYS, Gauge otherwise. `ckey` keys the delta state (2-tuple
    for flat stats, 3-tuple with the class tag for nested ones); the
    metric's tag_keys come from `tags` so class-tagged series declare
    both labels."""
    tag_keys = tuple(tags)
    if key in COUNTER_KEYS:
        c = _metric(_metrics.Counter, mname, tag_keys=tag_keys)
        if c is None:
            return
        last = _last_counts.get(ckey, 0.0)
        if num < last:          # stats reset upstream
            last = 0.0
        if num > last:
            c.inc(num - last, tags=tags)
        _last_counts[ckey] = num
    else:
        g = _metric(_metrics.Gauge, mname, tag_keys=tag_keys)
        if g is not None:
            g.set(num, tags=tags)


# ---------------------------------------------------------------------------
# MFU helpers
# ---------------------------------------------------------------------------

# bf16 peak FLOPs per chip by jax device_kind substring (the table
# bench.py established; first match wins).
PEAK_FLOPS = (
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),   # v5 litepod
    ("v5", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def device_peak_flops(device=None) -> float:
    """Peak bf16 FLOPs/s of `device` (default: jax.devices()[0])."""
    if device is None:
        import jax
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, val in PEAK_FLOPS:
        if key in kind:
            return val
    return 197e12


def mfu(flops_per_sec: float, n_devices: int | None = None,
        device=None) -> float:
    """Model FLOPs utilization: achieved model FLOPs/s over the
    devices' aggregate peak."""
    if n_devices is None:
        import jax
        n_devices = len(jax.devices())
    return flops_per_sec / (device_peak_flops(device)
                            * max(1, int(n_devices)))


# ---------------------------------------------------------------------------
# exports / self-test
# ---------------------------------------------------------------------------

def chrome_trace_events() -> list[dict]:
    """This process's recorder spans + application tracing spans as
    chrome://tracing events. The node's "timeline" control verb merges
    these with the task-event trace, so `GET /api/timeline` and
    `ray_tpu timeline` serve one combined view (cat = task | request |
    span)."""
    out = []
    for rec in list(_recorders):
        out.extend(rec.chrome_events())
    out.extend(_tracing.spans_to_chrome_trace())
    return out


def drain_recorder_spans() -> list[dict]:
    """Pop every live recorder's span ring — the worker side of cluster
    span collection (`worker_main._drain_spans_for_push` and the metrics
    flusher call this). Head-resident recorders are never drained: their
    rings are read in place by `chrome_trace_events()`, and draining
    them too would double-count once the head ingests its own ring."""
    out = []
    for rec in list(_recorders):
        out.extend(rec.drain_spans())
    return out


def _tracing_gauges() -> None:
    """Collect hook: surface the tracing ring's drop counter on /metrics
    so a truncated cluster trace is observable at scrape time."""
    g = _metric(_metrics.Gauge, "tracing_dropped_spans",
                "spans evicted from the in-process tracing ring")
    if g is not None:
        g.set(_tracing.dropped_spans(), tags={"source": "tracing"})


_metrics.add_collect_hook(_tracing_gauges)


def summary() -> dict:
    """JSON health summary for `/api/telemetry`."""
    return {
        "recorders": [{
            "name": r.name, "sample": r.sample,
            "requests_seen": r.requests_seen,
            "requests_traced": r.requests_traced,
            "live_requests": r.live_requests(),
            "spans": len(r.get_spans()),
            "dropped_spans": r.dropped_spans,
        } for r in list(_recorders)],
        "sentinels": [{
            "name": s.name, "armed": s.armed,
            "watching": s.watching(),
            "retraces_unexpected": s.retraces_unexpected,
            "events": list(s.events),
        } for s in list(_sentinels)],
        "tracing": {
            "enabled": _tracing.tracing_enabled(),
            "spans": len(_tracing.get_spans()),
            "max_spans": _tracing.max_spans(),
            "dropped_spans": _tracing.dropped_spans(),
        },
        "stats_sources": sorted(_sources.keys()),
    }


_PROM_SAMPLE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(?:\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
    r'(?:,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'
    r' (\S+)$')


def check_invariants() -> None:
    """Telemetry-plane self-test (tests/conftest.py runs it at session
    teardown, mirroring the engine's check_invariants pattern): every
    rendered metric sample parses under the Prometheus exposition
    grammar, the tracing and recorder rings honor their bounds, and
    every sentinel still watches its pinned paths."""
    text = _metrics.render_prometheus(_metrics.snapshot())
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _PROM_SAMPLE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        float(m.group(1))           # value must be a number
    assert len(_tracing.get_spans()) <= _tracing.max_spans(), \
        "tracing span ring exceeded its cap"
    for rec in list(_recorders):
        rec.check_invariants()
    for s in list(_sentinels):
        assert s.watching() or not s._watches, \
            f"sentinel {s.name} has watches but none armed"
