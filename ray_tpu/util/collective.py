"""Collective communication — host-side groups + in-graph ICI mapping.

Counterpart of the reference's `ray.util.collective`
(`util/collective/collective.py`: allreduce :258, reduce :311, broadcast
:373, allgather :423, reducescatter :472, send/recv :531/:594, GroupManager
:40, NCCL backend `collective_group/nccl_collective_group.py:127`).

TPU-native split (SURVEY.md §5.8):

- **Device-data collectives belong in the graph**: `jax.lax.psum` /
  `all_gather` / `ppermute` / `all_to_all` inside a jitted mesh program,
  compiled by XLA onto ICI. Use `ray_tpu.parallel` for those; this module's
  table maps every reference verb to its in-graph equivalent.
- **Host-data collectives** (checkpoint shards, sample batches, rendezvous —
  things NCCL's gloo fallback did) run here over the object store, via a
  rendezvous actor per group. This dogfoods the actor runtime the same way
  the reference's GLOOGroup rides its own store.

SCALE BOUNDARY: every rank's array funnels through the one rendezvous
actor — O(world_size * bytes) through a single process per op. That is
the right shape for control-plane payloads (histograms, metrics,
rendezvous blobs) and the WRONG shape for gradients or activations;
arrays above COLLECTIVE_MAX_BYTES are refused with a pointer to the
in-graph mapping below, so nobody ships model state through this path
by accident.

In-graph mapping (for code inside shard_map/pjit over a Mesh axis ``ax``):

    allreduce(t, op=SUM)   ->  jax.lax.psum(t, ax)        # or pmean
    allgather(t)           ->  jax.lax.all_gather(t, ax)
    reducescatter(t)       ->  jax.lax.psum_scatter(t, ax)
    broadcast(t, src)      ->  implicit (replicated sharding), or
                               jax.lax.all_gather + index
    send/recv ring         ->  jax.lax.ppermute(t, ax, perm)
    alltoall               ->  jax.lax.all_to_all(t, ax, ...)
    barrier()              ->  psum(0) data dependency
"""

from __future__ import annotations

import threading

import numpy as np

import ray_tpu
from ray_tpu.exceptions import RayTpuError

_REDUCE_OPS = {
    "sum": lambda xs: _tree_reduce(np.add, xs),
    "prod": lambda xs: _tree_reduce(np.multiply, xs),
    "max": lambda xs: _tree_reduce(np.maximum, xs),
    "min": lambda xs: _tree_reduce(np.minimum, xs),
    "mean": lambda xs: _tree_reduce(np.add, xs) / len(xs),
}


def _tree_reduce(op, xs):
    acc = np.asarray(xs[0], dtype=np.result_type(xs[0]))
    for x in xs[1:]:
        acc = op(acc, x)
    return acc


class _RendezvousActor:
    """One per collective group; methods run with max_concurrency=world so
    all ranks rendezvous inside (three-phase barrier: deposit, reduce,
    drain)."""

    def __init__(self, world_size: int):
        self.world = world_size
        self.lock = threading.Lock()
        self.slots: dict[int, object] = {}
        self.mailbox: dict[tuple, object] = {}
        self.barrier = threading.Barrier(world_size)
        self.result = None

    def _exchange(self, rank, value, combine):
        with self.lock:
            self.slots[rank] = value
        i = self.barrier.wait()
        if i == 0:
            # Snapshot + clear between the two barriers: no rank can be
            # depositing for the next round until everyone passes the
            # second barrier, and nobody passes the *next* round's first
            # barrier until all have read this round's result.
            ordered = [self.slots[r] for r in sorted(self.slots)]
            self.slots = {}
            self.result = combine(ordered)
        self.barrier.wait()
        return self.result

    def allreduce(self, rank, arr, op):
        return self._exchange(rank, arr, _REDUCE_OPS[op])

    def allgather(self, rank, arr):
        return self._exchange(rank, arr, lambda xs: list(xs))

    def reducescatter(self, rank, arr, op):
        full = self._exchange(rank, arr, _REDUCE_OPS[op])
        chunks = np.array_split(full, self.world)
        return chunks[rank]

    def broadcast(self, rank, arr, src):
        out = self._exchange(rank, arr, lambda xs: xs[src])
        return out

    def barrier_op(self, rank):
        self._exchange(rank, None, lambda xs: None)
        return True

    def put_p2p(self, dst, tag, arr):
        with self.lock:
            self.mailbox[(dst, tag)] = arr
        return True

    def take_p2p(self, dst, tag, timeout=60.0):
        import time
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self.lock:
                if (dst, tag) in self.mailbox:
                    return self.mailbox.pop((dst, tag))
            time.sleep(0.005)
        raise TimeoutError(f"recv timeout (dst={dst}, tag={tag})")


_local = threading.local()



def _guard_size(arr):
    """Refuse model-state-sized payloads: the rendezvous actor is a
    control-plane funnel (O(world * bytes) through one process). Big
    tensors belong in-graph — see the mapping table in the module
    docstring — or in the object store directly."""
    from ray_tpu._private import config as _config
    nbytes = getattr(arr, "nbytes", None)
    if nbytes is None:
        # non-buffer payloads (lists, dicts of arrays): len() counts
        # ELEMENTS, not bytes — measure the actual wire size instead
        # (control-plane payloads are small; one extra pickle is cheap)
        try:
            import cloudpickle
            nbytes = len(cloudpickle.dumps(arr))
        except Exception:
            return arr      # unpicklable: the send itself will say so
    cap = _config.get("COLLECTIVE_MAX_BYTES")
    if nbytes > cap:
        raise RayTpuError(
            f"host-side collective payload is {nbytes} bytes "
            f"(> COLLECTIVE_MAX_BYTES={cap}): this path funnels every "
            "rank through one rendezvous actor and is for control-plane "
            "data only. Move device tensors in-graph (jax.lax.psum/"
            "all_gather over a Mesh axis; ray_tpu.parallel) or ship "
            "them via the object store.")
    return arr


class CollectiveGroup:
    """Client handle bound to (group_name, rank)."""

    def __init__(self, name: str, world_size: int, rank: int):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        actor_name = f"_rtpu_collective:{name}"
        try:
            self._actor = ray_tpu.get_actor(actor_name)
            return
        except ValueError:
            pass
        cls = ray_tpu.remote(_RendezvousActor)
        try:
            cls.options(
                name=actor_name,
                max_concurrency=max(2 * world_size, 4),
            ).remote(world_size)
        except Exception:
            pass
        # Ranks race to create the group actor, and under pipelined
        # submission a lost naming race surfaces as an error object on
        # the creation return — not as a raised exception here. The
        # head's name table is the single authority either way: bind to
        # whichever creation it registered, polling briefly until the
        # winner's (possibly in-flight) registration lands.
        import time as _time
        deadline = _time.monotonic() + 30.0
        while True:
            try:
                self._actor = ray_tpu.get_actor(actor_name)
                return
            except ValueError:
                if _time.monotonic() > deadline:
                    raise
                _time.sleep(0.01)

    def allreduce(self, arr, op: str = "sum"):
        return ray_tpu.get(self._actor.allreduce.remote(
            self.rank, _guard_size(arr), op))

    def allgather(self, arr):
        return ray_tpu.get(self._actor.allgather.remote(
            self.rank, _guard_size(arr)))

    def reducescatter(self, arr, op: str = "sum"):
        return ray_tpu.get(
            self._actor.reducescatter.remote(
                self.rank, _guard_size(arr), op))

    def broadcast(self, arr, src: int = 0):
        return ray_tpu.get(self._actor.broadcast.remote(
            self.rank, _guard_size(arr), src))

    def barrier(self):
        return ray_tpu.get(self._actor.barrier_op.remote(self.rank))

    def send(self, arr, dst: int, tag: int = 0):
        return ray_tpu.get(self._actor.put_p2p.remote(
            dst, tag, _guard_size(arr)))

    def recv(self, src: int, tag: int = 0, timeout: float = 60.0):
        return ray_tpu.get(
            self._actor.take_p2p.remote(self.rank, tag, timeout))


def init_collective_group(world_size: int, rank: int,
                          backend: str = "store",
                          group_name: str = "default") -> CollectiveGroup:
    """Join a named collective group (reference:
    `collective.init_collective_group`). backend="store" is the host-data
    path; device data should use in-graph collectives (module docstring)."""
    if backend not in ("store", "gloo", "nccl"):
        raise ValueError(f"unknown backend {backend!r}")
    g = CollectiveGroup(group_name, world_size, rank)
    if not hasattr(_local, "groups"):
        _local.groups = {}
    _local.groups[group_name] = g
    return g


def _group(group_name: str) -> CollectiveGroup:
    groups = getattr(_local, "groups", {})
    if group_name not in groups:
        raise RayTpuError(
            f"collective group {group_name!r} not initialized in this "
            f"process; call init_collective_group first")
    return groups[group_name]


# Module-level functional API mirroring the reference's call shapes.

def allreduce(arr, group_name: str = "default", op: str = "sum"):
    return _group(group_name).allreduce(arr, op)


def allgather(arr, group_name: str = "default"):
    return _group(group_name).allgather(arr)


def reducescatter(arr, group_name: str = "default", op: str = "sum"):
    return _group(group_name).reducescatter(arr, op)


def broadcast(arr, src_rank: int = 0, group_name: str = "default"):
    return _group(group_name).broadcast(arr, src_rank)


def barrier(group_name: str = "default"):
    return _group(group_name).barrier()


def send(arr, dst_rank: int, group_name: str = "default", tag: int = 0):
    return _group(group_name).send(arr, dst_rank, tag)


def recv(src_rank: int, group_name: str = "default", tag: int = 0,
         timeout: float = 60.0):
    return _group(group_name).recv(src_rank, tag, timeout)
