"""State API: list/summarize live cluster state.

Counterpart of the reference's `ray.experimental.state.api`
(`experimental/state/api.py` list_tasks/list_actors/list_objects/… served
by `dashboard/state_aggregator.py:141` StateAPIManager over GCS + task
events). Here the driver's NodeServer holds all the state, so these are
thin control-channel reads.
"""

from __future__ import annotations

import json

from ray_tpu._private import worker as _worker


def _control(method: str, payload=None):
    return _worker.get_client().control(method, payload)


def list_tasks(filters: dict | None = None, limit: int = 10_000) -> list[dict]:
    """Lifecycle records for recent tasks (state `ray list tasks`)."""
    return _control("list_tasks", {"filters": filters, "limit": limit})


def list_actors(limit: int = 10_000) -> list[dict]:
    return _control("list_actors", {"limit": limit})


def list_objects(limit: int = 10_000) -> list[dict]:
    return _control("list_objects", {"limit": limit})


def list_workers(limit: int = 10_000) -> list[dict]:
    return _control("list_workers", {"limit": limit})


def list_placement_groups(limit: int = 10_000) -> list[dict]:
    return _control("list_placement_groups", {"limit": limit})


def list_nodes() -> list[dict]:
    return _control("list_nodes")


def summarize_tasks() -> dict:
    """Counts by task name and state (`ray summary tasks`)."""
    return _control("summarize_tasks")


def get_metrics() -> list[dict]:
    """Aggregated metrics snapshot across driver + workers."""
    return _control("get_metrics")


def prometheus_metrics() -> str:
    """Prometheus text exposition of the aggregated snapshot."""
    from ray_tpu.util import metrics as _metrics
    return _metrics.render_prometheus(get_metrics())


def timeline(filename: str | None = None, trace: str | None = None):
    """Chrome-trace task timeline (`ray timeline` CLI counterpart). Returns
    the event list; also writes JSON to `filename` when given. `trace`
    narrows the merged view to one distributed trace id."""
    events = _control("timeline", {"trace": trace} if trace else None)
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events


def stage_breakdown() -> dict:
    """Per-stage control-plane latency quantiles
    (submit→queue→dispatch→execute→result_put→got), p50/p99/mean/max ms
    over the recent sample window."""
    return _control("stage_breakdown")
