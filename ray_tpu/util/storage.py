"""URI-keyed pluggable storage for checkpoints, experiment state & spill.

Counterpart of the reference's remote-storage seam
(`air/_internal/remote_storage.py` upload_to_uri/download_from_uri over
pyarrow filesystems, `tune/syncer.py` experiment sync,
`_private/external_storage.py:246` spill targets): one scheme-keyed
registry of backends with copy-only semantics (no shared-filesystem
shortcuts), so the same code path runs against a real object store.

Built-in schemes:
- ``file://`` (and plain paths) — the local filesystem.
- ``mem://`` — a FAKE remote: bytes land under a hidden local root but
  are reachable only through the backend verbs, which is exactly how
  tests exercise the seam across processes (reference: the mock:// fs
  used by Train/Tune storage tests).
- ``gs://`` / ``s3://`` — not bundled (zero-egress image); register one
  with :func:`register_backend` to enable.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
from typing import Callable, Dict

_MEM_ROOT = "/tmp/ray_tpu_memfs"

# Commit marker for atomic-ish remote uploads: data objects are written
# first, this checksummed manifest last. Readers treat its absence as
# "no checkpoint here" — an interrupted upload can never be restored.
COMMIT_FILE = ".ray_tpu_commit.json"


class UncommittedError(RuntimeError):
    """The URI holds no committed upload (nothing there, an interrupted
    upload with no commit marker, or bytes failing the marker's
    checksums)."""


def is_uri(path: str | None) -> bool:
    return bool(path) and "://" in path


def parse(uri: str) -> tuple[str, str]:
    """'scheme://rest' -> (scheme, rest); plain paths -> ('file', path)."""
    if not is_uri(uri):
        return "file", uri
    scheme, _, rest = uri.partition("://")
    return scheme, rest


def uri_join(uri: str, *parts: str) -> str:
    out = uri.rstrip("/")
    for p in parts:
        out += "/" + str(p).strip("/")
    return out


def staging_dir(uri: str) -> str:
    """Deterministic local staging dir for a URI (same URI -> same dir in
    every process on this machine, so a restore finds the paths a
    previous run recorded)."""
    scheme, rest = parse(uri)
    digest = hashlib.sha1(uri.encode()).hexdigest()[:12]
    safe = rest.replace("/", "_")[-40:]
    return os.path.join("/tmp/ray_tpu_staging", f"{scheme}_{safe}_{digest}")


class StorageBackend:
    """Copy-only verbs against one scheme. Paths are the URI's
    scheme-stripped remainder (e.g. ``bucket/exp/ckpt_0``)."""

    def write_bytes(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def read_bytes(self, path: str) -> bytes:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        """Remove a file or an entire prefix (directory)."""
        raise NotImplementedError

    def list_prefix(self, path: str) -> list[str]:
        """All file paths under `path`, relative to it."""
        raise NotImplementedError

    # -- generic directory transfer over the byte verbs -----------------

    def upload_dir(self, local_dir: str, path: str) -> None:
        for root, _dirs, files in os.walk(local_dir):
            for name in files:
                full = os.path.join(root, name)
                rel = os.path.relpath(full, local_dir)
                with open(full, "rb") as f:
                    self.write_bytes(path.rstrip("/") + "/" + rel,
                                     f.read())

    def download_dir(self, path: str, local_dir: str) -> None:
        os.makedirs(local_dir, exist_ok=True)
        for rel in self.list_prefix(path):
            dest = os.path.join(local_dir, rel)
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            with open(dest, "wb") as f:
                f.write(self.read_bytes(path.rstrip("/") + "/" + rel))


class _FSBackend(StorageBackend):
    """Filesystem-rooted backend (local paths, and the mem:// fake which
    roots everything under a hidden directory)."""

    def __init__(self, root: str = ""):
        self.root = root

    def _abs(self, path: str) -> str:
        return os.path.join(self.root, path) if self.root else path

    def write_bytes(self, path: str, data: bytes) -> None:
        full = self._abs(path)
        os.makedirs(os.path.dirname(full), exist_ok=True)
        tmp = full + ".tmp.%d" % os.getpid()
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, full)

    def read_bytes(self, path: str) -> bytes:
        try:
            with open(self._abs(path), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise FileNotFoundError(
                f"no object at {path!r} in {type(self).__name__}") \
                from None

    def exists(self, path: str) -> bool:
        return os.path.exists(self._abs(path))

    def delete(self, path: str) -> None:
        full = self._abs(path)
        if os.path.isdir(full):
            shutil.rmtree(full, ignore_errors=True)
        else:
            try:
                os.unlink(full)
            except FileNotFoundError:
                pass

    def list_prefix(self, path: str) -> list[str]:
        base = self._abs(path)
        out = []
        for root, _dirs, files in os.walk(base):
            for name in files:
                out.append(os.path.relpath(os.path.join(root, name), base))
        return sorted(out)


_lock = threading.Lock()
_backends: Dict[str, StorageBackend] = {}
_factories: Dict[str, Callable[[], StorageBackend]] = {
    "file": lambda: _FSBackend(""),
    "mem": lambda: _FSBackend(_MEM_ROOT),
    "mock": lambda: _FSBackend(_MEM_ROOT),
}


def register_backend(scheme: str,
                     factory: Callable[[], StorageBackend]) -> None:
    """Plug in a real object-store backend, e.g.
    ``register_backend("gs", lambda: MyGCSBackend())``."""
    with _lock:
        _factories[scheme] = factory
        _backends.pop(scheme, None)


def get_backend(uri: str) -> tuple[StorageBackend, str]:
    """Resolve a URI to (backend, scheme-stripped path)."""
    scheme, rest = parse(uri)
    with _lock:
        b = _backends.get(scheme)
        if b is None:
            factory = _factories.get(scheme)
            if factory is None:
                raise ValueError(
                    f"no storage backend for scheme {scheme!r} "
                    f"(register one with ray_tpu.util.storage."
                    f"register_backend)")
            b = _backends[scheme] = factory()
    return b, rest


# -- convenience wrappers ----------------------------------------------------

def upload_dir(local_dir: str, uri: str) -> None:
    b, path = get_backend(uri)
    b.upload_dir(local_dir, path)


def download_dir(uri: str, local_dir: str) -> None:
    b, path = get_backend(uri)
    b.download_dir(path, local_dir)


def upload_dir_committed(local_dir: str, uri: str) -> None:
    """Upload a directory with commit-marker semantics: every data file
    first (checksummed as it streams), then one COMMIT_FILE manifest
    LAST. A writer that dies mid-upload leaves objects but no marker, so
    `download_dir_committed` / `Checkpoint.from_uri` refuse the
    partial upload instead of restoring it."""
    b, root = get_backend(uri)
    entries = []
    for walk_root, _dirs, files in os.walk(local_dir):
        for name in sorted(files):
            full = os.path.join(walk_root, name)
            rel = os.path.relpath(full, local_dir)
            if rel == COMMIT_FILE:
                continue
            with open(full, "rb") as f:
                data = f.read()
            b.write_bytes(root.rstrip("/") + "/" + rel, data)
            entries.append({"path": rel,
                            "sha256": hashlib.sha256(data).hexdigest(),
                            "size": len(data)})
    manifest = json.dumps({"files": sorted(entries,
                                           key=lambda e: e["path"])},
                          sort_keys=True).encode()
    b.write_bytes(root.rstrip("/") + "/" + COMMIT_FILE, manifest)


def download_dir_committed(uri: str, local_dir: str) -> None:
    """Download a committed upload into a CLEAN `local_dir` (wiped
    first, so stale staging files never mask what the backend holds).
    Raises UncommittedError when there is no commit marker, a listed
    object is missing, or bytes fail their recorded checksum."""
    b, root = get_backend(uri)
    try:
        manifest = json.loads(
            b.read_bytes(root.rstrip("/") + "/" + COMMIT_FILE))
    except FileNotFoundError:
        present = b.list_prefix(root)
        detail = ("nothing uploaded" if not present else
                  f"{len(present)} object(s) but no commit marker "
                  f"(interrupted upload?)")
        raise UncommittedError(f"{uri!r}: {detail}") from None
    if os.path.isdir(local_dir):
        shutil.rmtree(local_dir)
    os.makedirs(local_dir, exist_ok=True)
    for entry in manifest["files"]:
        src = root.rstrip("/") + "/" + entry["path"]
        try:
            data = b.read_bytes(src)
        except FileNotFoundError:
            raise UncommittedError(
                f"{uri!r}: committed file {entry['path']!r} is missing "
                f"from the backend") from None
        if hashlib.sha256(data).hexdigest() != entry["sha256"]:
            raise UncommittedError(
                f"{uri!r}: checksum mismatch on {entry['path']!r}")
        dest = os.path.join(local_dir, entry["path"])
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        with open(dest, "wb") as f:
            f.write(data)


def is_committed(uri: str) -> bool:
    b, root = get_backend(uri)
    return b.exists(root.rstrip("/") + "/" + COMMIT_FILE)


def write_bytes(uri: str, data: bytes) -> None:
    b, path = get_backend(uri)
    b.write_bytes(path, data)


def read_bytes(uri: str) -> bytes:
    b, path = get_backend(uri)
    return b.read_bytes(path)


def exists(uri: str) -> bool:
    b, path = get_backend(uri)
    return b.exists(path)


def delete(uri: str) -> None:
    b, path = get_backend(uri)
    b.delete(path)


def list_prefix(uri: str) -> list[str]:
    b, path = get_backend(uri)
    return b.list_prefix(path)


class DirSyncer:
    """Incremental local->URI mirror (reference: tune/syncer.py): each
    sync_up pass uploads only files whose (mtime, size) changed since the
    last pass."""

    def __init__(self, local_dir: str, uri: str):
        self.local_dir = local_dir
        self.uri = uri
        self._seen: dict[str, tuple] = {}

    def sync_up(self) -> int:
        b, path = get_backend(self.uri)
        n = 0
        for root, _dirs, files in os.walk(self.local_dir):
            for name in files:
                if name.endswith(".tmp") or ".tmp." in name:
                    continue
                full = os.path.join(root, name)
                rel = os.path.relpath(full, self.local_dir)
                try:
                    st = os.stat(full)
                except OSError:
                    continue
                sig = (st.st_mtime_ns, st.st_size)
                if self._seen.get(rel) == sig:
                    continue
                with open(full, "rb") as f:
                    b.write_bytes(path.rstrip("/") + "/" + rel, f.read())
                self._seen[rel] = sig
                n += 1
        return n

    def sync_down(self) -> None:
        download_dir(self.uri, self.local_dir)
