"""Dask-on-ray_tpu scheduler shim.

Counterpart of the reference's `python/ray/util/dask/` (`ray_dask_get`:
a dask custom scheduler that executes every task in a dask graph as a
Ray task, with dask keys backed by ObjectRefs so shared subgraphs
compute once and intermediates live in the object store).

The scheduler implements dask's documented graph spec directly
(https://docs.dask.org/en/stable/spec.html): a graph is a dict mapping
keys to computations, where a computation is a literal, another key, or
a task tuple ``(callable, arg1, ...)`` (possibly nested in
lists/tuples). That means it works — and is tested — without dask
installed; with dask present, pass it as the ``scheduler=`` argument:

    import dask
    from ray_tpu.util.dask import ray_dask_get
    dask.compute(obj, scheduler=ray_dask_get)

or enable it globally with ``enable_dask_on_ray()``.
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, List

import ray_tpu

__all__ = ["ray_dask_get", "ray_dask_get_sync", "enable_dask_on_ray",
           "disable_dask_on_ray"]


def _is_task(x) -> bool:
    """Dask spec: a task is a tuple whose first element is callable."""
    return isinstance(x, tuple) and bool(x) and callable(x[0])


def _is_key(x, dsk) -> bool:
    """Dask spec: keys are hashables present in the graph (str/bytes/
    int/float or tuples thereof)."""
    try:
        return isinstance(x, Hashable) and x in dsk
    except TypeError:
        return False


@ray_tpu.remote
def _dask_task(func, /, *args):
    """One dask task as a ray_tpu task. Nested structures containing
    ObjectRefs were materialized by the driver; top-level refs resolve
    through normal arg passing."""
    return func(*args)


def _execute_graph(dsk: Dict, keys) -> Dict:
    """Topologically execute the graph; returns {key: ObjectRef|value}."""
    results: Dict[Any, Any] = {}
    state: Dict[Any, str] = {}

    def resolve(expr, materialize: bool):
        """Rebuild a task argument, substituting computed keys. When a
        substituted value is an ObjectRef nested INSIDE a structure (a
        list of partitions, say), it must be materialized — only
        top-level args pass as refs."""
        if _is_task(expr):
            # dask spec: nested tasks execute inline (they are not keys,
            # so they have no identity to share)
            func, *fargs = expr
            return func(*[resolve(a, True) for a in fargs])
        if _is_key(expr, dsk):
            v = results[expr]
            if materialize and isinstance(v, ray_tpu.ObjectRef):
                return ray_tpu.get(v)
            return v
        if isinstance(expr, list):
            return [resolve(a, True) for a in expr]
        if isinstance(expr, tuple):
            return tuple(resolve(a, True) for a in expr)
        if isinstance(expr, dict):
            return {k: resolve(v, True) for k, v in expr.items()}
        return expr

    def compute(key):
        comp = dsk[key]
        if _is_task(comp):
            func, *fargs = comp
            args = [resolve(a, False) for a in fargs]
            results[key] = _dask_task.remote(func, *args)
        elif _is_key(comp, dsk):
            results[key] = results[comp]
        else:
            results[key] = resolve(comp, False)

    # explicit worklist (not recursion): deep delayed-chains exceed the
    # interpreter recursion limit otherwise. White/gray/black DFS: gray
    # nodes are exactly the current path's ancestors, so a gray dep is a
    # back edge (cycle).
    for root in _flatten_keys(keys, dsk):
        stack = [(root, False)]
        while stack:
            key, post = stack.pop()
            if post:
                compute(key)
                state[key] = "done"
                continue
            if state.get(key) in ("done", "visiting"):
                continue       # duplicate stack entry (shared dep)
            state[key] = "visiting"
            stack.append((key, True))
            for dep in _deps(dsk[key], dsk):
                if state.get(dep) == "visiting":
                    raise ValueError(
                        f"cycle in dask graph at key {dep!r}")
                if state.get(dep) != "done":
                    stack.append((dep, False))
    return results


def _deps(comp, dsk) -> List:
    out = []

    def scan(x):
        if _is_task(x):
            for a in x[1:]:
                scan(a)
        elif _is_key(x, dsk):
            out.append(x)
        elif isinstance(x, (list, tuple)):
            for a in x:
                scan(a)
        elif isinstance(x, dict):
            for a in x.values():
                scan(a)
    scan(comp)
    return out


def _flatten_keys(keys, dsk):
    """Dask keys are often TUPLES (('chunk-xyz', 0) for collections), so
    a tuple only denotes key STRUCTURE when it is not itself a graph
    key; lists always nest (dask spec)."""
    if _is_key(keys, dsk):
        return [keys]
    if isinstance(keys, (list, tuple, set)):
        out = []
        for k in keys:
            out.extend(_flatten_keys(k, dsk))
        return out
    return [keys]


def _repack(keys, results, dsk):
    if not _is_key(keys, dsk) and isinstance(keys, (list, tuple)):
        return type(keys)(_repack(k, results, dsk) for k in keys)
    v = results[keys]
    return ray_tpu.get(v) if isinstance(v, ray_tpu.ObjectRef) else v


def ray_dask_get(dsk: Dict, keys, **kwargs):
    """Dask scheduler entry point (reference: util/dask/scheduler.py
    ray_dask_get): execute `dsk`, return values matching the structure
    of `keys`. Tasks run as ray_tpu tasks; shared keys compute once."""
    results = _execute_graph(dsk, keys)
    return _repack(keys, results, dsk)


def ray_dask_get_sync(dsk: Dict, keys, **kwargs):
    """Local synchronous variant (debugging aid, like the reference's
    ray_dask_get_sync): same semantics, no task submission."""

    def local_resolve(expr, results):
        if _is_task(expr):
            func, *fargs = expr
            return func(*[local_resolve(a, results) for a in fargs])
        if _is_key(expr, dsk):
            return results[expr]
        if isinstance(expr, list):
            return [local_resolve(a, results) for a in expr]
        if isinstance(expr, tuple):
            return tuple(local_resolve(a, results) for a in expr)
        if isinstance(expr, dict):
            return {k: local_resolve(v, results) for k, v in expr.items()}
        return expr

    results: Dict = {}
    state: Dict = {}
    for root in _flatten_keys(keys, dsk):
        stack = [(root, False)]
        while stack:
            key, post = stack.pop()
            if post:
                results[key] = local_resolve(dsk[key], results)
                state[key] = "done"
                continue
            if state.get(key) in ("done", "visiting"):
                continue
            state[key] = "visiting"
            stack.append((key, True))
            for dep in _deps(dsk[key], dsk):
                if state.get(dep) == "visiting":
                    raise ValueError(
                        f"cycle in dask graph at key {dep!r}")
                if state.get(dep) != "done":
                    stack.append((dep, False))
    return _repack(keys, results, dsk)


_saved_scheduler = None


def enable_dask_on_ray() -> None:
    """Make ray_dask_get dask's global default scheduler (requires dask
    installed)."""
    global _saved_scheduler
    import dask
    _saved_scheduler = dask.config.get("scheduler", None)
    dask.config.set(scheduler=ray_dask_get)


def disable_dask_on_ray() -> None:
    import dask
    dask.config.set(scheduler=_saved_scheduler)
