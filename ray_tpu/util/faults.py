"""Deterministic fault injection for the serve plane.

Chaos tests need failures that happen at the *same* place on every run:
"the replica dies on its 21st emitted token", "the 2nd control message
is dropped", "tick 5 stalls for 600ms". This module provides that as a
declarative, picklable `FaultPlan` — a list of specs keyed by *site*
strings that production code consults at its fault points via
`check(site)`:

  * ``engine.tick``   — top of `InferenceEngine.step` (fail/delay;
    a `delay` spec here IS the "tick stall" chaos site — the watchdog
    and per-token latency series see it)
  * ``engine.emit``   — per emitted token (kill = die at step N)
  * ``engine.alloc``  — per admission attempt inside the scheduler
    (fail = simulated allocator exhaustion: the admit is refused as if
    the block pool had no room, driving the preemption path for
    higher-class requests exactly where real block pressure would)
  * ``engine.preempt`` — per scheduler tick (fail = force-preempt the
    lowest-class active stream this tick, real pressure or not)
  * ``replica.health_ping``    — `Replica.check_health`
  * ``controller.health_ping`` — controller health fan-out
  * ``netaddr.send`` / ``netaddr.recv`` — control-channel messages
    (wrapped onto every `netaddr.client()` connection while a plan with
    those sites is active)

Determinism: each site carries a visit counter and, for probabilistic
specs, its own `random.Random` seeded from `(plan.seed, site)` — so a
fixed plan replays the identical fire sequence on every install,
independent of wall clock, thread timing, or other sites. Installed
state is process-global (`install`/`clear`); plans pickle cleanly so a
test can ship one into a replica actor (`Replica.install_faults`) or
the controller (`ServeController.inject_faults`).

When no plan is active `check()` is a single global read — cheap enough
to sit on the engine's per-token path.
"""

from __future__ import annotations

import os
import threading
import time

from ray_tpu.exceptions import RayTpuError

__all__ = [
    "FaultInjected", "FaultPlan", "install", "clear", "active",
    "check", "fired", "maybe_wrap_connection",
]


class FaultInjected(RayTpuError):
    """An injected fault fired (action='fail'). Typed so tests and the
    health plane can tell deliberate chaos from organic failures."""


class _Spec:
    """One declared fault. Fires on visits ``at <= visit < at + times``
    of its site (``times=None`` = forever), or — when ``p`` is set —
    on visits its seeded coin lands heads for."""

    __slots__ = ("site", "action", "at", "times", "p", "delay_s")

    def __init__(self, site: str, action: str, at: int = 0,
                 times: int | None = 1, p: float | None = None,
                 delay_s: float = 0.0):
        if action not in ("fail", "delay", "drop", "kill"):
            raise ValueError(f"unknown fault action {action!r}")
        self.site = site
        self.action = action
        self.at = int(at)
        self.times = times
        self.p = p
        self.delay_s = float(delay_s)

    def matches(self, visit: int, coin) -> bool:
        if self.p is not None:
            # the coin is advanced exactly once per (spec, visit) by the
            # caller; deciding here keeps count-gating composable with it
            return coin < self.p
        if visit < self.at:
            return False
        return self.times is None or visit < self.at + self.times

    def __repr__(self):
        return (f"_Spec({self.site!r}, {self.action!r}, at={self.at}, "
                f"times={self.times}, p={self.p}, "
                f"delay_s={self.delay_s})")


class FaultPlan:
    """A picklable, seeded set of fault specs. Build with the fluent
    helpers (each returns self so plans chain):

        plan = (FaultPlan(seed=7)
                .kill("engine.emit", at=20)
                .delay("netaddr.send", delay_s=0.3, p=0.5))
    """

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self.specs: list[_Spec] = []

    def _add(self, *a, **kw) -> "FaultPlan":
        self.specs.append(_Spec(*a, **kw))
        return self

    def fail(self, site: str, at: int = 0, times: int | None = 1,
             p: float | None = None) -> "FaultPlan":
        """Raise FaultInjected at the site."""
        return self._add(site, "fail", at=at, times=times, p=p)

    def delay(self, site: str, delay_s: float, at: int = 0,
              times: int | None = 1, p: float | None = None
              ) -> "FaultPlan":
        """Sleep delay_s at the site before proceeding."""
        return self._add(site, "delay", at=at, times=times, p=p,
                         delay_s=delay_s)

    def drop(self, site: str, at: int = 0, times: int | None = 1,
             p: float | None = None) -> "FaultPlan":
        """Silently discard the message at the site (netaddr sites;
        elsewhere it reads as a no-op skip)."""
        return self._add(site, "drop", at=at, times=times, p=p)

    def kill(self, site: str, at: int = 0, times: int | None = 1,
             p: float | None = None) -> "FaultPlan":
        """os._exit(1) the whole process at the site — the SIGKILL-shaped
        death mid-stream failover is built to survive."""
        return self._add(site, "kill", at=at, times=times, p=p)

    def sites(self) -> frozenset:
        return frozenset(s.site for s in self.specs)

    def __reduce__(self):
        return (_rebuild_plan, (self.seed, [
            (s.site, s.action, s.at, s.times, s.p, s.delay_s)
            for s in self.specs]))


def _rebuild_plan(seed, rows) -> FaultPlan:
    plan = FaultPlan(seed)
    for site, action, at, times, p, delay_s in rows:
        plan._add(site, action, at=at, times=times, p=p, delay_s=delay_s)
    return plan


class _Active:
    """Runtime state of the installed plan: per-site visit counters and
    seeded coins, plus a log of fired events for test assertions."""

    def __init__(self, plan: FaultPlan):
        import random
        self.plan = plan
        self.visits: dict[str, int] = {}
        self.coins = {
            site: random.Random(f"{plan.seed}:{site}")
            for site in plan.sites()}
        self.log: list[tuple[str, int, str]] = []


_lock = threading.Lock()
_active: _Active | None = None


def install(plan: FaultPlan) -> None:
    """Make `plan` the process's active plan (resetting all counters)."""
    global _active
    with _lock:
        _active = _Active(plan)


def clear() -> None:
    global _active
    with _lock:
        _active = None


def active() -> FaultPlan | None:
    st = _active
    return st.plan if st is not None else None


def fired() -> list[tuple[str, int, str]]:
    """(site, visit, action) tuples of every fault that has fired since
    install — the replay-determinism oracle for tests."""
    st = _active
    if st is None:
        return []
    with _lock:
        return list(st.log)


def check(site: str) -> str | None:
    """Consult the active plan at a fault point. Counts one visit of
    `site`; if a spec fires: 'fail' raises FaultInjected, 'delay' sleeps
    then returns, 'kill' exits the process, 'drop' returns "drop" (the
    caller discards its message). Returns None when nothing fired."""
    st = _active
    if st is None:
        return None
    delay_s = 0.0
    verdict: str | None = None
    with _lock:
        if _active is not st:      # cleared/replaced concurrently
            return None
        visit = st.visits.get(site, 0)
        st.visits[site] = visit + 1
        for spec in st.plan.specs:
            if spec.site != site:
                continue
            coin = (st.coins[site].random() if spec.p is not None
                    else None)
            if not spec.matches(visit, coin):
                continue
            st.log.append((site, visit, spec.action))
            if spec.action == "delay":
                delay_s = max(delay_s, spec.delay_s)
            elif verdict is None:
                verdict = spec.action
    # act OUTSIDE the registry lock: the sleep may be long, and 'fail'
    # must not unwind through it
    if delay_s > 0.0:
        time.sleep(delay_s)
    if verdict == "fail":
        raise FaultInjected(f"injected fault at {site!r}")
    if verdict == "kill":
        os._exit(1)
    return verdict


class _FaultyConnection:
    """Proxy over a `multiprocessing.connection.Connection` consulting
    `<label>.send` / `<label>.recv` per message. Drop on send discards
    the payload; drop on recv reads and discards, then keeps waiting —
    both present to the peer exactly as a lost message does."""

    def __init__(self, conn, label: str):
        self._conn = conn
        self._site_send = label + ".send"
        self._site_recv = label + ".recv"

    def send(self, obj):
        if check(self._site_send) != "drop":
            self._conn.send(obj)

    def send_bytes(self, buf, *a, **kw):
        if check(self._site_send) != "drop":
            self._conn.send_bytes(buf, *a, **kw)

    def recv(self):
        while True:
            obj = self._conn.recv()
            if check(self._site_recv) != "drop":
                return obj

    def recv_bytes(self, *a, **kw):
        while True:
            buf = self._conn.recv_bytes(*a, **kw)
            if check(self._site_recv) != "drop":
                return buf

    def __getattr__(self, name):
        # fileno/poll/close/closed/... delegate untouched
        return getattr(self._conn, name)


def maybe_wrap_connection(conn, label: str):
    """Wrap `conn` when the active plan declares `<label>.*` sites;
    otherwise hand it back untouched (the common, zero-overhead case).
    Wrapping is decided at connection time — install the plan before
    dialing."""
    st = _active
    if st is None:
        return conn
    prefix = label + "."
    if any(site.startswith(prefix) for site in st.plan.sites()):
        return _FaultyConnection(conn, label)
    return conn
