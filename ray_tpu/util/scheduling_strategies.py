"""Scheduling strategies (reference: `python/ray/util/scheduling_strategies.py`)."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class PlacementGroupSchedulingStrategy:
    """Schedule a task/actor inside a placement group reservation."""
    placement_group: object
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclass
class NodeAffinitySchedulingStrategy:
    """Pin to a node (single-node sessions: advisory only for now)."""
    node_id: str
    soft: bool = False
